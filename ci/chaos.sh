#!/bin/sh
# Chaos lane (mirrors ci/real_integrations.sh): runs the fault-injection
# suite standalone — deterministic kill/hang/drop/starve faults against
# np=2/np=4 worker jobs, asserting the no-hang property (coordinated
# errors on all survivors, or a successful elastic recovery) under
# per-test wall-clock bounds.  The integrity-plane cases (wire-CRC
# corruption, truncated frames, kill-mid-ckpt.save, and the elastic
# corruption-recovery bit-identical proof) ride the same lane, as do the
# control-plane survivability cases (lease-expiry epoch advance, and the
# SIGKILL-and-restart of the external journaled rendezvous server that
# must converge bit-identical with zero epoch bumps —
# docs/control_plane.md); suite
# order keeps them AFTER the fast in-process spec tests and np=2/np=4
# abort cases, per the tier-1 budget rule — heavy multiprocess tests run
# late so DOTS_PASSED comparison stays meaningful on the 1-core box.
#
#   sh ci/chaos.sh [extra pytest args...]
#
# Needs only the repo's baseline deps (jax + numpy + pytest); the faults
# are injected via HOROVOD_FAULT_SPEC inside each test, so the lane is
# self-contained.  A hang here is a failed TEST, not a wedged lane: every
# chaos test carries a @pytest.mark.timeout SIGALRM watchdog
# (tests/conftest.py) on top of the harness's own subprocess timeouts.
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

# Sweep stale flight-recorder dumps BEFORE asserting: a crashed or
# aborted earlier run leaves hvd_flight_recorder/ post-mortems in the
# cwd, and any "dump exists / dump absent" assertion in the suite would
# then judge last week's wreckage instead of this run's.
rm -rf hvd_flight_recorder/ hvd_flight_recorder.rank*.json

# No `... | tee` here: plain sh has no pipefail, so a pipeline would
# swallow pytest's exit status and always report PASSED.  The slow-marked
# np=8 reshard proofs are excluded here and run in their own lane below.
rc=0
JAX_PLATFORMS=cpu python -m pytest tests/test_fault_injection.py \
    -m "chaos and not slow" \
    -v -p no:cacheprovider "$@" > ci/chaos.last.log 2>&1 || rc=$?
cat ci/chaos.last.log
[ "$rc" -eq 0 ] || { echo "chaos lane FAILED (rc=$rc)"; exit "$rc"; }

# Large-mesh lane (ISSUE 15): a bounded np=128 simulated cluster —
# the REAL journaled server + elastic driver over a shaped wire
# (horovod_tpu/sim/, docs/sim_cluster.md) — completes churn epochs
# including a coordinated abort, with the lock-dependency tracker armed
# and ZERO inversion cycles across the batched server/store/journal
# lock nests.  Deterministic: fixed HOROVOD_SIM_SEED, tight timeouts.
echo "large-mesh lane: np=128 simulated churn under HOROVOD_LOCK_DEBUG=1"
rc=0
JAX_PLATFORMS=cpu HOROVOD_LOCK_DEBUG=1 HOROVOD_SIM_SEED=0 \
python - > ci/chaos.largemesh.log 2>&1 <<'EOF' || rc=$?
from horovod_tpu.common import lockdep
from horovod_tpu.sim.cluster import COORDINATED_ABORT, SimCluster

rec = SimCluster(128, slots_per_host=8, seed=0, lease_timeout=1.2,
                 renew_period=0.25).run(events=4)
assert rec["final_epoch"] == 4, rec
assert rec["events"][-1]["kind"] == COORDINATED_ABORT, rec
assert rec["attribution"]["coverage"] >= 0.90, rec["attribution"]
cycles = lockdep.find_cycles()
assert not cycles, f"lock inversion cycles: {cycles}"
print(f"np=128 churn: {rec['final_epoch']} epochs, "
      f"abort {rec['coordinated_abort_ms']:.0f}ms, "
      f"coverage {rec['attribution']['coverage']:.2%}, 0 lock cycles")
EOF
cat ci/chaos.largemesh.log
[ "$rc" -eq 0 ] || { echo "large-mesh lane FAILED (rc=$rc)"; exit "$rc"; }

# Negotiation fan-in lane (docs/data_plane.md "Negotiation fan-in"): a
# bounded np=1024 sim — the REAL coordinator mask path behind a scripted
# mesh, star vs tree over the arithmetic wire clock — must show the
# O(ranks)->O(hosts) ingress drop counter-asserted, bit-identical agreed
# masks, and >= 0.90 critical-path coverage, with the lock-dependency
# tracker armed and ZERO inversion cycles.  The np=4096 curve artifact
# regenerates in the slow-marked test below.
echo "negotiation lane: np=1024 sim fan-in under HOROVOD_LOCK_DEBUG=1"
rc=0
JAX_PLATFORMS=cpu HOROVOD_LOCK_DEBUG=1 HOROVOD_SIM_SEED=0 \
python - > ci/chaos.negotiation.log 2>&1 <<'EOF' || rc=$?
from horovod_tpu.common import lockdep
from horovod_tpu.sim.negotiation import SimNegotiation

rec = SimNegotiation(1024, slots_per_host=8, seed=0).run(cycles=4)
assert rec["star"]["ingress_frames_per_cycle"] == 1023, rec
assert rec["fanin"]["ingress_frames_per_cycle"] == 127 + 7, rec
assert rec["star"]["reply_mask"] == rec["fanin"]["reply_mask"] != 0, rec
for mode in ("star", "fanin"):
    assert rec["attribution"][mode]["coverage"] >= 0.90, rec["attribution"]
cycles = lockdep.find_cycles()
assert not cycles, f"lock inversion cycles: {cycles}"
print(f"np=1024 negotiation: ingress {rec['star']['ingress_frames_per_cycle']}"
      f" -> {rec['fanin']['ingress_frames_per_cycle']} frames/cycle, "
      f"cycle speedup {rec['cycle_speedup_p50']}x, "
      f"coverage {rec['attribution']['fanin']['coverage']:.2%}, 0 lock cycles")
EOF
cat ci/chaos.negotiation.log
[ "$rc" -eq 0 ] || { echo "negotiation lane FAILED (rc=$rc)"; exit "$rc"; }

# The np=4096 committed-artifact proof (star-vs-tree latency curves,
# benchmarks/results/sim_negotiation_np4096.json): slow-marked, so
# tier-1 never pays for it; this lane regrows and re-verifies it.
echo "negotiation artifact lane: np=4096 curve regeneration"
rc=0
JAX_PLATFORMS=cpu HOROVOD_LOCK_DEBUG=1 \
python -m pytest "tests/test_sim_cluster.py::test_sim_negotiation_np4096_artifact" \
    -m slow -v -p no:cacheprovider > ci/chaos.negotiation_artifact.log 2>&1 || rc=$?
cat ci/chaos.negotiation_artifact.log
[ "$rc" -eq 0 ] || { echo "negotiation artifact lane FAILED (rc=$rc)"; exit "$rc"; }

# Self-healing demotion lane (docs/elastic.md "Self-healing demotion").
# The live np=3 chronic-straggler scenario (host shed, cause=demotion,
# bit-identical convergence, HOROVOD_LOCK_DEBUG=1 below) already ran in
# the pytest chaos lane above via the module's chaos mark; this lane adds
# the np=128 scale proof — the artifact-generating slow test drives 3
# demotion reports through the real driver over the shaped wire, regrows
# benchmarks/results/sim_demotion_np128.json, and asserts the committed
# artifact's digest reproduces from a fresh same-seed cluster (the
# non-fabrication witness), with zero lock-inversion cycles.
echo "demotion lane: np=128 simulated demotions under HOROVOD_LOCK_DEBUG=1"
rc=0
JAX_PLATFORMS=cpu HOROVOD_LOCK_DEBUG=1 \
python -m pytest "tests/test_sim_cluster.py::test_sim_demotion_np128_artifact" \
    -m slow -v -p no:cacheprovider > ci/chaos.demotion.log 2>&1 || rc=$?
cat ci/chaos.demotion.log
[ "$rc" -eq 0 ] || { echo "demotion lane FAILED (rc=$rc)"; exit "$rc"; }

# Zero-restart reshard lane (docs/elastic.md "Live resharding"): the
# np=8 live proof — a rank SIGKILL'd mid-train, the reshard-marked
# publish, the survivor-acked commit, exactly one post-churn spawn (the
# victim's identity back as a joiner), bit-identical convergence — plus
# the HOROVOD_RESHARD=0 kill-switch variant converging through the
# legacy fallback.  Both under HOROVOD_LOCK_DEBUG=1 (the jobs arm it in
# their own env too; this instruments the test process as well).
echo "reshard lane: np=8 live churn under HOROVOD_LOCK_DEBUG=1"
rc=0
JAX_PLATFORMS=cpu HOROVOD_LOCK_DEBUG=1 \
python -m pytest tests/test_fault_injection.py -m "chaos and slow" \
    -k "live_reshard" -v -p no:cacheprovider \
    > ci/chaos.reshard.log 2>&1 || rc=$?
cat ci/chaos.reshard.log
[ "$rc" -eq 0 ] || { echo "reshard lane FAILED (rc=$rc)"; exit "$rc"; }
echo "chaos lane PASSED"
