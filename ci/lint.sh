#!/bin/sh
# Lint lane (mirrors ci/chaos.sh): the hvd-lint static pass over the
# package, the hvd-mck exhaustive model-checks of the shm ring protocol
# and of the elastic epoch protocol (crash/reorder, `hvd-mck proto`),
# plus their test suites (per-rule fixtures, the zero-violation tree
# contract, the mutation-kill suites, and the lockdep unit tests).  Fast
# — run it FIRST: a reopened invariant (blocking call under a lock,
# typo'd fault site, reordered doorbell publish) fails here in seconds
# instead of wedging a multiprocess job in the chaos lane.
#
#   sh ci/lint.sh [extra pytest args...]
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

# Sweep stale flight-recorder dumps BEFORE asserting, the way the
# chaos/bench lanes already do: a crashed earlier run leaves
# hvd_flight_recorder/ post-mortems in the cwd, and any dump-presence
# assertion in the suites below would judge last week's wreckage
# instead of this run's.
rm -rf hvd_flight_recorder/ hvd_flight_recorder.rank*.json

rc=0
{
    python -m horovod_tpu.tools.lint horovod_tpu/ &&
    # The deployment claim: every scenario, fully explored, zero
    # violations — truncation exits 2 and fails the lane (an incomplete
    # exploration must never pass as exhaustive).  The JSON report is
    # the lane's machine-readable artifact.
    python -m horovod_tpu.tools.mck --mode tso --smoke -q \
        --json ci/mck.last.report.json &&
    # The counterfactual: under store-store reordering the checker MUST
    # find the missed wakeup (exit 1, specifically — not a crash).  A
    # weak run that passes means the checker went blind; fail the lane.
    { weak_rc=0; python -m horovod_tpu.tools.mck --mode weak -q \
          > /dev/null 2>&1 || weak_rc=$?
      if [ "$weak_rc" -eq 1 ]; then
          echo "hvd-mck: weak-memory run finds the missed wakeup (expected)"
      else
          echo "hvd-mck: weak-memory run exited $weak_rc, expected 1" \
               "(violations found) — the checker can no longer detect" \
               "the bug class it exists for"
          false
      fi; } &&
    # The checker's checker: every seeded protocol bug killed by name.
    python -m horovod_tpu.tools.mck --mutants -q &&
    # The elastic epoch protocol under the same engine: every scenario
    # COMPLETE and clean — TRUNCATED exits 2 and fails the lane; an
    # incomplete exploration must never pass as proof.  The JSON report
    # is this lane's second machine-readable artifact.
    python -m horovod_tpu.tools.mck proto --smoke -q \
        --json ci/mck.proto.report.json &&
    # The proto teeth guard (the weak-mode idiom, for this protocol): a
    # seeded bug run as a plain check MUST exit 1 — violations found,
    # specifically — not 0 (checker gone blind) and not a crash.
    { inject_rc=0; python -m horovod_tpu.tools.mck proto \
          --inject apply_before_journal -q > /dev/null 2>&1 \
          || inject_rc=$?
      if [ "$inject_rc" -eq 1 ]; then
          echo "hvd-mck proto: injected WAL inversion is found (expected)"
      else
          echo "hvd-mck proto: injected run exited $inject_rc, expected" \
               "1 (violations found) — the checker can no longer detect" \
               "the bug class it exists for"
          false
      fi; } &&
    # Second teeth guard, for the reshard invariants specifically: the
    # unguarded-commit bug (survivor acks forged at the probe) MUST be
    # found by the store-side early-commit check — exit 1, not 0.
    { inject_rc=0; python -m horovod_tpu.tools.mck proto \
          --inject reshard_commit_unguarded -q > /dev/null 2>&1 \
          || inject_rc=$?
      if [ "$inject_rc" -eq 1 ]; then
          echo "hvd-mck proto: injected unguarded reshard commit is" \
               "found (expected)"
      else
          echo "hvd-mck proto: injected reshard run exited $inject_rc," \
               "expected 1 (violations found) — the reshard early-commit" \
               "invariant has gone blind"
          false
      fi; } &&
    # And the full proto kill suite: every seeded protocol bug dead.
    python -m horovod_tpu.tools.mck proto --mutants -q &&
    JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py tests/test_mck.py \
        tests/test_mck_proto.py tests/test_lockdep.py -q \
        -p no:cacheprovider "$@"
} > ci/lint.last.log 2>&1 || rc=$?
cat ci/lint.last.log
[ "$rc" -eq 0 ] || { echo "lint lane FAILED (rc=$rc)"; exit "$rc"; }
echo "lint lane PASSED"

# Strict live-scrape validation rides the lint lane (same "fail in
# seconds, not in the chaos lane" rationale): one np=2 smoke job, its
# GET /metrics output checked line by line against the catalog.
sh ci/metrics_smoke.sh
