#!/bin/sh
# Lint lane (mirrors ci/chaos.sh): the hvd-lint static pass over the
# package plus its own test suite (per-rule fixtures, the zero-violation
# tree contract, and the lockdep unit tests).  Fast — run it FIRST: a
# reopened invariant (blocking call under a lock, typo'd fault site,
# swallowed thread exception) fails here in seconds instead of wedging a
# multiprocess job in the chaos lane.
#
#   sh ci/lint.sh [extra pytest args...]
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

rc=0
{
    python -m horovod_tpu.tools.lint horovod_tpu/ &&
    JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py tests/test_lockdep.py \
        -q -p no:cacheprovider "$@"
} > ci/lint.last.log 2>&1 || rc=$?
cat ci/lint.last.log
[ "$rc" -eq 0 ] || { echo "lint lane FAILED (rc=$rc)"; exit "$rc"; }
echo "lint lane PASSED"

# Strict live-scrape validation rides the lint lane (same "fail in
# seconds, not in the chaos lane" rationale): one np=2 smoke job, its
# GET /metrics output checked line by line against the catalog.
sh ci/metrics_smoke.sh
