#!/bin/sh
# Metrics smoke lane (docs/observability.md): boots a real np=2 job,
# scrapes the rendezvous server's live GET /metrics from inside it, and
# STRICTLY validates the Prometheus text (tools/prom_validate.py): every
# line parses, HELP/TYPE precede samples, histogram buckets are
# cumulative with a +Inf == _count, every scraped family is a CATALOG
# entry of the right kind, and the families a clean run must always
# serve are present.  Catches a renderer regression or an uncataloged
# series in seconds, before the chaos lane would trip over it.
#
#   sh ci/metrics_smoke.sh
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

# Sweep stale flight-recorder dumps BEFORE running, the way the chaos
# and lint lanes already do: an earlier crashed run leaves
# hvd_flight_recorder/ post-mortems in the cwd, and anything judging
# dump presence downstream would read last week's wreckage.
rm -rf hvd_flight_recorder/ hvd_flight_recorder.rank*.json

rc=0
{
    JAX_PLATFORMS=cpu python - <<'EOF' > ci/metrics_smoke.last.scrape &&
import sys


def _worker():
    import os
    import time
    import urllib.request

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    # Named tensors hit the negotiation table path; repeats hit the mask
    # fast path — both planes contribute series to the scrape.
    for i in range(6):
        hvd.allreduce(np.ones(2048, np.float32), name=f"smoke{i % 2}")
    hvd.barrier()
    time.sleep(1.2)  # let both ranks' push loops ship a snapshot
    hvd.barrier()
    text = ""
    if hvd.rank() == 0:
        addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
        port = os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]
        deadline = time.time() + 30
        while time.time() < deadline:
            text = urllib.request.urlopen(
                f"http://{addr}:{port}/metrics", timeout=5).read().decode()
            if 'rank="1"' in text:
                break
            time.sleep(0.3)
    hvd.shutdown()
    return text


import horovod_tpu.runner as runner

outs = runner.run(_worker, np=2, timeout=150,
                  use_env={"JAX_PLATFORMS": "cpu",
                           "HOROVOD_METRICS_PUSH_SECS": "0.2"})
if 'rank="1"' not in outs[0]:
    print("metrics-smoke: scrape never showed rank 1's snapshot",
          file=sys.stderr)
    sys.exit(1)
sys.stdout.write(outs[0])
EOF
    python -m horovod_tpu.tools.prom_validate ci/metrics_smoke.last.scrape \
        --required controller_cycles_total controller_cycle_seconds \
        collective_latency_seconds tensor_queue_depth phase_seconds_total \
        wire_bytes_on_wire_total rendezvous_store_ops_total \
        rendezvous_request_seconds rendezvous_requests_in_flight \
        rendezvous_scope_ops_total rendezvous_store_lock_wait_seconds
} > ci/metrics_smoke.last.log 2>&1 || rc=$?
cat ci/metrics_smoke.last.log
[ "$rc" -eq 0 ] || { echo "metrics smoke FAILED (rc=$rc)"; exit "$rc"; }
echo "metrics smoke PASSED"
