#!/bin/sh
# Bench gate (docs/observability.md, A/B harness): proves the
# same-session A/B verdict machinery end to end on this box.
#
#   1. A/A null check — identical control and candidate must come back
#      "no significant difference" (the sign test's false-positive rate
#      at the defaults is ~3%, so one unlucky unanimous sweep is retried
#      once before failing the lane);
#   2. injected slowdown — a delay_ms fault on rank 1's collective
#      submission (the enqueue.collective site, docs/fault_injection.md)
#      must come back "regression";
#   3. shm transport win — HOROVOD_TRANSPORT=auto (shm intra-host data
#      plane, docs/data_plane.md "Transports") vs forced tcp on the same
#      intra-host 4 MiB np=2 step must come back "improvement";
#   4. int8 wire compression — must not REGRESS the loopback step
#      ("improvement" or "no significant difference"; the 4x byte cut is
#      counter-asserted in tests/test_wire_compression.py — the
#      wall-clock win belongs to wire-bound topologies, not loopback).
#
# Artifacts land in benchmarks/results/ab_aa_gate.json,
# benchmarks/results/ab_rank1_delay_gate.json,
# benchmarks/results/ab_shm_gate.json and
# benchmarks/results/ab_wire_int8_gate.json.
#
#   sh ci/bench_gate.sh
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

NBYTES="${BENCH_GATE_NBYTES:-4194304}"
ROUNDS="${BENCH_GATE_ROUNDS:-10}"
# 5 ms on every rank-1 submission inflates the ~tens-of-ms 4 MiB np=2
# step deterministically (~20%) — every pair votes "slower".
DELAY_SPEC="enqueue.collective:rank=1:action=delay_ms,5"

check_verdict() {
    # check_verdict FILE EXPECTED -- EXPECTED may be "a|b" when either
    # verdict passes the gate (the int8 case: loopback has no wire to
    # win back, so "improvement" and "no significant difference" both
    # clear it; "regression" never does)
    python - "$1" "$2" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
got, want = rec["verdict"], sys.argv[2].split("|")
print(f"bench-gate: {rec['label']}: verdict={got!r} "
      f"(control={rec['median_control_ms']}ms "
      f"candidate={rec['median_candidate_ms']}ms p={rec['p_value']})")
sys.exit(0 if got in want else 1)
EOF
}

run_case() {
    # run_case LABEL EXPECTED OUT [candidate K=V...]
    label="$1"; expected="$2"; out="$3"; shift 3
    attempt=1
    while :; do
        JAX_PLATFORMS=cpu python benchmarks/ab_harness.py \
            --label "$label" --nbytes "$NBYTES" --rounds "$ROUNDS" \
            --out "$out" "$@" > /dev/null
        if check_verdict "$out" "$expected"; then
            return 0
        fi
        [ "$attempt" -ge 2 ] && {
            echo "bench-gate: $label FAILED (wanted $expected twice)"
            return 1
        }
        echo "bench-gate: $label verdict mismatch, retrying once"
        attempt=$((attempt + 1))
    done
}

mkdir -p benchmarks/results
# Sweep stale flight-recorder dumps before the verdict runs: an earlier
# wedged job's hvd_flight_recorder/ post-mortems in the cwd would make
# any dump-presence check (and a human reading the artifacts dir) blame
# this run for last week's failure.
rm -rf hvd_flight_recorder/ hvd_flight_recorder.rank*.json
rc=0
run_case aa-null "no significant difference" \
    benchmarks/results/ab_aa_gate.json || rc=$?
run_case rank1-delay regression \
    benchmarks/results/ab_rank1_delay_gate.json \
    --candidate "HOROVOD_FAULT_SPEC=$DELAY_SPEC" || rc=$?
run_case shm-transport improvement \
    benchmarks/results/ab_shm_gate.json \
    --control "HOROVOD_TRANSPORT=tcp" \
    --candidate "HOROVOD_TRANSPORT=auto" || rc=$?
# The int8 case runs at 64 KiB, not the 4 MiB default: this box has ONE
# core, so at large payloads both ranks' quantization passes timeshare
# it and the gate would measure compute contention, not the wire.  At a
# dispatch-bound size the codec must simply not hurt (the trailing
# --nbytes overrides run_case's default; argparse keeps the last value).
run_case wire-int8 "improvement|no significant difference" \
    benchmarks/results/ab_wire_int8_gate.json \
    --candidate "HOROVOD_WIRE_COMPRESSION=int8" \
    --nbytes "${BENCH_GATE_WIRE_NBYTES:-65536}" || rc=$?
[ "$rc" -eq 0 ] || { echo "bench gate FAILED (rc=$rc)"; exit "$rc"; }
echo "bench gate PASSED"
