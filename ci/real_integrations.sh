#!/bin/sh
# Real-library integration lane (VERDICT r2 #8): verifies the ray / spark /
# mxnet bindings against the GENUINE libraries instead of tests/fake_*.
#
# The default CI image ships none of the three (and the build environment
# forbids installs), so this lane runs wherever a network + venv exist:
#
#   sh ci/real_integrations.sh [/path/to/venv]
#
# It creates (or reuses) a venv, installs the pinned versions from
# ci/requirements-integrations.txt, and runs the real-API test module plus
# the fake-backed suites (which must ALSO pass with the real libs
# importable — guarding against fakes that shadow real behavior).
set -eu
VENV="${1:-.venv-integrations}"
ROOT="$(cd "$(dirname "$0")/.." && pwd)"

# Prefer a python with an mxnet wheel (none exist for >= 3.12): the mxnet
# smoke + engine-ordering tests only run when the venv python can install
# it.  Override with HVD_CI_PYTHON.
PY="${HVD_CI_PYTHON:-}"
if [ -z "$PY" ]; then
    for cand in python3.11 python3.10 python3; do
        if command -v "$cand" >/dev/null 2>&1 \
           && "$cand" -m venv --help >/dev/null 2>&1; then
            PY="$cand"
            break
        fi
    done
fi
echo "real-integrations venv python: $PY"

"$PY" -m venv "$VENV"
. "$VENV/bin/activate"
pip install -q -U pip
pip install -q -r "$ROOT/ci/requirements-integrations.txt"
pip install -q "mxnet==1.9.1" \
    || echo "mxnet wheel unavailable for $PY; mxnet tests will skip"
pip install -q -e "$ROOT" pytest

python - <<'PY'
import ray, pyspark
print("verified versions:", "ray", ray.__version__, "| pyspark", pyspark.__version__)
try:
    import mxnet
    print("mxnet", mxnet.__version__)
except ImportError:
    print("mxnet unavailable on this platform (py>=3.12 has no wheel); "
          "its smoke will skip")
PY

cd "$ROOT"
python -m pytest tests/test_real_integrations.py tests/test_ray.py \
    tests/test_spark.py -v 2>&1 | tee ci/real_integrations.last.log
echo "real-integration lane PASSED"
