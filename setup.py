"""Build hook for the native kernel library (horovod_tpu/_native).

Reference analog: setup.py delegating the native build to CMake
(reference setup.py:56-190).  Ours is one g++ invocation; metadata lives
in pyproject.toml.  Source checkouts don't need this — the loader in
horovod_tpu/_native/__init__.py compiles on first use — but installed
wheels should ship the prebuilt .so.
"""

import hashlib
import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


class BinaryDistribution(Distribution):
    """The wheel ships a compiled .so: force a platform tag so pip never
    installs an x86-64 build onto a foreign architecture."""

    def has_ext_modules(self):
        return True


class build_py_with_native(build_py):
    def run(self):
        super().run()
        src = os.path.join(self.build_lib, "horovod_tpu", "_native",
                           "native.cc")
        if not os.path.exists(src):
            return
        # Must match _native/__init__.py's hash-keyed artifact name so the
        # loader accepts the wheel-built .so without a rebuild.
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:12]
        out = os.path.join(self.build_lib, "horovod_tpu", "_native",
                           f"libhvdnative-{digest}.so")
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 src, "-o", out],
                check=True, timeout=300)
        except (OSError, subprocess.SubprocessError) as e:
            # The package works without it (numpy fallbacks); don't
            # fail installation on compiler-less hosts.
            print(f"warning: native kernel build skipped: {e}")


setup(cmdclass={"build_py": build_py_with_native},
      distclass=BinaryDistribution)
