"""Chaos suite: deterministic fault injection against the failure plane.

Every subprocess test here asserts the NO-HANG property: with a fault spec
killing, hanging, or starving a rank, all surviving ranks either raise a
coordinated ``HorovodInternalError`` or complete an elastic recovery —
within a hard wall-clock bound (the ``timeout`` marker's SIGALRM watchdog
in conftest).  ``ci/chaos.sh`` runs this lane standalone.

Spec grammar and site list: ``docs/fault_injection.md`` /
``horovod_tpu/common/faults.py``.
"""

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.common import faults
from horovod_tpu.common.exceptions import FaultInjectedError

from .helpers import (
    REPO_ROOT,
    release_reservations,
    reserve_port,
    run_distributed,
)

pytestmark = pytest.mark.chaos

# Chaos workers run with a short recv progress deadline so hang-flavored
# faults convert to PeerGoneError within seconds, not the 600 s production
# default.  Transport pinned to tcp: these scenarios inject on the
# tcp.* sites, which the auto policy would route around on a single host
# (the shm twins live in test_shm_transport.py).
_FAST_DEADLINE = {"HOROVOD_TCP_PROGRESS_DEADLINE_SECS": "3",
                  "HOROVOD_TRANSPORT": "tcp"}


@pytest.fixture(autouse=True)
def _clean_faults():
    """Injection state must never leak between tests (or into the suite)."""
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# the injection registry itself (in-process)
# ---------------------------------------------------------------------------


class TestFaultSpec:
    def test_inactive_by_default(self):
        assert not faults.ACTIVE
        assert faults.inject("tcp.send", rank=0) is False

    def test_grammar_errors_are_loud(self):
        for bad in ["nosuch.site:action=raise",
                    "tcp.send:action=explode",
                    "tcp.send:frobnicate",
                    "tcp.send:nth=0:action=raise",
                    "tcp.send:nth=1:after=2:action=raise",
                    # payload actions are send-only: anywhere else they
                    # would silently inject nothing
                    "tcp.recv:action=drop",
                    "dispatch.collective:action=drop",
                    "tcp.recv:action=corrupt",
                    "rendezvous.get:action=truncate,3",
                    "ckpt.save:action=corrupt,2"]:
            with pytest.raises(ValueError):
                faults.configure(bad)

    def test_rank_and_peer_filters(self):
        faults.configure("tcp.send:rank=1:peer=2:action=drop")
        assert faults.inject("tcp.send", rank=0, peer=2) is False
        assert faults.inject("tcp.send", rank=1, peer=0) is False
        assert faults.inject("tcp.recv", rank=1, peer=2) is False
        assert faults.inject("tcp.send", rank=1, peer=2) is True

    def test_nth_fires_exactly_once_deterministically(self):
        for _ in range(2):  # same spec → same firing call, run after run
            faults.configure("tcp.send:nth=3:action=drop")
            fired = [faults.inject("tcp.send", rank=0) for _ in range(6)]
            assert fired == [False, False, True, False, False, False]

    def test_after_fires_on_every_later_call(self):
        faults.configure("tcp.send:after=2:action=drop")
        fired = [faults.inject("tcp.send", rank=0) for _ in range(5)]
        assert fired == [False, False, True, True, True]

    def test_counters_are_per_clause(self):
        faults.configure(
            "tcp.send:rank=0:nth=1:action=drop;tcp.send:rank=1:nth=2:action=drop")
        assert faults.inject("tcp.send", rank=0) is True
        assert faults.inject("tcp.send", rank=1) is False  # its own call #1
        assert faults.inject("tcp.send", rank=1) is True

    def test_raise_action(self):
        faults.configure("controller.negotiate:action=raise")
        with pytest.raises(FaultInjectedError, match="controller.negotiate"):
            faults.inject("controller.negotiate", rank=0)

    def test_raise_oserror_action(self):
        faults.configure("rendezvous.get:action=raise_oserror")
        with pytest.raises(OSError, match="injected connection reset"):
            faults.inject("rendezvous.get")

    def test_delay_action(self):
        faults.configure("dispatch.collective:action=delay_ms,150")
        t0 = time.monotonic()
        assert faults.inject("dispatch.collective", rank=0) is False
        assert time.monotonic() - t0 >= 0.14

    def test_hang_action_blocks(self):
        faults.configure("tcp.recv:action=hang")
        done = threading.Event()

        def call():
            faults.inject("tcp.recv", rank=0)
            done.set()  # unreachable

        threading.Thread(target=call, daemon=True).start()
        assert not done.wait(0.3), "hang action returned"

    def test_env_spec_parsed_in_fresh_process(self):
        """Workers self-configure from HOROVOD_FAULT_SPEC at import."""
        out = subprocess.run(
            [sys.executable, "-c",
             "from horovod_tpu.common import faults; print(faults.ACTIVE)"],
            env={**os.environ,
                 "HOROVOD_FAULT_SPEC": "tcp.send:nth=1:action=drop"},
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert out.stdout.strip() == "True", (out.stdout, out.stderr)

    def test_inject_deferred_returns_delay_without_sleeping(self):
        """The tally site's deferral contract: a delay_ms clause hands the
        delay back (in seconds) instead of sleeping, so the coordinator
        can park the tally rather than stall its whole lockstep cycle."""
        faults.configure("controller.tally:rank=1:action=delay_ms,150")
        t0 = time.monotonic()
        delay = faults.inject_deferred("controller.tally", rank=1)
        assert time.monotonic() - t0 < 0.1, "inject_deferred slept"
        assert delay == pytest.approx(0.150)

    def test_inject_deferred_rank_filter(self):
        faults.configure("controller.tally:rank=1:action=delay_ms,150")
        assert faults.inject_deferred("controller.tally", rank=0) == 0.0
        assert faults.inject_deferred("controller.tally", rank=2) == 0.0

    def test_inject_deferred_non_delay_actions_still_run(self):
        """Only delay_ms is deferred; raise keeps its normal semantics
        through the deferred entry point."""
        faults.configure("controller.tally:action=raise")
        with pytest.raises(faults.FaultInjectedError):
            faults.inject_deferred("controller.tally", rank=0)

    def test_inject_deferred_nth_fires_once(self):
        faults.configure("controller.tally:rank=1:nth=2:action=delay_ms,200")
        assert faults.inject_deferred("controller.tally", rank=1) == 0.0
        assert faults.inject_deferred("controller.tally", rank=1) \
            == pytest.approx(0.200)
        assert faults.inject_deferred("controller.tally", rank=1) == 0.0

    def test_inject_deferred_after_fires_every_call(self):
        faults.configure("controller.tally:rank=1:after=1:action=delay_ms,50")
        assert faults.inject_deferred("controller.tally", rank=1) == 0.0
        for _ in range(3):
            assert faults.inject_deferred("controller.tally", rank=1) \
                == pytest.approx(0.050)


# ---------------------------------------------------------------------------
# chaos: subprocess worker jobs under injected faults
# ---------------------------------------------------------------------------

_SURVIVOR_BODY = """
from horovod_tpu.common.exceptions import HorovodInternalError
try:
    for i in range(500):
        hvd.allreduce(np.ones(32, np.float32), name=f"t{i % 4}")
    print("NO_FAULT_SEEN", rank, flush=True)
except HorovodInternalError as e:
    print("SURVIVOR_ABORT", rank, str(e).replace("\\n", " "), flush=True)
"""


@pytest.mark.timeout(150)
def test_kill_rank_mid_allreduce_np4_coordinated_abort():
    """A rank hard-dying mid-collective (os._exit via the
    dispatch.collective site) must surface as a coordinated
    HorovodInternalError on EVERY survivor — not an eternal block in
    recv."""
    outs = run_distributed(
        4, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "dispatch.collective:rank=2:nth=2:action=exit,9"})
    for r in (0, 1, 3):
        assert f"SURVIVOR_ABORT {r}" in outs[r], (r, outs[r])
    assert "SURVIVOR_ABORT 2" not in outs[2]  # the victim died, silently


@pytest.mark.timeout(150)
def test_hang_recv_np2_deadline_then_coordinated_abort():
    """A rank wedged inside recv (bounded-hang flavor of ``action=hang``,
    so the harness can also observe the VICTIM's recovery): the healthy
    rank's progress deadline trips, it broadcasts the abort, and when the
    victim unwedges it reads the abort frame instead of re-blocking."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "tcp.recv:rank=1:nth=3:action=delay_ms,8000"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "no recv progress" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]
    # The victim's exact error depends on whether rank 0's process is
    # still alive when it unwedges: it either reads the buffered abort
    # frame (coordinated abort) or fails fast on the torn socket
    # (PeerGoneError).  Both are clean errors; neither is a hang.
    assert "coordinated abort from rank 0" in outs[1] \
        or "peer rank 0 is gone" in outs[1], outs[1]


@pytest.mark.timeout(150)
def test_drop_negotiation_frame_np2_coordinated_abort():
    """A silently-lost control-plane frame must not strand the job: the
    coordinator sees no progress, marks the peer gone, aborts both
    sides."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=5:action=drop"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]


@pytest.mark.timeout(150)
def test_delayed_frames_complete_without_false_abort():
    """Slow-but-alive must NOT abort: per-frame delays well under the
    deadline reset the progress clock (any bytes count), and the job
    completes normally."""
    outs = run_distributed(
        2, """
for i in range(5):
    out = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"d{i}")
    assert np.allclose(np.asarray(out), 2.0), out
print("DELAY_OK", rank, flush=True)
""", timeout=120, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:after=0:action=delay_ms,80"})
    for r in range(2):
        assert f"DELAY_OK {r}" in outs[r], outs[r]


@pytest.mark.timeout(150)
def test_stall_shutdown_np4_propagates_to_all_ranks():
    """The stall inspector's hard abort must reach the ranks that DID
    submit: the coordinator raises locally and the abort broadcast carries
    the stall text (tensor + missing ranks) to every survivor."""
    outs = run_distributed(
        4, """
import time
from horovod_tpu.common.exceptions import HorovodInternalError
if rank == 3:
    time.sleep(8)    # never submits (must outlive the 3s stall deadline)
else:
    try:
        hvd.allreduce(np.ones(4, np.float32), name="never")
        print("STALL_NOT_DETECTED", rank, flush=True)
    except HorovodInternalError as e:
        print("STALL_ABORT", rank, str(e).replace("\\n", " "), flush=True)
""", timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_STALL_CHECK_TIME_SECONDS": "1",
                   "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "3"})
    for r in (0, 1, 2):
        assert f"STALL_ABORT {r}" in outs[r], (r, outs[r])
        assert "stall shutdown" in outs[r], (r, outs[r])
        assert "never" in outs[r], (r, outs[r])


@pytest.mark.timeout(150)
def test_rendezvous_failure_fails_init_fast():
    """A dying rendezvous store during bring-up must fail init promptly on
    every rank (HorovodInternalError out of hvd.init) — the no-hang bound
    is this test's own watchdog."""
    outs = run_distributed(
        2, "", timeout=90, expect_failure=True, retries=0,
        extra_env={"HOROVOD_FAULT_SPEC":
                       "rendezvous.get:action=raise_oserror",
                   "HOROVOD_MESH_STARTUP_TIMEOUT": "10"})
    for out in outs:
        assert "WORKER_OK" not in out  # init must have failed


@pytest.mark.timeout(150)
def test_corrupt_frame_np2_coordinated_abort():
    """A single in-flight byte flip must abort BOTH ranks with the wire-CRC
    diagnosis within one poll quantum — never desync into reading
    negotiation bytes as tensor data (the PR 2 failure this plane
    closes)."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=corrupt,1"})
    # rank 0 detects (its recv fails CRC); rank 1 hears the abort naming
    # the CRC failure — or observes the torn socket, both clean errors
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "wire CRC" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]


@pytest.mark.timeout(150)
def test_corrupt_abort_writes_flight_recorder_dump_on_every_rank(tmp_path):
    """The flight recorder's contract (docs/observability.md): an injected
    mid-train corruption abort leaves a parseable post-mortem JSON on
    EVERY rank — the detector (CRC failure) and the survivor (coordinated
    abort) alike — naming the reason and carrying the recent-event ring
    plus a metrics snapshot.  The injecting rank's ring must contain the
    fired fault itself (recorded before the action ran)."""
    import json

    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FLIGHT_RECORDER_DIR": str(tmp_path),
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=corrupt,1"})
    for r in range(2):
        assert f"SURVIVOR_ABORT {r}" in outs[r], (r, outs[r])
        dump = tmp_path / "hvd_flight_recorder" \
            / f"hvd_flight_recorder.rank{r}.json"
        assert dump.exists(), (r, outs[r])
        doc = json.loads(dump.read_text())  # parseable on every rank
        assert doc["rank"] == r
        assert "background loop death" in doc["reason"], doc["reason"]
        assert doc["events"], "flight-recorder ring was empty"
        kinds = {e["kind"] for e in doc["events"]}
        assert "frame" in kinds, kinds
        assert doc["metrics"] and "counters" in doc["metrics"]
    # the detector's dump names the CRC failure; the injector's ring
    # recorded its own fired fault clause
    dump_dir = tmp_path / "hvd_flight_recorder"
    doc0 = json.loads((dump_dir / "hvd_flight_recorder.rank0.json")
                      .read_text())
    assert "wire CRC" in doc0["reason"] or "FrameCorrupt" in doc0["reason"]
    doc1 = json.loads((dump_dir / "hvd_flight_recorder.rank1.json")
                      .read_text())
    assert "fault" in {e["kind"] for e in doc1["events"]}, doc1["events"]


@pytest.mark.timeout(150)
def test_corrupt_compressed_frame_np2_coordinated_abort():
    """Compression must not open an integrity hole: a byte flip on a
    COMPRESSED (fp16-on-the-wire, digest-deferred) frame is caught by the
    step digest and aborts both ranks with the wire-CRC diagnosis."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_WIRE_COMPRESSION": "fp16",
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=corrupt,1"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "wire CRC" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]


@pytest.mark.timeout(150)
def test_truncate_compressed_frame_np2_coordinated_abort():
    """A truncated compressed frame misframes the stream; the size/parse
    layer (or the step digest, whichever meets it first) must convert it
    into a coordinated abort — never a hang or a struct.error."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_WIRE_COMPRESSION": "fp16",
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=truncate,4"})
    for r in range(2):
        assert f"SURVIVOR_ABORT {r}" in outs[r], (r, outs[r])
        assert "struct.error" not in outs[r], (r, outs[r])


@pytest.mark.timeout(150)
def test_corrupt_int8_frame_np2_coordinated_abort():
    """The lossy codecs ride the same integrity plane: a byte flip on an
    int8-quantized (digest-deferred) byte blob is caught by the step
    digest and aborts both ranks with the wire-CRC diagnosis."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_WIRE_COMPRESSION": "int8",
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=corrupt,1"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "wire CRC" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]


@pytest.mark.timeout(150)
def test_truncate_topk_frame_np2_coordinated_abort():
    """A truncated variable-length topk frame misframes the stream; the
    exact-size contract (sizes derived from wire_nbytes on both ends, not
    from the bytes) converts it into a coordinated abort — never a hang
    or a struct.error."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_WIRE_COMPRESSION": "topk10",
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=truncate,4"})
    for r in range(2):
        assert f"SURVIVOR_ABORT {r}" in outs[r], (r, outs[r])
        assert "struct.error" not in outs[r], (r, outs[r])


@pytest.mark.timeout(150)
def test_truncated_frame_np2_typed_abort():
    """A misframed (short) application frame passes the wire CRC by
    construction and must be caught by the defensive parse layer as a
    typed error — both ranks abort, nobody hangs or struct-errors."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_FAST_DEADLINE,
                   "HOROVOD_FAULT_SPEC":
                       "tcp.send:rank=1:nth=6:action=truncate,4"})
    for r in range(2):
        assert f"SURVIVOR_ABORT {r}" in outs[r], (r, outs[r])
        assert "struct.error" not in outs[r], (r, outs[r])


# ---------------------------------------------------------------------------
# performance attribution plane (docs/observability.md): straggler
# detector + lifecycle trace + critical-path report, one np=3 run
# ---------------------------------------------------------------------------


_STRAGGLER_BODY = """
from horovod_tpu.core import flight_recorder, metrics

gauge_named_rank1 = 0
for i in range(24):
    # DISTINCT names every round: cache misses keep the negotiation on
    # the table path, so the coordinator emits NEGOTIATE spans with
    # per-rank readiness instants (critical_path's attribution input).
    hvd.allreduce(np.ones(4096, np.float32), name=f"cp{i}")
    if rank == 0 and metrics.registry.get_gauge("straggler_suspect") == 1:
        gauge_named_rank1 += 1
hvd.barrier()
if rank == 0:
    flags = metrics.registry.get_counter("straggler_flags_total", rank="1")
    assert flags >= 1, f"rank 1 never flagged (flags={flags})"
    for r in (0, 2):
        assert metrics.registry.get_counter(
            "straggler_flags_total", rank=str(r)) == 0, r
    assert gauge_named_rank1 > 0, "straggler_suspect gauge never hit 1"
    stragglers = [e for e in flight_recorder.recorder.events()
                  if e["kind"] == "straggler"]
    assert stragglers, "no straggler event in the coordinator's ring"
    assert all(e["rank"] == 1 for e in stragglers), stragglers
    path = flight_recorder.recorder.dump("straggler-proof")
    assert path, "flight-recorder dump failed"
    print("STRAGGLER_OK", flush=True)
"""


@pytest.mark.timeout(360)
def test_straggler_attribution_np3_all_surfaces_agree(tmp_path):
    """Headline acceptance: ONE np=3 run with an injected 60 ms delay on
    every rank-1 collective submission (the ``enqueue.collective`` site),
    run under lockdep, must make all three attribution surfaces agree:

    - the online detector flags rank 1 (``straggler_flags_total`` +
      ``straggler_suspect`` gauge observed naming rank 1, never 0 or 2),
    - the coordinator's flight-recorder dump carries ``straggler`` events
      for rank 1,
    - the merged 3-rank timeline's critical-path report attributes the
      inflated step time to rank 1's negotiation-wait phase."""
    from horovod_tpu.tools import critical_path, trace_merge

    tl = tmp_path / "tl.json"
    outs = run_distributed(
        3, _STRAGGLER_BODY, timeout=300,
        extra_env={
            "HOROVOD_FAULT_SPEC":
                "enqueue.collective:rank=1:action=delay_ms,60",
            "HOROVOD_STRAGGLER_THRESHOLD_SECS": "0.015",
            "HOROVOD_STRAGGLER_EWMA_ALPHA": "0.6",
            "HOROVOD_TIMELINE": str(tl),
            "HOROVOD_FLIGHT_RECORDER_DIR": str(tmp_path),
            "HOROVOD_LOCK_DEBUG": "1",
        })
    assert "STRAGGLER_OK" in outs[0], outs[0]

    # surface 2: the dump artifact (hvd_flight_recorder/ subdir) parses
    # and names rank 1
    dump = tmp_path / "hvd_flight_recorder" / "hvd_flight_recorder.rank0.json"
    assert dump.exists()
    doc = json.loads(dump.read_text())
    events = [e for e in doc["events"] if e["kind"] == "straggler"]
    assert events and all(e["rank"] == 1 for e in events), doc["events"]

    # surface 3: hvd-critical-path over the merged trace pins the
    # inflation on rank 1's negotiation wait
    traces = [trace_merge.load_trace(
        str(tl) if r == 0 else f"{tl}.rank{r}") for r in range(3)]
    report = critical_path.analyze(trace_merge.merge(traces))
    waits = {r: report["totals_us"].get(str(r), {})
             .get("negotiation_wait", 0.0) for r in range(3)}
    # 24 rounds x 60 ms injected: rank 1 owes most of a second of
    # negotiation wait; the healthy ranks only scheduling jitter.
    assert waits[1] > 500e3, waits
    assert waits[1] > 5 * max(waits[0], waits[2]), waits
    dominated = [s for s in report["steps"]
                 if s["dominant"]["rank"] == 1
                 and s["dominant"]["phase"] == "negotiation_wait"]
    assert dominated, report["steps"][:3]


_KILL_MID_SAVE_BODY = """
import horovod_tpu.frameworks.jax.checkpoint as ckpt
base = BASE_DIR + "/run"
for step in (1, 2, 3):
    ckpt.save_rotating(
        base, {"w": np.full(4, float(step), np.float32), "step": step},
        keep=5, step=step)
    print("SAVED", step, flush=True)
print("SURVIVED_ALL_SAVES", flush=True)
"""

_RESTORE_AFTER_KILL_BODY = """
import logging, sys
import horovod_tpu.frameworks.jax.checkpoint as ckpt
_log = logging.getLogger("horovod_tpu.frameworks.jax.checkpoint")
_log.addHandler(logging.StreamHandler(sys.stdout))
_log.setLevel(logging.INFO)
state = ckpt.restore_latest(
    BASE_DIR + "/run",
    like={"w": np.zeros(4, np.float32), "step": 0})
assert int(state["step"]) == 2, state
assert np.allclose(np.asarray(state["w"]), 2.0), state
print("RESTORED_PREVIOUS_VALID", rank, flush=True)
"""


@pytest.mark.timeout(150)
def test_kill_mid_ckpt_save_restore_latest_skips_half_written(tmp_path):
    """A rank hard-dying inside ``ckpt.save`` (between payload publish
    and manifest commit — the ``ckpt.save`` site's window) leaves a
    half-written newest snapshot; ``restore_latest`` must detect it,
    LOG the skip, and land on the last intact snapshot."""
    prelude = f"BASE_DIR = {str(tmp_path)!r}\n"
    outs = run_distributed(
        1, prelude + _KILL_MID_SAVE_BODY, timeout=120,
        expect_failure=True, retries=0,
        extra_env={"HOROVOD_FAULT_SPEC": "ckpt.save:nth=3:action=exit,9"})
    assert "SAVED 2" in outs[0], outs[0]
    assert "SURVIVED_ALL_SAVES" not in outs[0], outs[0]

    outs = run_distributed(1, prelude + _RESTORE_AFTER_KILL_BODY,
                           timeout=120, retries=0)
    assert "RESTORED_PREVIOUS_VALID 0" in outs[0], outs[0]
    assert "skipping snapshot" in outs[0], outs[0]
    assert "00000003" in outs[0], outs[0]   # names WHAT it skipped
    assert "no manifest" in outs[0], outs[0]  # ...and why


_ELASTIC_CHAOS_TRAIN = """
import os, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0)

@hvd.elastic.run
def train(state):
    while state.batch < 25:
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="g")
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()}",
              flush=True)
        state.batch += 1
        state.commit()
        time.sleep(0.05)

train(state)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


_ELASTIC_CORRUPTION_TRAIN = """
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0, params=np.zeros(4, np.float32))

@hvd.elastic.run
def train(state):
    while state.batch < 15:
        grad = hvd.allreduce(
            np.full(4, float(state.batch + 1), np.float32),
            op=hvd.Sum, name="g")
        state.params = state.params + np.asarray(grad)
        state.batch += 1
        state.commit()

train(state)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), np.asarray(state.params).tobytes().hex()), flush=True)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


_ELASTIC_INT8_TRAIN = _ELASTIC_CORRUPTION_TRAIN.replace(
    "np.full(4, float(state.batch + 1), np.float32)",
    "np.full(4, 127.0 * float(state.batch + 1), np.float32)")


def _run_elastic_corruption_job(tmp_path, fault_spec, extra_env=None,
                                train_src=_ELASTIC_CORRUPTION_TRAIN):
    disc = tmp_path / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / f"train_{'fault' if fault_spec else 'clean'}.py"
    train.write_text(train_src)

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.update(extra_env or {})
    env["HOROVOD_LOG_LEVEL"] = "info"  # driver logs the reset trigger
    env.pop("HOROVOD_FAULT_SPEC", None)
    if fault_spec:
        env["HOROVOD_FAULT_SPEC"] = fault_spec
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "2",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=240)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)",
                             proc.stdout))
    assert set(params) == {"0", "1"}, proc.stdout[-2000:]
    assert params["0"] == params["1"], "ranks diverged"
    return params["0"], proc


@pytest.mark.timeout(600)
def test_elastic_recovers_from_frame_corruption_bit_identical(tmp_path):
    """The integrity plane end to end: an in-flight byte flip mid-training
    aborts both (still-alive) ranks, the worker-posted reset request makes
    the driver advance an epoch, both workers roll back to their last
    commit and re-rendezvous — and the finished run's params are
    BIT-identical to a no-fault run of the same script."""
    clean, _ = _run_elastic_corruption_job(tmp_path, None)
    faulted, proc = _run_elastic_corruption_job(
        tmp_path, "tcp.send:rank=1:nth=25:action=corrupt,1")
    assert faulted == clean, "recovery did not converge to the no-fault run"
    # the fault actually fired and recovered through the epoch plane: the
    # driver logged the worker's reset request naming the CRC failure
    assert "reset_requests" in proc.stderr and "advancing epoch" \
        in proc.stderr, proc.stderr[-3000:]
    assert "wire CRC" in proc.stderr, proc.stderr[-3000:]


@pytest.mark.timeout(600)
def test_elastic_recovers_from_corruption_with_compression_on(tmp_path):
    """The full composition: fp16 wire compression + shadow digests +
    an in-flight byte flip.  The step digest catches the flip, both ranks
    roll back and re-rendezvous, and the finished params are BIT-identical
    to a no-fault run with the same compression config (quantization is
    deterministic, so recovery replay converges exactly)."""
    comp_env = {"HOROVOD_WIRE_COMPRESSION": "fp16"}
    clean, _ = _run_elastic_corruption_job(tmp_path, None,
                                           extra_env=comp_env)
    faulted, proc = _run_elastic_corruption_job(
        tmp_path, "tcp.send:rank=1:nth=25:action=corrupt,1",
        extra_env=comp_env)
    assert faulted == clean, "recovery did not converge to the no-fault run"
    assert "wire CRC" in proc.stderr, proc.stderr[-3000:]


@pytest.mark.timeout(600)
def test_elastic_recovers_with_int8_compression_bit_identical(tmp_path):
    """Lossy compression composes with elastic recovery: int8 + error
    feedback + an in-flight byte flip.  The gradients are crafted so the
    int8 round trip is EXACT (magnitudes 127·(batch+1) → scale divides
    out, residuals stay zero), so dropping the EF accumulators at
    re-init — which recovery must do, state is op-owned — leaves the
    faulted run BIT-identical to a no-fault run."""
    comp_env = {"HOROVOD_WIRE_COMPRESSION": "int8"}
    clean, _ = _run_elastic_corruption_job(
        tmp_path, None, extra_env=comp_env,
        train_src=_ELASTIC_INT8_TRAIN)
    faulted, proc = _run_elastic_corruption_job(
        tmp_path, "tcp.send:rank=1:nth=25:action=corrupt,1",
        extra_env=comp_env, train_src=_ELASTIC_INT8_TRAIN)
    assert faulted == clean, "recovery did not converge to the no-fault run"
    assert "wire CRC" in proc.stderr, proc.stderr[-3000:]


@pytest.mark.timeout(300)
def test_elastic_recovers_from_injected_rank_death(tmp_path):
    """End-to-end: HOROVOD_FAULT_SPEC hard-kills rank 1 mid-run under the
    elastic launcher; the survivor rolls back to its last commit,
    re-rendezvouses at size 1, and finishes — an injected fault rides the
    exact recovery path a real worker death does."""
    disc = tmp_path / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_ELASTIC_CHAOS_TRAIN)

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    # Fires only in rank 1's worker process (rank filter); the respawned
    # world has no rank 1, so recovery runs fault-free.
    env["HOROVOD_FAULT_SPEC"] = "dispatch.collective:rank=1:nth=8:action=exit,9"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "1",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=240)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "ELASTIC_DONE" in proc.stdout, proc.stdout[-2000:]
    assert "size=2" in proc.stdout, "never ran at full size"
    assert "size=1" in proc.stdout, "never recovered at reduced size"


# ---------------------------------------------------------------------------
# self-healing straggler demotion (docs/elastic.md "self-healing demotion")
# ---------------------------------------------------------------------------

# Averaging allreduce (the default op) with IDENTICAL per-rank
# contributions: the average equals the contribution at every world size,
# so a run that sheds a host mid-training must still land on params
# BIT-identical to an undisturbed run.  Contributions are small integers
# (exact in fp32; sum/divide round-trips exactly), so "bit-identical" is
# a meaningful assertion, not a tolerance.
_ELASTIC_DEMOTION_TRAIN = """
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0, params=np.zeros(4, np.float32))

@hvd.elastic.run
def train(state):
    while state.batch < 30:
        grad = hvd.allreduce(
            np.full(4, float(state.batch + 1), np.float32), name="g")
        state.params = state.params + np.asarray(grad)
        state.batch += 1
        state.commit()

train(state)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), np.asarray(state.params).tobytes().hex()), flush=True)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""

# Aggressive-but-stable detector tuning for a CI-sized job.  The chronic
# clause defers rank 1's tallies by 300ms per cycle, far over the 0.1s
# demote threshold; 3 consecutive over-threshold cycles take ~1s of
# wall-clock.  The response cache must be OFF: cache-bit announcements
# bypass the request-table tally path the controller.tally site lives on
# (docs/fault_injection.md).
_DEMOTION_KNOBS = {
    "HOROVOD_STRAGGLER_THRESHOLD_SECS": "0.08",
    "HOROVOD_STRAGGLER_EWMA_ALPHA": "0.5",
    "HOROVOD_STRAGGLER_DEMOTE_SECS": "0.1",
    "HOROVOD_STRAGGLER_DEMOTE_CYCLES": "3",
    "HOROVOD_CACHE_CAPACITY": "0",
    "HOROVOD_LOCK_DEBUG": "1",
}


def _run_demotion_job(tmp_path, fault_spec, min_np=2, extra_env=None):
    """np=3 elastic job across three loopback 'hosts' (one slot each) so a
    demotion sheds exactly one host.  Returns (rank->params map, proc)."""
    disc = tmp_path / "discover3.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n"
                    "echo 127.0.0.2:1\n")
    disc.chmod(0o755)
    train = tmp_path / f"train_{'fault' if fault_spec else 'clean'}.py"
    train.write_text(_ELASTIC_DEMOTION_TRAIN)

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.update(_DEMOTION_KNOBS)
    env.update(extra_env or {})
    env["HOROVOD_LOG_LEVEL"] = "info"  # driver logs the demotion cause
    env.pop("HOROVOD_FAULT_SPEC", None)
    if fault_spec:
        env["HOROVOD_FAULT_SPEC"] = fault_spec
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "3", "--min-np", str(min_np),
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=240)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-3000:])
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)",
                             proc.stdout))
    assert params, proc.stdout[-2000:]
    assert len(set(params.values())) == 1, "ranks diverged"
    return params, proc


@pytest.mark.timeout(600)
def test_chronic_straggler_demoted_job_converges_bit_identical(tmp_path):
    """The tentpole end to end: a chronically slow rank (every tally
    deferred 300ms via controller.tally) trips the demotion state machine,
    the coordinator posts the verdict over the rendezvous store, the
    driver blacklists the straggler's host and advances the epoch with
    cause=demotion, and the surviving np=2 world finishes with params
    BIT-identical to an undisturbed np=3 run."""
    clean, _ = _run_demotion_job(tmp_path, None)
    assert set(clean) == {"0", "1", "2"}
    faulted, proc = _run_demotion_job(
        tmp_path, "controller.tally:rank=1:after=0:action=delay_ms,300")
    # The straggler's host was shed: the run finished at size 2, and the
    # demoted worker never printed final params.
    assert set(faulted) == {"0", "1"}, proc.stdout[-2000:]
    assert faulted["0"] == clean["0"], \
        "demoted run did not converge to the no-fault run"
    # The full demotion chain is visible in the driver/coordinator logs:
    # chronic verdict -> blacklist with EWMA evidence -> epoch advance
    # attributed to the demotion (not to a worker death or reset).
    assert "chronic straggler" in proc.stderr, proc.stderr[-3000:]
    assert "blacklisting host 127.0.0.1" in proc.stderr, proc.stderr[-3000:]
    assert "readiness-lag EWMA" in proc.stderr, proc.stderr[-3000:]
    assert "cause=demotion" in proc.stderr, proc.stderr[-3000:]
    assert "advancing epoch" in proc.stderr, proc.stderr[-3000:]


@pytest.mark.timeout(600)
def test_one_shot_straggle_flags_but_does_not_demote(tmp_path):
    """Demotion false-positive guard: a single 200ms spike trips the
    straggler FLAG (threshold 0.05s) but can never fill the demotion
    window — the lag EWMA is bounded by the largest observed lag (~0.2s),
    which stays strictly under the 0.3s demote threshold, so no streak
    ever starts.  The job keeps all three ranks and still converges
    bit-identically to the clean run: flagging is free, shedding is not."""
    spike_knobs = {"HOROVOD_STRAGGLER_THRESHOLD_SECS": "0.05",
                   "HOROVOD_STRAGGLER_DEMOTE_SECS": "0.3"}
    clean, _ = _run_demotion_job(tmp_path, None, extra_env=spike_knobs)
    faulted, proc = _run_demotion_job(
        tmp_path, "controller.tally:rank=1:nth=3:action=delay_ms,200",
        extra_env=spike_knobs)
    assert set(faulted) == {"0", "1", "2"}, \
        "a one-shot delay cost the job a host"
    assert faulted["0"] == clean["0"]
    assert "straggler detected" in proc.stderr, \
        "the spike never even flagged — the test exercised nothing"
    assert "chronic straggler" not in proc.stderr, proc.stderr[-3000:]
    assert "blacklisting host" not in proc.stderr, proc.stderr[-3000:]
    assert "cause=demotion" not in proc.stderr, proc.stderr[-3000:]


# ---------------------------------------------------------------------------
# zero-restart elastic resharding (docs/elastic.md "Live resharding")
# ---------------------------------------------------------------------------

# Crash limit raised over the default of 1 so the SIGKILL'd victim's host
# is NOT shed: its identity must come back as a JOINER of the resharded
# epoch (exercising the sync_root broadcast), not vanish with the host.
# min_np == np below pins the world size, so the averaging-allreduce
# bit-identity argument needs no size-change caveat.
_RESHARD_KNOBS = {
    "HOROVOD_ELASTIC_CRASH_FAILURE_LIMIT": "5",
    "HOROVOD_LOCK_DEBUG": "1",
}


# The victim's fault must fire ONCE per job, not once per process: the
# respawned joiner inherits HOROVOD_FAULT_SPEC and would kill itself
# again every nth collectives until the host blacklists.  Each identity
# marks its first incarnation with a flag file keyed on
# HOROVOD_LOCAL_RANK (set per slot by the launcher, readable before
# hvd.init); a REspawned incarnation finds its own flag and disarms the
# spec before the faults registry parses it at import.
_RESHARD_DISARM_PREAMBLE = """
import os
_flag = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "spawned_%s" % os.environ.get("HOROVOD_LOCAL_RANK"))
if os.path.exists(_flag):
    os.environ.pop("HOROVOD_FAULT_SPEC", None)
else:
    open(_flag, "w").close()
"""


def _run_reshard_job(tmp_path, fault_spec, extra_env=None):
    """np=8 elastic job on ONE loopback host (8 slots).  Returns
    (rank->params map, proc)."""
    disc = tmp_path / "discover8.sh"
    disc.write_text("#!/bin/sh\necho localhost:8\n")
    disc.chmod(0o755)
    arm = "fault" if fault_spec else "clean"
    jobdir = tmp_path / arm
    jobdir.mkdir()
    train = jobdir / "train.py"
    train.write_text(_RESHARD_DISARM_PREAMBLE + _ELASTIC_DEMOTION_TRAIN)

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.update(_RESHARD_KNOBS)
    env.update(extra_env or {})
    env["HOROVOD_LOG_LEVEL"] = "info"  # driver logs publish/commit/fallback
    env.pop("HOROVOD_FAULT_SPEC", None)
    if fault_spec:
        env["HOROVOD_FAULT_SPEC"] = fault_spec
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "8", "--min-np", "8",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-3000:])
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)",
                             proc.stdout))
    assert params, proc.stdout[-2000:]
    assert len(set(params.values())) == 1, "ranks diverged"
    return params, proc


def _spawns_by_epoch(stderr):
    """[(identity, epoch), ...] from the driver's spawn log lines."""
    return [(ident, int(ep)) for ident, ep in
            re.findall(r"spawning worker (\S+) \(epoch (\d+)", stderr)]


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_live_reshard_np8_survivors_keep_processes_joiner_syncs(tmp_path):
    """The tentpole end to end at np=8: rank 3 is SIGKILL'd mid-train, the
    driver publishes the next assignment with the reshard marker, the 7
    survivors abort their in-flight collectives and re-rendezvous IN PLACE
    (the driver spawns exactly one post-churn process: the victim's
    identity, back as a joiner), the joiner receives mid-training state
    over the sync_root broadcast — this job has no checkpointing at all,
    so the joiner finishing bit-identical IS the proof the state came over
    collectives — and the commit record lands only after every survivor
    acked the new epoch."""
    clean, _ = _run_reshard_job(tmp_path, None)
    assert set(clean) == {str(r) for r in range(8)}
    faulted, proc = _run_reshard_job(
        tmp_path, "dispatch.collective:rank=3:nth=8:action=exit,9")
    assert set(faulted) == {str(r) for r in range(8)}, proc.stdout[-2000:]
    assert faulted["0"] == clean["0"], \
        "resharded run did not converge to the no-churn run"
    # The reshard protocol ran — marked publish, then the commit that
    # requires every survivor's ack — and never degraded to the legacy
    # full-teardown path.
    assert "published with reshard marker" in proc.stderr, \
        proc.stderr[-3000:]
    assert "reshard committed at epoch" in proc.stderr, proc.stderr[-3000:]
    assert "falls back to the full-teardown path" not in proc.stderr, \
        proc.stderr[-3000:]
    # Zero restarts for survivors: 8 spawns at epoch 0, then exactly ONE
    # post-churn spawn, and it is the victim's identity.
    spawns = _spawns_by_epoch(proc.stderr)
    initial = [ident for ident, ep in spawns if ep == 0]
    later = [ident for ident, ep in spawns if ep > 0]
    assert len(initial) == 8, spawns
    assert later == ["localhost:3"], \
        f"survivors were respawned (or the victim was not): {spawns}"


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_live_reshard_kill_switch_falls_back_and_still_converges(tmp_path):
    """HOROVOD_RESHARD=0 is the operator kill-switch: the same SIGKILL
    churn must publish NO reshard marker and write NO commit record — the
    job recovers on the legacy path (survivors ride out the progress
    deadline instead of the prompt abort) and still converges
    bit-identical.  The fallback is load-bearing: this is also the path a
    wedged reshard degrades to."""
    clean, _ = _run_reshard_job(tmp_path, None,
                                extra_env={"HOROVOD_RESHARD": "0"})
    faulted, proc = _run_reshard_job(
        tmp_path, "dispatch.collective:rank=3:nth=8:action=exit,9",
        extra_env={"HOROVOD_RESHARD": "0"})
    assert set(faulted) == {str(r) for r in range(8)}, proc.stdout[-2000:]
    assert faulted["0"] == clean["0"]
    assert "published with reshard marker" not in proc.stderr, \
        proc.stderr[-3000:]
    assert "reshard committed" not in proc.stderr, proc.stderr[-3000:]
    # The legacy path also keeps survivor processes: only the victim's
    # identity is respawned.  What the kill-switch changes is the abort
    # latency and the sync discipline, not the process-lifetime contract.
    later = [ident for ident, ep in _spawns_by_epoch(proc.stderr) if ep > 0]
    assert later == ["localhost:3"], proc.stderr[-3000:]


# ---------------------------------------------------------------------------
# negotiation fan-in aggregator death (docs/data_plane.md "Negotiation
# fan-in"): np=4 on TWO loopback hosts — the smallest layout that trees
# ---------------------------------------------------------------------------

# Keyed on HOROVOD_RANK (not LOCAL_RANK: two loopback hosts collide on
# local_rank 0) so the respawned aggregator incarnation disarms the kill
# before the faults registry parses it at import.
_FANIN_DISARM_PREAMBLE = """
import os
_flag = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "spawned_%s" % os.environ.get("HOROVOD_RANK"))
if os.path.exists(_flag):
    os.environ.pop("HOROVOD_FAULT_SPEC", None)
else:
    open(_flag, "w").close()
"""


_ELASTIC_FANIN_TRAIN = """
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
from horovod_tpu.core.state import global_state
_plan = global_state().controller.fanin_plan
print("FANIN_ROLE r%d %s" % (
    hvd.rank(), _plan.role if _plan is not None else "none"), flush=True)
state = hvd.elastic.ObjectState(batch=0, params=np.zeros(4, np.float32))

@hvd.elastic.run
def train(state):
    while state.batch < 15:
        grad = hvd.allreduce(
            np.full(4, float(state.batch + 1), np.float32),
            op=hvd.Sum, name="g")
        state.params = state.params + np.asarray(grad)
        state.batch += 1
        state.commit()

train(state)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), np.asarray(state.params).tobytes().hex()), flush=True)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


def _run_fanin_death_job(tmp_path, fault_spec, extra_env=None):
    """np=4 elastic job on TWO loopback hosts (2 slots each): the blocked
    2x2 layout turns tree negotiation fan-in on (auto), making rank 2 the
    host-1 aggregator.  Returns (rank->params map, proc)."""
    arm = "fault" if fault_spec else "clean"
    jobdir = tmp_path / arm
    jobdir.mkdir()
    disc = jobdir / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:2\necho 127.0.0.1:2\n")
    disc.chmod(0o755)
    train = jobdir / "train.py"
    train.write_text(_FANIN_DISARM_PREAMBLE + _ELASTIC_FANIN_TRAIN)

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.update(_RESHARD_KNOBS)
    env.update(extra_env or {})
    env["HOROVOD_LOG_LEVEL"] = "info"
    env.pop("HOROVOD_FAULT_SPEC", None)
    if fault_spec:
        env["HOROVOD_FAULT_SPEC"] = fault_spec
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "4", "--min-np", "4",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=360)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-3000:])
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)",
                             proc.stdout))
    assert set(params) == {str(r) for r in range(4)}, proc.stdout[-2000:]
    assert len(set(params.values())) == 1, "ranks diverged"
    return params, proc


@pytest.mark.timeout(600)
def test_fanin_aggregator_death_np4_reconverges_bit_identical(tmp_path):
    """An aggregator death must never silence its host or lose a
    readiness bit: rank 2 (host 1's negotiation aggregator) is SIGKILL'd
    mid-train; its member's blocking recv raises PeerGoneError promptly,
    the coordinated abort discards the in-flight cycle on every path,
    the PR 19 reshard respawns exactly the victim's identity, and the
    re-treed epoch finishes BIT-identical to an undisturbed run — the
    stateless-fold property live (every cycle re-announces the full
    mask, so the discarded cycle loses nothing).  The wedge flavor
    (stale heartbeat -> veto -> direct) is exhaustively model-checked in
    test_mck_proto.py and unit-covered in test_negotiation_fanin.py."""
    clean, cproc = _run_fanin_death_job(tmp_path, None)
    faulted, proc = _run_fanin_death_job(
        tmp_path, "dispatch.collective:rank=2:nth=8:action=exit,9")
    assert faulted == clean, \
        "aggregator-death recovery did not converge to the no-fault run"
    # The tree was live in both runs and rank 2 WAS host 1's aggregator
    # (the respawned incarnation re-trees into the same role).
    for out in (cproc.stdout, proc.stdout):
        roles = dict(re.findall(r"FANIN_ROLE r(\d+) (\w+)", out))
        assert roles == {"0": "coordinator", "1": "direct",
                         "2": "aggregator", "3": "member"}, out[-2000:]
    # Zero-restart recovery: exactly one post-churn spawn, the dead
    # aggregator's identity.
    later = [ident for ident, ep in _spawns_by_epoch(proc.stderr) if ep > 0]
    assert later == ["127.0.0.1:0"], proc.stderr[-3000:]


# ---------------------------------------------------------------------------
# control-plane survivability (docs/control_plane.md)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_dead_worker_lease_expiry_advances_epoch_within_one_tick():
    """A worker whose PROCESS is alive but whose lease stops renewing is
    genuinely dead to the job: the driver must declare it dead and advance
    the epoch on the first tick after expiry — the liveness half of
    dead-vs-partitioned (a store outage, by contrast, must freeze this
    judgment; tested in the SIGKILL run below)."""
    from horovod_tpu.core import metrics as metrics_mod
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import parse_hosts
    from horovod_tpu.runner.rendezvous import RendezvousServer
    from horovod_tpu.transport.store import LEASE_SCOPE

    server = RendezvousServer("127.0.0.1")
    server.start()
    spawned = []
    driver = ElasticDriver(
        server,
        HostManager(FixedHosts(parse_hosts("localhost:1,127.0.0.1:1"))),
        min_np=2, lease_timeout=1.5)
    stop_renewals = threading.Event()

    def renew_survivor():
        n = 0
        while not stop_renewals.is_set():
            n += 1  # the VALUE must change: freshness is change-based
            server.set(LEASE_SCOPE, "localhost:0",
                       json.dumps({"rank": 0, "epoch": 0,
                                   "renewals": n}).encode())
            time.sleep(0.3)

    expirations_before = metrics_mod.registry.get_counter(
        "lease_expirations_total")
    try:
        driver.start(lambda slot, epoch: spawned.append(
            (f"{slot.hostname}:{slot.local_rank}", epoch)))
        assert driver.epoch == 0 and len(spawned) == 2
        threading.Thread(target=renew_survivor, daemon=True).start()
        # The doomed worker posts exactly ONE lease, then goes silent —
        # no exit event ever reaches the driver.
        server.set(LEASE_SCOPE, "127.0.0.1:0",
                   json.dumps({"rank": 1, "epoch": 0,
                               "renewals": 1}).encode())
        t0 = time.monotonic()
        while driver.epoch == 0 and time.monotonic() - t0 < 30:
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
        assert driver.epoch >= 1, "lease expiry never advanced the epoch"
        # Bound: baseline sighting (≤1 tick) + timeout (1.5 s) + one
        # judgment tick (1 s) + scheduling slack.  Anything near the 15 s
        # production default means expiry didn't drive the advance.
        assert elapsed < 10.0, f"epoch advance took {elapsed:.1f}s"
        # The dead identity was respawned at the new epoch; the renewing
        # survivor was left alone.
        deadline = time.monotonic() + 10
        while ("127.0.0.1:0", 1) not in spawned and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert ("127.0.0.1:0", 1) in spawned, spawned
        assert ("localhost:0", 1) not in spawned, spawned
        assert metrics_mod.registry.get_counter(
            "lease_expirations_total") >= expirations_before + 1
        # The transition itself must be attributable after the fact: a
        # cause-tagged flight-recorder event and counter (the driver runs
        # in this process, so both are inspectable directly).
        from horovod_tpu.core import flight_recorder

        trans = [e for e in flight_recorder.recorder.events()
                 if e.get("kind") == "epoch_transition"]
        assert trans, "driver recorded no epoch_transition event"
        assert trans[-1]["cause"] == "lease_expiry", trans[-1]
        assert "127.0.0.1:0" in trans[-1]["dead_workers"], trans[-1]
        assert metrics_mod.registry.get_counter(
            "driver_epoch_transitions_total", cause="lease_expiry") >= 1
    finally:
        stop_renewals.set()
        driver.stop()
        driver._discovery_thread.join(timeout=10)
        server.stop()


_SURVIVABILITY_TRAIN = """
import time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0, params=np.zeros(4, np.float32))

@hvd.elastic.run
def train(state):
    while state.batch < 80:
        grad = hvd.allreduce(
            np.full(4, float(state.batch + 1), np.float32),
            op=hvd.Sum, name="g")
        state.params = state.params + np.asarray(grad)
        if state.batch % 5 == 0:
            print(f"BATCH {state.batch} rank={hvd.rank()}", flush=True)
        state.batch += 1
        state.commit()
        time.sleep(0.1)

train(state)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), np.asarray(state.params).tobytes().hex()), flush=True)
hvd.shutdown()
"""


def _spawn_external_server(port, journal_dir, env):
    """Start the standalone journaled rendezvous server and wait for it
    to accept connections."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.rendezvous",
         "--bind", "127.0.0.1", "--port", str(port),
         "--journal-dir", str(journal_dir)],
        cwd=REPO_ROOT, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return proc
        except OSError:
            if proc.poll() is not None:
                break
            time.sleep(0.1)
    proc.kill()
    raise RuntimeError("standalone rendezvous server never came up")


def _pump(stream, sink):
    for line in iter(stream.readline, ""):
        sink.append(line)
    stream.close()


def _run_survivable_job(tmp_path, kill_server):
    """np=2 elastic job against an EXTERNAL journaled rendezvous server;
    optionally SIGKILL the server mid-train and restart it over the same
    journal ~2 s later.  Returns (params_hex, stdout, stderr)."""
    label = "kill" if kill_server else "clean"
    jdir = tmp_path / f"journal_{label}"
    port = reserve_port()
    release_reservations()  # hand the port to the server child

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.pop("HOROVOD_FAULT_SPEC", None)
    env["HOROVOD_LOG_LEVEL"] = "info"
    env["HOROVOD_SECRET_KEY"] = "survivability-chaos"
    env["HOROVOD_METRICS_PUSH_SECS"] = "0.5"  # lease-renewal cadence
    env["HOROVOD_RENDEZVOUS_EXTERNAL"] = f"127.0.0.1:{port}"

    disc = tmp_path / f"discover_{label}.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / f"train_{label}.py"
    train.write_text(_SURVIVABILITY_TRAIN)

    server = _spawn_external_server(port, jdir, env)
    launcher = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "2",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    out_lines, err_lines = [], []
    pumps = [threading.Thread(target=_pump, args=(launcher.stdout, out_lines),
                              daemon=True),
             threading.Thread(target=_pump, args=(launcher.stderr, err_lines),
                              daemon=True)]
    for t in pumps:
        t.start()
    try:
        if kill_server:
            # Wait until BOTH ranks are demonstrably past init and
            # training (a kill during init would be a different test).
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                text = "".join(out_lines)
                if re.search(r"BATCH \d+ rank=0", text) and \
                        re.search(r"BATCH \d+ rank=1", text):
                    break
                if launcher.poll() is not None:
                    break
                time.sleep(0.2)
            else:
                raise RuntimeError("ranks never reached training")
            server.kill()  # SIGKILL: no flush, no goodbye
            server.wait()
            time.sleep(2.0)  # a real supervisor restart delay
            server = _spawn_external_server(port, jdir, env)
        rc = launcher.wait(timeout=300)
    finally:
        if launcher.poll() is None:
            launcher.kill()
            launcher.wait()
        server.kill()
        server.wait()
    for t in pumps:
        t.join(timeout=10)
    stdout, stderr = "".join(out_lines), "".join(err_lines)
    assert rc == 0, (stdout[-2000:], stderr[-2000:])
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)", stdout))
    assert set(params) == {"0", "1"}, stdout[-2000:]
    assert params["0"] == params["1"], "ranks diverged"
    return params["0"], stdout, stderr


@pytest.mark.timeout(600)
def test_rendezvous_server_sigkill_restart_bit_identical(tmp_path):
    """The headline survivability proof: SIGKILL the external rendezvous
    server mid-train and restart it over the same journal — the np=2 job
    rides out the outage (best-effort pushes, partitioned-mode driver),
    reattaches, and converges BIT-identical to a no-fault run with ZERO
    epoch advances."""
    clean, _, _ = _run_survivable_job(tmp_path, kill_server=False)
    killed, _, stderr = _run_survivable_job(tmp_path, kill_server=True)
    assert killed == clean, \
        "post-restart run diverged from the no-fault run"
    # Zero epoch bumps: the outage must read as partitioned, never as
    # dead workers.
    assert "advancing epoch" not in stderr, stderr[-3000:]
    # And the outage actually happened and healed — this test must not
    # pass vacuously if the kill lands in a blind spot.
    assert "unreachable" in stderr, stderr[-3000:]
    assert "reachable again" in stderr, stderr[-3000:]


_STATIC_SURVIVABILITY_TRAIN = """
import jax
jax.config.update("jax_platforms", "cpu")
import time
import numpy as np
import horovod_tpu as hvd

hvd.init()
params = np.zeros(4, dtype=np.float32)
for batch in range(30):
    g = hvd.allreduce(np.full(4, batch + 1, dtype=np.float32),
                      name="g%d" % batch, average=False)
    params += np.asarray(g)
    print("BATCH %d rank=%d" % (batch, hvd.rank()), flush=True)
    time.sleep(0.1)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), params.tobytes().hex()), flush=True)
hvd.shutdown()
"""


@pytest.mark.timeout(300)
def test_static_launch_attaches_external_server_and_survives_restart(
        tmp_path):
    """HOROVOD_RENDEZVOUS_EXTERNAL on the PLAIN (non-elastic) launch
    path: the static launcher must attach to the standalone journaled
    server instead of starting its own, the np=2 job must ride out a
    SIGKILL+restart of that server mid-train, and the restarted server's
    journal must replay the slot table the launcher published."""
    jdir = tmp_path / "journal_static"
    port = reserve_port()
    release_reservations()

    env = os.environ.copy()
    env.update(_FAST_DEADLINE)
    env.pop("HOROVOD_FAULT_SPEC", None)
    env["HOROVOD_SECRET_KEY"] = "survivability-chaos"
    env["HOROVOD_METRICS_PUSH_SECS"] = "0.5"
    env["HOROVOD_RENDEZVOUS_EXTERNAL"] = f"127.0.0.1:{port}"
    train = tmp_path / "train_static.py"
    train.write_text(_STATIC_SURVIVABILITY_TRAIN)

    server = _spawn_external_server(port, jdir, env)
    launcher = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out_lines = []
    pump = threading.Thread(target=_pump, args=(launcher.stdout, out_lines),
                            daemon=True)
    pump.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            text = "".join(out_lines)
            if re.search(r"BATCH \d+ rank=0", text) and \
                    re.search(r"BATCH \d+ rank=1", text):
                break
            if launcher.poll() is not None:
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("ranks never reached training")
        server.kill()
        server.wait()
        time.sleep(1.0)
        server = _spawn_external_server(port, jdir, env)
        rc = launcher.wait(timeout=180)
    finally:
        if launcher.poll() is None:
            launcher.kill()
            launcher.wait()
        server.kill()
        server.wait()
    pump.join(timeout=10)
    stdout = "".join(out_lines)
    assert rc == 0, stdout[-3000:]
    params = dict(re.findall(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)", stdout))
    assert set(params) == {"0", "1"} and params["0"] == params["1"], \
        stdout[-2000:]
    # The launcher really went THROUGH the external server: its published
    # slot table (and both workers' leases) replay from the journal.
    from horovod_tpu.transport.store import LEASE_SCOPE, DurableMemoryStore
    store = DurableMemoryStore(str(jdir))
    try:
        assert sorted(store.keys("rank_and_size")) == \
            ["localhost:0", "localhost:1"]
        assert len(store.keys(LEASE_SCOPE)) == 2
    finally:
        store.close()
