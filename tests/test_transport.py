"""Transport-layer tests: KV stores, rendezvous HTTP server, TCP mesh.

The mesh tests run N ranks as threads inside one process sharing a
MemoryStore / live HTTP server — the transport doesn't care, which is the
point (reference analog: gloo connectFullMesh through any Store)."""

import threading

import pytest

from horovod_tpu.runner.rendezvous import RendezvousServer
from horovod_tpu.transport import HTTPStoreClient, MemoryStore, TcpMesh


def run_ranks(size, fn, timeout=30):
    """Run fn(rank) on `size` threads; re-raise the first failure."""
    errs = []
    results = [None] * size

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errs.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errs:
        raise errs[0][1]
    return results


def test_memory_store_wait():
    store = MemoryStore()
    store.set("s", "a", b"1")

    def delayed():
        store.set("s", "b", b"2")

    threading.Timer(0.05, delayed).start()
    got = store.wait("s", ["a", "b"], timeout=5)
    assert got == {"a": b"1", "b": b"2"}


def test_memory_store_wait_timeout():
    store = MemoryStore()
    with pytest.raises(TimeoutError):
        store.wait("s", ["missing"], timeout=0.1)


def test_http_store_roundtrip():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        client = HTTPStoreClient("127.0.0.1", port)
        assert client.get("scope", "k") is None
        client.set("scope", "k", b"\x00\x01binary\xff")
        assert client.get("scope", "k") == b"\x00\x01binary\xff"
        client.delete("scope", "k")
        assert client.get("scope", "k") is None
        client.delete("scope", "k")  # idempotent
        # scoping: same key name, different scope
        client.set("a", "k", b"1")
        client.set("b", "k", b"2")
        assert client.get("a", "k") == b"1"
        assert client.get("b", "k") == b"2"
    finally:
        server.stop()


def test_http_store_wait_across_clients():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        c1 = HTTPStoreClient("127.0.0.1", port)
        c2 = HTTPStoreClient("127.0.0.1", port)
        threading.Timer(0.05, lambda: c2.set("s", "x", b"hello")).start()
        got = c1.wait("s", ["x"], timeout=5)
        assert got["x"] == b"hello"
    finally:
        server.stop()


@pytest.mark.parametrize("size", [2, 4])
def test_tcp_mesh_pairwise(size):
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)
        try:
            # everyone sends its rank to everyone else
            for peer in range(size):
                if peer != rank:
                    mesh.send(peer, f"from-{rank}".encode())
            got = {}
            for peer in range(size):
                if peer != rank:
                    got[peer] = mesh.recv(peer).decode()
            return got
        finally:
            mesh.close()

    results = run_ranks(size, fn)
    for rank, got in enumerate(results):
        assert got == {p: f"from-{p}" for p in range(size) if p != rank}


def test_tcp_mesh_large_payload_ring():
    """Ring exchange with payloads larger than socket buffers must not
    deadlock (sendrecv overlaps directions)."""
    size = 3
    store = MemoryStore()
    payload = b"x" * (8 * 1024 * 1024)

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)
        try:
            nxt, prv = (rank + 1) % size, (rank - 1) % size
            got = mesh.sendrecv(nxt, payload, prv)
            assert got == payload
            return True
        finally:
            mesh.close()

    assert all(run_ranks(size, fn, timeout=60))


def test_tcp_mesh_size_one_noop():
    mesh = TcpMesh(0, 1, MemoryStore())
    with pytest.raises(Exception):
        mesh.send(1, b"nope")
    mesh.close()


def test_tcp_mesh_over_http_store():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        def fn(rank):
            client = HTTPStoreClient("127.0.0.1", port)
            mesh = TcpMesh(rank, 2, client, bind_addr="127.0.0.1",
                           advertise_addr="127.0.0.1", timeout=10)
            try:
                if rank == 0:
                    mesh.send(1, b"ping")
                    assert mesh.recv(1) == b"pong"
                else:
                    assert mesh.recv(0) == b"ping"
                    mesh.send(0, b"pong")
                return True
            finally:
                mesh.close()

        assert all(run_ranks(2, fn))
    finally:
        server.stop()
