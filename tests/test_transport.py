"""Transport-layer tests: KV stores, rendezvous HTTP server, TCP mesh.

The mesh tests run N ranks as threads inside one process sharing a
MemoryStore / live HTTP server — the transport doesn't care, which is the
point (reference analog: gloo connectFullMesh through any Store)."""

import threading

import pytest

from horovod_tpu.runner.rendezvous import RendezvousServer
from horovod_tpu.transport import HTTPStoreClient, MemoryStore, TcpMesh


pytestmark = pytest.mark.smoke


def run_ranks(size, fn, timeout=30):
    """Run fn(rank) on `size` threads; re-raise the first failure.

    The join budget is load-scaled like every other suite timeout: mesh
    bring-up with 5 s-per-socket accept/dial steps legitimately exceeds a
    fixed 30 s when the box is saturated (the "rank thread hung" flake,
    run-2 audit)."""
    from .helpers import _timeout_scale

    errs = []
    results = [None] * size

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errs.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    budget = timeout * _timeout_scale()
    for t in threads:
        t.join(budget)
        assert not t.is_alive(), "rank thread hung"
    if errs:
        raise errs[0][1]
    return results


def test_memory_store_wait():
    store = MemoryStore()
    store.set("s", "a", b"1")

    def delayed():
        store.set("s", "b", b"2")

    threading.Timer(0.05, delayed).start()
    got = store.wait("s", ["a", "b"], timeout=5)
    assert got == {"a": b"1", "b": b"2"}


def test_memory_store_wait_timeout():
    store = MemoryStore()
    with pytest.raises(TimeoutError):
        store.wait("s", ["missing"], timeout=0.1)


def test_http_store_roundtrip():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        client = HTTPStoreClient("127.0.0.1", port)
        assert client.get("scope", "k") is None
        client.set("scope", "k", b"\x00\x01binary\xff")
        assert client.get("scope", "k") == b"\x00\x01binary\xff"
        client.delete("scope", "k")
        assert client.get("scope", "k") is None
        client.delete("scope", "k")  # idempotent
        # scoping: same key name, different scope
        client.set("a", "k", b"1")
        client.set("b", "k", b"2")
        assert client.get("a", "k") == b"1"
        assert client.get("b", "k") == b"2"
    finally:
        server.stop()


def test_http_store_wait_across_clients():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        c1 = HTTPStoreClient("127.0.0.1", port)
        c2 = HTTPStoreClient("127.0.0.1", port)
        threading.Timer(0.05, lambda: c2.set("s", "x", b"hello")).start()
        got = c1.wait("s", ["x"], timeout=5)
        assert got["x"] == b"hello"
    finally:
        server.stop()


@pytest.mark.parametrize("size", [2, 4])
def test_tcp_mesh_pairwise(size):
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)
        try:
            # everyone sends its rank to everyone else
            for peer in range(size):
                if peer != rank:
                    mesh.send(peer, f"from-{rank}".encode())
            got = {}
            for peer in range(size):
                if peer != rank:
                    got[peer] = mesh.recv(peer).decode()
            return got
        finally:
            mesh.close()

    results = run_ranks(size, fn)
    for rank, got in enumerate(results):
        assert got == {p: f"from-{p}" for p in range(size) if p != rank}


def test_tcp_mesh_large_payload_ring():
    """Ring exchange with payloads larger than socket buffers must not
    deadlock (sendrecv overlaps directions)."""
    size = 3
    store = MemoryStore()
    payload = b"x" * (8 * 1024 * 1024)

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)
        try:
            nxt, prv = (rank + 1) % size, (rank - 1) % size
            got = mesh.sendrecv(nxt, payload, prv)
            assert got == payload
            return True
        finally:
            mesh.close()

    assert all(run_ranks(size, fn, timeout=60))


def test_tcp_mesh_size_one_noop():
    mesh = TcpMesh(0, 1, MemoryStore())
    with pytest.raises(Exception):
        mesh.send(1, b"nope")
    mesh.close()


def test_tcp_mesh_over_http_store():
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        def fn(rank):
            client = HTTPStoreClient("127.0.0.1", port)
            mesh = TcpMesh(rank, 2, client, bind_addr="127.0.0.1",
                           advertise_addr="127.0.0.1", timeout=10)
            try:
                if rank == 0:
                    mesh.send(1, b"ping")
                    assert mesh.recv(1) == b"pong"
                else:
                    assert mesh.recv(0) == b"ping"
                    mesh.send(0, b"pong")
                return True
            finally:
                mesh.close()

        assert all(run_ranks(2, fn))
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# service-plane security (reference network.py:50-85, secret.py:36)
# ---------------------------------------------------------------------------


def test_rendezvous_rejects_unsigned_requests(monkeypatch):
    """A server holding a job secret must 403 unsigned/missigned traffic —
    otherwise any LAN peer can rewrite the rank table."""
    import urllib.error
    import urllib.request

    from horovod_tpu.common import env as env_mod

    server = RendezvousServer(bind_addr="127.0.0.1", job_secret=b"k" * 32)
    port = server.start()
    try:
        # unsigned PUT → 403
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/s/a", data=b"evil", method="PUT")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 403
        # signed client (secret via env) → accepted
        monkeypatch.setenv(env_mod.HOROVOD_SECRET_KEY, "k" * 32)
        good = HTTPStoreClient("127.0.0.1", port)
        good.set("s", "a", b"ok")
        assert good.get("s", "a") == b"ok"
        # client with the WRONG key → 403 on read too
        monkeypatch.setenv(env_mod.HOROVOD_SECRET_KEY, "x" * 32)
        bad = HTTPStoreClient("127.0.0.1", port)
        with pytest.raises(urllib.error.HTTPError) as ei:
            bad.get("s", "a")
        assert ei.value.code == 403
    finally:
        server.stop()


def test_tcp_mesh_authenticated_hello(monkeypatch):
    """With a job secret, mesh peers HMAC their hellos; an interloper
    without the key cannot join (its connection is dropped, the real mesh
    still forms)."""
    import socket as socket_mod

    from horovod_tpu.common import env as env_mod

    monkeypatch.setenv(env_mod.HOROVOD_SECRET_KEY, "s" * 32)
    store = MemoryStore()

    def make(rank):
        return TcpMesh(rank, 2, store, scope="auth")

    def attack():
        # wait for rank 1's advertised endpoint, connect with a bogus hello
        try:
            import time
            deadline = time.monotonic() + 5
            val = None
            while val is None and time.monotonic() < deadline:
                val = store.get("auth", "1")
                time.sleep(0.01)
            host, port = val.decode().split(",")[0].rsplit(":", 1)
            s = socket_mod.create_connection((host, int(port)), timeout=5)
            s.sendall(b"HVMT" + b"\x00" * 8 + b"\x00" * 32)  # bad sig
        except OSError:
            pass  # mesh dropping us mid-write is the expected outcome

    threading.Thread(target=attack, daemon=True).start()
    meshes = run_ranks(2, make)
    meshes[0].send(1, b"payload")
    assert meshes[1].recv(0) == b"payload"
    for m in meshes:
        m.close()


# ---------------------------------------------------------------------------
# failure plane: dead-peer state, progress deadline, coordinated abort
# ---------------------------------------------------------------------------


def _mesh_pair(store=None, **kwargs):
    store = store or MemoryStore()
    meshes = [None, None]

    def make(rank):
        meshes[rank] = TcpMesh(rank, 2, store, bind_addr="127.0.0.1",
                               advertise_addr="127.0.0.1", timeout=10,
                               **kwargs)
        return meshes[rank]

    run_ranks(2, make)
    return meshes


def test_recv_progress_deadline_marks_peer_gone():
    """A recv with zero byte progress past the deadline raises
    PeerGoneError; every later call to that peer fails fast instead of
    re-blocking on the socket.  The deadline arms only after the peer's
    FIRST bytes — bring-up staggering must never count as death."""
    import time as time_mod

    from horovod_tpu.common.exceptions import PeerGoneError

    meshes = _mesh_pair(progress_deadline=0.6)
    try:
        # pre-first-frame: generously slow bring-up does not trip it
        threading.Timer(1.2, lambda: meshes[0].send(1, b"up")).start()
        assert meshes[1].recv(0) == b"up"
        # armed now: total silence past the deadline marks the peer gone
        with pytest.raises(PeerGoneError, match="no recv progress"):
            meshes[1].recv(0)
        t0 = time_mod.monotonic()
        with pytest.raises(PeerGoneError):
            meshes[1].recv(0)
        with pytest.raises(PeerGoneError):
            meshes[1].send(0, b"late")
        assert time_mod.monotonic() - t0 < 0.3, "dead peer did not fail fast"
    finally:
        for m in meshes:
            m.close()


def test_recv_progress_resets_deadline():
    """Slow-but-alive traffic (bytes trickling in) must never trip the
    deadline — only a total stop does."""
    import time as time_mod

    meshes = _mesh_pair(progress_deadline=2.0)
    payload = b"y" * (256 * 1024)

    def drip():
        # hand-frame the payload and drip it in chunks spaced at ~25% of
        # the deadline: every chunk resets the progress clock, and the
        # 1.5 s margin keeps scheduler hiccups on a loaded box from
        # tripping it (this in-process test has no retry gate)
        import struct as struct_mod
        import zlib as zlib_mod

        sock = meshes[0]._peers[1].sock
        frame = struct_mod.pack("<Q", len(payload)) \
            + struct_mod.pack("<I", zlib_mod.crc32(payload) & 0xFFFFFFFF) \
            + payload
        for off in range(0, len(frame), len(frame) // 4):
            sock.sendall(frame[off:off + len(frame) // 4])
            time_mod.sleep(0.5)

    t = threading.Thread(target=drip, daemon=True)
    t.start()
    try:
        assert meshes[1].recv(0) == payload
    finally:
        t.join(10)
        for m in meshes:
            m.close()


def test_send_progress_deadline_on_unread_peer():
    """A peer that is alive but never READS must not hang the sender:
    once the socket buffers fill, zero accepted bytes past the deadline
    raises PeerGoneError (TCP itself would block forever — the peer is
    healthy at the transport level, just wedged at the app level)."""
    from horovod_tpu.common.exceptions import PeerGoneError

    meshes = _mesh_pair(progress_deadline=0.8)
    big = b"z" * (8 * 1024 * 1024)
    try:
        with pytest.raises(PeerGoneError, match="no send progress"):
            for _ in range(64):  # fill both ends' socket buffers
                meshes[0].send(1, big)
    finally:
        for m in meshes:
            m.close()


def test_abort_frame_unblocks_recv_and_carries_reason():
    from horovod_tpu.common.exceptions import CoordinatedAbortError

    meshes = _mesh_pair()
    try:
        errs = []

        def blocked():
            try:
                meshes[0].recv(1)
            except CoordinatedAbortError as e:
                errs.append(e)

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        import time as time_mod

        time_mod.sleep(0.2)
        meshes[1].send_abort("stall shutdown: tensor g missing ranks [2]")
        t.join(5)
        assert not t.is_alive(), "abort frame did not unblock the recv"
        assert errs and errs[0].origin_rank == 1
        assert "stall shutdown" in errs[0].reason
    finally:
        for m in meshes:
            m.close()


def test_stale_epoch_abort_discarded():
    """An abort stamped with a pre-reset elastic epoch must be dropped at
    the transport layer — data frames behind it still deliver."""
    meshes = _mesh_pair(epoch=5)
    try:
        meshes[0].send_abort("old world", epoch=3)
        meshes[0]._abort = None  # broadcast marks the sender; clear to reuse
        meshes[0].send(1, b"fresh")
        assert meshes[1].recv(0) == b"fresh"
    finally:
        for m in meshes:
            m.close()


def test_wire_crc_catches_inflight_corruption():
    """An injected in-flight byte flip (``action=corrupt``: the sender's
    CRC covers the ORIGINAL payload) must surface as FrameCorruptError on
    the receiver — naming the peer, frame index, and both CRCs — and
    broadcast a coordinated abort back across the mesh."""
    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import (
        CoordinatedAbortError,
        FrameCorruptError,
    )

    meshes = _mesh_pair()
    try:
        meshes[0].send(1, b"clean")  # frame 1: intact
        assert meshes[1].recv(0) == b"clean"
        faults.configure("tcp.send:rank=0:nth=1:action=corrupt,2")
        meshes[0].send(1, b"poisoned-payload")
        with pytest.raises(FrameCorruptError) as exc:
            meshes[1].recv(0)
        err = exc.value
        assert err.peer == 0 and err.frame_index == 2
        assert err.expected_crc != err.got_crc
        assert "resync is impossible" in str(err)
        # the detector's abort reached the corrupting side
        with pytest.raises(CoordinatedAbortError, match="wire CRC"):
            meshes[0].recv(1)
        # and the detector itself fails fast now (peer marked dead)
        from horovod_tpu.common.exceptions import HorovodInternalError

        with pytest.raises(HorovodInternalError):
            meshes[1].recv(0)
    finally:
        faults.reset()
        for m in meshes:
            m.close()


def test_corrupt_injection_is_deterministic():
    """The same spec must flip the same bytes with the same masks — the
    reproducibility contract every other fault action keeps."""
    from horovod_tpu.common import faults

    outs = []
    for _ in range(2):
        faults.configure("tcp.send:nth=1:action=corrupt,3")
        v = faults.inject("tcp.send", rank=0, payload=b"x" * 64)
        outs.append((v.payload, v.wire_bytes()))
        faults.reset()
    assert outs[0] == outs[1]
    assert outs[0][0] != outs[0][1], "corrupt flipped nothing"


def test_truncate_fault_passes_crc_parse_layer_catches():
    """``action=truncate`` shortens the payload BEFORE framing: header
    and CRC agree with the short bytes, so the transport hands them up
    intact — and the defensive parse layer is what catches the damage
    (typed TruncatedFrameError, never a raw struct.error)."""
    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import TruncatedFrameError
    from horovod_tpu.core.messages import Request, RequestList

    wire = RequestList(
        requests=[Request(tensor_name="layer0/kernel.grad",
                          tensor_shape=[128, 784])]).to_bytes()
    meshes = _mesh_pair()
    try:
        faults.configure("tcp.send:rank=0:nth=1:action=truncate,5")
        meshes[0].send(1, wire)
        got = meshes[1].recv(0)  # transport-level: a clean short frame
        assert got == wire[:-5]
        with pytest.raises(TruncatedFrameError, match="truncated"):
            RequestList.from_bytes(got)
    finally:
        faults.reset()
        for m in meshes:
            m.close()


def test_corrupted_length_word_aborts_before_allocating():
    """The length word is NOT CRC-covered: a flipped high byte claims
    terabytes, and recv must treat it as a poisoned stream (coordinated
    abort) BEFORE trying to allocate the claimed buffer — the failure
    mode is MemoryError/OOM-kill otherwise, which no abort path survives."""
    import struct as struct_mod

    from horovod_tpu.common.exceptions import HorovodInternalError

    meshes = _mesh_pair()
    try:
        sock = meshes[0]._peers[1].sock
        # hand-frame a header claiming 1 TiB (as a corrupted-in-flight
        # length word would); CRC field and payload never matter — the
        # cap must trip first
        sock.sendall(struct_mod.pack("<Q", 1 << 40))
        with pytest.raises(HorovodInternalError,
                           match="corrupted length word") as exc:
            meshes[1].recv(0)
        assert "aborting before allocating" in str(exc.value)
        # the abort reached the sending side too
        from horovod_tpu.common.exceptions import CoordinatedAbortError

        with pytest.raises(CoordinatedAbortError):
            meshes[0].recv(1)
    finally:
        for m in meshes:
            m.close()


def test_wire_crc_disabled_by_knob(monkeypatch):
    """HOROVOD_WIRE_CRC=0 falls back to the bare 8-byte header — frames
    still deliver (both sides read the knob from the shared env)."""
    monkeypatch.setenv("HOROVOD_WIRE_CRC", "0")
    meshes = _mesh_pair()
    try:
        assert all(not m.wire_crc for m in meshes)
        meshes[0].send(1, b"unverified")
        assert meshes[1].recv(0) == b"unverified"
    finally:
        for m in meshes:
            m.close()


def test_sendrecv_helper_recovers_after_task_error():
    """Regression: a raising helper task must not wedge the _sr_queue — the
    next sendrecv still completes (previously a dead helper thread orphaned
    queued tasks and their completion events)."""
    meshes = _mesh_pair()
    try:
        meshes[0]._sr_submit(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        import time as time_mod

        time_mod.sleep(0.1)
        out = [None]

        def r0():
            out[0] = meshes[0].sendrecv(1, b"ring", 1)

        def r1():
            got = meshes[1].recv(0)
            assert got == b"ring"
            meshes[1].send(0, b"pong")

        t0, t1 = threading.Thread(target=r0), threading.Thread(target=r1)
        t0.start(); t1.start()
        t0.join(10); t1.join(10)
        assert not t0.is_alive() and not t1.is_alive(), "sendrecv wedged"
        assert out[0] == b"pong"
    finally:
        for m in meshes:
            m.close()


# ---------------------------------------------------------------------------
# zero-copy data plane: view sends, recv_into, incremental CRC
# ---------------------------------------------------------------------------


def test_send_accepts_numpy_views_and_recv_into_lands_in_place():
    """The zero-copy pair: a numpy slice goes out as a view (no tobytes)
    and the payload lands directly in a caller buffer (no fresh bytes),
    with the default-on wire CRC verified incrementally over the
    destination."""
    import numpy as np

    meshes = _mesh_pair()
    try:
        src = np.arange(64, dtype=np.float32)
        dest = np.zeros(16, dtype=np.float32)
        meshes[0].send(1, memoryview(src[8:24]).cast("B"))
        got = meshes[1].recv_into(0, memoryview(dest).cast("B"))
        assert got == 64
        assert np.array_equal(dest, src[8:24])
    finally:
        for m in meshes:
            m.close()


def test_sendrecv_into_concurrent_exchange():
    import numpy as np

    meshes = _mesh_pair()
    payloads = [np.full(1024, float(r), np.float32) for r in range(2)]
    outs = [np.empty(1024, np.float32) for _ in range(2)]
    results = [None, None]

    def fn(rank):
        results[rank] = meshes[rank].sendrecv_into(
            1 - rank, memoryview(payloads[rank]).cast("B"),
            1 - rank, memoryview(outs[rank]).cast("B"))

    try:
        threads = [threading.Thread(target=fn, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
            assert not t.is_alive(), "sendrecv_into wedged"
        for rank in range(2):
            assert results[rank] == 4096
            assert np.array_equal(outs[rank], payloads[1 - rank])
    finally:
        for m in meshes:
            m.close()


def test_recv_into_size_mismatch_poisons_stream():
    """A data frame whose size disagrees with the caller's negotiated
    destination is positional desync in the making (a truncating fault, a
    desynced negotiation): the stream must be poisoned — peer dead,
    coordinated abort broadcast — exactly like a CRC failure."""
    from horovod_tpu.common.exceptions import (
        CoordinatedAbortError,
        HorovodInternalError,
    )

    meshes = _mesh_pair()
    try:
        meshes[0].send(1, b"x" * 10)
        dest = bytearray(16)
        with pytest.raises(HorovodInternalError, match="misframed"):
            meshes[1].recv_into(0, memoryview(dest))
        # the abort reached the sending side
        with pytest.raises(CoordinatedAbortError):
            meshes[0].recv(1)
    finally:
        for m in meshes:
            m.close()


def test_recv_into_wire_crc_catches_inflight_corruption():
    """The incremental CRC over the recv_into destination must catch an
    injected in-flight flip exactly like the materializing recv path —
    typed FrameCorruptError, peer marked dead, abort broadcast back."""
    import numpy as np

    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import (
        CoordinatedAbortError,
        FrameCorruptError,
    )

    meshes = _mesh_pair()
    try:
        src = np.ones(256, np.float32)
        dest = np.empty(256, np.float32)
        meshes[0].send(1, memoryview(src).cast("B"))
        assert meshes[1].recv_into(0, memoryview(dest).cast("B")) == 1024
        faults.configure("tcp.send:rank=0:nth=1:action=corrupt,2")
        meshes[0].send(1, memoryview(src).cast("B"))
        with pytest.raises(FrameCorruptError) as exc:
            meshes[1].recv_into(0, memoryview(dest).cast("B"))
        assert exc.value.peer == 0 and exc.value.frame_index == 2
        with pytest.raises(CoordinatedAbortError, match="wire CRC"):
            meshes[0].recv(1)
    finally:
        faults.reset()
        for m in meshes:
            m.close()


def test_recv_into_truncate_fault_caught_as_misframe():
    """``action=truncate`` on the view path: header and CRC agree with
    the short payload, so the CRC passes — and the size check against the
    negotiated destination is what catches it (poison + abort, never a
    silent short read into the staging buffer)."""
    import numpy as np

    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import HorovodInternalError

    meshes = _mesh_pair()
    try:
        faults.configure("tcp.send:rank=0:nth=1:action=truncate,4")
        src = np.ones(64, np.float32)
        meshes[0].send(1, memoryview(src).cast("B"))
        dest = np.empty(64, np.float32)
        with pytest.raises(HorovodInternalError, match="misframed"):
            meshes[1].recv_into(0, memoryview(dest).cast("B"))
    finally:
        faults.reset()
        for m in meshes:
            m.close()


def test_abort_frame_interleaves_with_recv_into():
    """A control frame (coordinated abort) arriving while a recv_into is
    posted must surface as CoordinatedAbortError on the view path too."""
    from horovod_tpu.common.exceptions import CoordinatedAbortError

    meshes = _mesh_pair()
    try:
        errs = []

        def blocked():
            try:
                meshes[0].recv_into(1, memoryview(bytearray(128)))
            except CoordinatedAbortError as e:
                errs.append(e)

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        import time as time_mod

        time_mod.sleep(0.2)
        meshes[1].send_abort("pipelined step abort")
        t.join(5)
        assert not t.is_alive(), "abort did not unblock recv_into"
        assert errs and errs[0].origin_rank == 1
    finally:
        for m in meshes:
            m.close()


def test_recv_into_rejects_readonly_destination():
    meshes = _mesh_pair()
    try:
        with pytest.raises(ValueError, match="writable"):
            meshes[1].recv_into(0, memoryview(b"readonly"))
    finally:
        for m in meshes:
            m.close()


def test_tcp_mesh_multi_addr_fallback():
    """Dialers fall through dead advertised addresses to a live one
    (NIC-negotiation role, reference driver_service.py:162-194).  The
    dialing rank sees rank 0's advertisement with an unroutable first
    entry — as a multi-homed host with a dead NIC would publish."""
    store = MemoryStore()

    class DeadFirstStore(MemoryStore):
        """Rank 1's view: rank 0 advertises a dead endpoint first."""

        def get(self, scope, key):
            val = store.get(scope, key)
            if val is not None and scope == "nic" and key == "0":
                # 203.0.113.0/24 is TEST-NET-3: guaranteed unroutable.
                return b"203.0.113.1:59999," + val
            return val

        def set(self, scope, key, value):
            store.set(scope, key, value)

    dead_first = DeadFirstStore()

    def make(rank):
        if rank == 0:
            return TcpMesh(0, 2, store, scope="nic",
                           advertise_addr="127.0.0.1")
        return TcpMesh(1, 2, dead_first, scope="nic",
                       advertise_addr="127.0.0.1")

    res = run_ranks(2, make, timeout=60)
    res[1].send(0, b"hi")
    assert res[0].recv(1) == b"hi"
    for m in res:
        m.close()


def test_tcp_mesh_dead_first_candidate_races_fast():
    """Multi-addr dialing probes candidates CONCURRENTLY: a dead first
    candidate (blackhole address) must not serialize a connect timeout in
    front of the live one (reference probe-and-intersect role)."""
    import time as time_mod

    store = MemoryStore()

    class DeadFirstStore(MemoryStore):
        """Prepends an unroutable candidate to every advertisement."""

        def set(self, scope, key, value):
            if scope.startswith("tcp") or scope == "tcp":
                spec = value.decode()
                port = spec.rsplit(":", 1)[1]
                value = f"10.255.255.1:{port},{spec}".encode()
            super().set(scope, key, value)

    dead_store = DeadFirstStore()

    def fn(rank):
        t0 = time_mod.monotonic()
        mesh = TcpMesh(rank, 2, dead_store, bind_addr="127.0.0.1",
                       timeout=20)
        dt = time_mod.monotonic() - t0
        try:
            mesh.send(1 - rank, b"hi")
            assert mesh.recv(1 - rank) == b"hi"
        finally:
            mesh.close()
        return dt

    times = run_ranks(2, fn)
    # serial probing would eat the ~5s connect timeout on the dead
    # candidate first; the concurrent race finishes in well under that
    assert max(times) < 4.0, times
