"""Hybrid mode: the eager runtime (broadcast/allreduce over the mesh of
PROCESSES) composed with an in-process SPMD device mesh — the deployment
shape of real TPU jobs (data-parallel across hosts via eager collectives,
model sharding across local chips via pjit).  VERDICT weak #5: round 1
never drove both in one process."""

import numpy as np

from .helpers import run_distributed


def test_eager_broadcast_into_jit_spmd_step():
    out = run_distributed(2, """
import os
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Each PROCESS owns a 4-device virtual mesh (2 procs x 4 devices: the
# per-host chips of a 2-host TPU job).
devs = jax.devices()[:4]
mesh = Mesh(np.array(devs), ("model",))

# 1. eager broadcast: rank 0's params are canonical
w = np.arange(8, dtype=np.float32) * (1 if rank == 0 else 99)
w = np.asarray(hvd.broadcast(w, root_rank=0, name="w"))
assert np.allclose(w, np.arange(8)), w

# 2. jit SPMD compute over the local mesh: shard w across devices
sharding = NamedSharding(mesh, P("model"))
w_sharded = jax.device_put(jnp.asarray(w), sharding)

@jax.jit
def local_grad(w, x):
    return jax.grad(lambda w: jnp.sum((w * x) ** 2))(w)

x = jnp.ones(8) * (rank + 1)
g = local_grad(w_sharded, x)
assert len(g.sharding.device_set) == 4  # stayed sharded through jit

# 3. eager allreduce of the SPMD result across processes
g_sum = np.asarray(hvd.allreduce(np.asarray(g), op=hvd.Sum, name="g"))
exp = sum(2 * np.arange(8) * (r + 1) ** 2 for r in range(2))
assert np.allclose(g_sum, exp), (g_sum, exp)
print("HYBRID_OK", rank, flush=True)
""", timeout=240,
                          extra_env={"XLA_FLAGS":
                                     "--xla_force_host_platform_device_count=4"})
    for r, o in enumerate(out):
        assert f"HYBRID_OK {r}" in o
