"""Per-framework elastic state: TorchState / ElasticSampler /
TensorFlowKerasState.

Mirrors the reference's ``test/single/test_torch_elastic.py`` (state
save/restore/sync, sampler resharding that skips processed indices) plus a
2-process sync lane under the real launcher harness.
"""

from __future__ import annotations

import textwrap

import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.frameworks.torch.elastic import (  # noqa: E402
    ElasticSampler,
    TorchState,
)
from tests.helpers import run_distributed  # noqa: E402


@pytest.fixture
def single_rank(monkeypatch):
    """Pretend hvd is initialized with rank 0 / size 1 for in-process
    tests (reference runs these under a real np=1 launcher)."""
    import horovod_tpu.frameworks.torch as hvd_torch

    monkeypatch.setattr(hvd_torch, "rank", lambda: 0)
    monkeypatch.setattr(hvd_torch, "size", lambda: 1)


class TestElasticSampler:
    def test_full_epoch_partition(self, single_rank):
        data = list(range(10))
        s = ElasticSampler(data, shuffle=False)
        assert len(s) == 10
        assert list(iter(s)) == data

    def test_two_rank_shards_are_disjoint_and_cover(self, monkeypatch):
        import horovod_tpu.frameworks.torch as hvd_torch

        monkeypatch.setattr(hvd_torch, "size", lambda: 2)
        data = list(range(10))
        shards = []
        for r in range(2):
            monkeypatch.setattr(hvd_torch, "rank", lambda r=r: r)
            s = ElasticSampler(data, shuffle=False)
            assert len(s) == 5
            shards.append(list(iter(s)))
        assert sorted(shards[0] + shards[1]) == data

    def test_record_and_reshard_skips_processed(self, monkeypatch):
        """The headline semantic (reference ``sampler.py:24-131``): after
        processing some batches on 2 ranks, a reset to 1 rank hands out
        exactly the unprocessed remainder."""
        import horovod_tpu.frameworks.torch as hvd_torch

        monkeypatch.setattr(hvd_torch, "rank", lambda: 0)
        monkeypatch.setattr(hvd_torch, "size", lambda: 2)
        data = list(range(12))
        s = ElasticSampler(data, shuffle=False)
        it = list(iter(s))
        # process the first two batches of size 2 on this rank
        s.record_batch(0, 2)
        s.record_batch(1, 2)
        processed = set(it[:4])
        assert s.processed_indices == processed

        # world shrinks to 1; simulate the sync union (only this rank's
        # record survives) then reshard
        monkeypatch.setattr(hvd_torch, "size", lambda: 1)
        s.reset()
        remaining = list(iter(s))
        assert set(remaining) == set(data) - processed
        assert len(s) == len(data) - len(processed)

    def test_set_epoch_clears_processed(self, single_rank):
        s = ElasticSampler(list(range(6)), shuffle=True, seed=3)
        s.record_indices({0, 1, 2})
        s.set_epoch(1)
        assert s.processed_indices == set()
        assert len(s) == 6

    def test_shuffle_is_deterministic_across_ranks(self, monkeypatch):
        import horovod_tpu.frameworks.torch as hvd_torch

        monkeypatch.setattr(hvd_torch, "size", lambda: 2)
        data = list(range(20))
        orders = []
        for r in range(2):
            monkeypatch.setattr(hvd_torch, "rank", lambda r=r: r)
            s = ElasticSampler(data, shuffle=True, seed=7)
            s.set_epoch(2)
            orders.append(list(iter(s)))
        # same (seed, epoch) ⇒ same global permutation ⇒ disjoint shards
        assert not (set(orders[0]) & set(orders[1]))

    def test_state_dict_roundtrip(self, single_rank):
        s = ElasticSampler(list(range(8)), shuffle=False)
        s.record_indices({1, 5})
        s.epoch = 3
        blob = s.state_dict()
        s2 = ElasticSampler(list(range(8)), shuffle=False)
        s2.load_state_dict(blob)
        assert s2.epoch == 3
        assert s2.processed_indices == {1, 5}
        assert set(iter(s2)) == set(range(8)) - {1, 5}


class TestTorchStateSingle:
    def _model_and_opt(self):
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        return model, opt

    def test_save_restore_model_and_optimizer(self, single_rank):
        model, opt = self._model_and_opt()
        state = TorchState(model=model, optimizer=opt, batch=0, epoch=0)

        before = {k: v.clone() for k, v in model.state_dict().items()}
        # take a training step (mutates weights + momentum buffers)
        loss = model(torch.ones(3, 4)).sum()
        loss.backward()
        opt.step()
        state.batch = 7
        assert any((before[k] != v).any()
                   for k, v in model.state_dict().items())

        state.restore()
        for k, v in model.state_dict().items():
            assert torch.equal(before[k], v)
        # plain attributes roll back too
        assert state.batch == 0

    def test_commit_advances_snapshot(self, single_rank):
        model, opt = self._model_and_opt()
        state = TorchState(model=model, optimizer=opt, batch=0)
        loss = model(torch.ones(3, 4)).sum()
        loss.backward()
        opt.step()
        after = {k: v.clone() for k, v in model.state_dict().items()}
        state.batch = 3
        state.commit()

        # new mutation, then restore → lands on the committed point
        opt.zero_grad()
        loss = model(torch.ones(3, 4)).sum()
        loss.backward()
        opt.step()
        state.restore()
        for k, v in model.state_dict().items():
            assert torch.equal(after[k], v)
        assert state.batch == 3

    def test_reassign_handled_attribute(self, single_rank):
        model, opt = self._model_and_opt()
        state = TorchState(model=model, optimizer=opt)
        new_model = torch.nn.Linear(4, 2)
        state.model = new_model
        assert state._handlers["model"].value is new_model
        # restore now targets the new model's snapshot
        snap = {k: v.clone() for k, v in new_model.state_dict().items()}
        with torch.no_grad():
            new_model.weight.add_(1.0)
        state.restore()
        for k, v in new_model.state_dict().items():
            assert torch.equal(snap[k], v)

    def test_sampler_in_state_roundtrip(self, single_rank):
        model, opt = self._model_and_opt()
        sampler = ElasticSampler(list(range(10)), shuffle=False)
        state = TorchState(model=model, optimizer=opt, sampler=sampler)
        list(iter(sampler))
        sampler.record_batch(0, 4)
        state.commit()
        sampler.record_batch(1, 4)
        state.restore()
        assert sampler.processed_indices == set(range(4))


def test_torch_state_sync_two_ranks():
    """Under the real launcher: rank-dependent weights + processed sets;
    sync() must equalize on rank-0 weights and union the indices."""
    body = textwrap.dedent("""
    import torch
    from horovod_tpu.frameworks.torch.elastic import ElasticSampler, TorchState

    torch.manual_seed(rank)
    model = torch.nn.Linear(3, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    sampler = ElasticSampler(list(range(8)), shuffle=False)
    list(iter(sampler))
    sampler.record_indices({rank, rank + 4})
    state = TorchState(model=model, optimizer=opt, sampler=sampler, batch=rank)

    state.sync()

    # model weights equal rank 0's
    torch.manual_seed(0)
    ref = torch.nn.Linear(3, 2)
    for a, b in zip(model.parameters(), ref.parameters()):
        assert torch.allclose(a.data, b.data), (rank, a, b)
    # processed indices are the union of all ranks'
    assert sampler.processed_indices == {0, 1, 4, 5}, sampler.processed_indices
    # plain attrs broadcast from rank 0
    assert state.batch == 0
    print("SYNC_OK", rank)
    """)
    outs = run_distributed(2, body, timeout=180)
    for out in outs:
        assert "SYNC_OK" in out


def test_tensorflow_keras_state_save_restore():
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.frameworks.tensorflow.elastic import TensorFlowKerasState

    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(2, input_shape=(3,))])
    opt = tf.keras.optimizers.SGD(learning_rate=0.1)
    model.compile(optimizer=opt, loss="mse")
    model.build((None, 3))

    state = TensorFlowKerasState(model, optimizer=opt, batch=0, epoch=0)
    before = [v.numpy().copy() for v in model.variables]

    model.variables[0].assign_add(tf.ones_like(model.variables[0]))
    state.epoch = 5
    state.restore()

    import numpy as np
    for b, v in zip(before, model.variables):
        assert np.allclose(b, v.numpy())
    assert state.epoch == 0


def test_keras_elastic_callbacks_exist():
    pytest.importorskip("tensorflow")
    from horovod_tpu.frameworks.keras import elastic as kel

    class Box:
        epoch = 0
        batch = 0

        def commit(self):
            self.committed = True

    state = Box()
    cbs = [kel.CommitStateCallback(state, batches_per_commit=2),
           kel.UpdateBatchStateCallback(state),
           kel.UpdateEpochStateCallback(state)]
    for cb in cbs:
        assert hasattr(cb, "on_epoch_end")
    cbs[0].on_batch_end(0)
    cbs[0].on_batch_end(1)
    assert getattr(state, "committed", False)
    cbs[2].on_epoch_end(0)
    assert state.epoch == 1
