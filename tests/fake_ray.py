"""A minimal in-repo `ray` stand-in for testing horovod_tpu.ray.

Implements just the surface the integration uses — ``ray.remote(cls)``,
``.options().remote()`` actor construction, ``actor.method.remote()`` →
ref, ``ray.get``, ``ray.kill``, ``ray.nodes`` — with REAL subprocess
actors (spawn context) so hvd.init() runs in isolated processes exactly
like under real Ray.  Tests inject it as ``sys.modules['ray']``.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
from concurrent.futures import Future
from typing import Any, Dict, List

import cloudpickle

_ctx = mp.get_context("spawn")

# Configurable cluster state for ray.nodes()
NODES: List[Dict[str, Any]] = []


def _actor_server(conn, cls_blob):
    cls, args, kwargs = cloudpickle.loads(cls_blob)
    inst = cls(*args, **kwargs)
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        method, a, kw = cloudpickle.loads(msg)
        if method == "__stop__":
            return
        try:
            result = ("ok", getattr(inst, method)(*a, **kw))
        except BaseException as e:  # noqa: BLE001 — marshalled to caller
            result = ("err", repr(e))
        try:
            conn.send_bytes(cloudpickle.dumps(result))
        except (OSError, BrokenPipeError):
            return


class ObjectRef:
    def __init__(self, future: Future):
        self.future = future


class _MethodProxy:
    def __init__(self, actor: "ActorHandle", name: str):
        self._actor = actor
        self._name = name

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._actor._call(self._name, args, kwargs)


class ActorHandle:
    def __init__(self, cls, args, kwargs):
        parent, child = _ctx.Pipe()
        self._conn = parent
        self._proc = _ctx.Process(
            target=_actor_server,
            args=(child, cloudpickle.dumps((cls, args, kwargs))),
            daemon=True)
        self._proc.start()
        child.close()
        self._queue: "queue.Queue" = queue.Queue()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()
        self._methods = {name for name in dir(cls)
                         if not name.startswith("_")}

    def _pump_loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            payload, future = item
            try:
                self._conn.send_bytes(payload)
                status, value = cloudpickle.loads(self._conn.recv_bytes())
            except BaseException as e:  # noqa: BLE001 — actor died
                future.set_exception(RuntimeError(f"actor died: {e}"))
                continue
            if status == "ok":
                future.set_result(value)
            else:
                future.set_exception(RuntimeError(value))

    def _call(self, method, args, kwargs) -> ObjectRef:
        future: Future = Future()
        self._queue.put((cloudpickle.dumps((method, args, kwargs)), future))
        return ObjectRef(future)

    def __getattr__(self, name):
        if name in self.__dict__.get("_methods", ()):
            return _MethodProxy(self, name)
        raise AttributeError(name)

    def _kill(self):
        self._queue.put(None)
        self._proc.terminate()
        self._conn.close()


class RemoteClass:
    def __init__(self, cls):
        self._cls = cls

    def options(self, **_kwargs) -> "RemoteClass":
        return self

    def remote(self, *args, **kwargs) -> ActorHandle:
        return ActorHandle(self._cls, args, kwargs)


def remote(cls) -> RemoteClass:
    return RemoteClass(cls)


def get(refs, timeout=None):
    if isinstance(refs, ObjectRef):
        return refs.future.result(timeout)
    return [r.future.result(timeout) for r in refs]


def kill(actor: ActorHandle) -> None:
    actor._kill()


def nodes() -> List[Dict[str, Any]]:
    return list(NODES)
