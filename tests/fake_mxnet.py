"""Minimal mxnet stand-in with an ASYNC dependency engine.

The real binding target (reference ``mxnet/mpi_ops.cc:182-191``) pushes
collectives into MXNet's engine with read/write variable dependencies so
they serialize with surrounding NDArray ops.  Our bridge instead relies on
the two sync points every NDArray exposes — ``asnumpy()`` waits for pending
writes, in-place assignment enqueues a write — so ordering holds under ANY
legal engine schedule.  This fake proves that against an actually-async
engine: every NDArray op is deferred onto a single worker thread (FIFO is
a conservative legal schedule of the dependency engine) and only
``asnumpy``/``wait_to_read`` synchronize.  A bridge that assumed eager
execution would read stale buffers here.

Injected via ``sys.modules["mxnet"]`` by tests; shaped like the small
slice of the mxnet API the binding touches (``mx.nd.array/ones/zeros``,
NDArray arithmetic, ``context``).
"""

from __future__ import annotations

import queue
import threading

import numpy as np


class _Engine:
    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fake-mxnet-engine")
        self._thread.start()

    def _loop(self):
        while True:
            fn = self._q.get()
            try:
                fn()
            finally:
                self._q.task_done()

    def push(self, fn):
        self._q.put(fn)

    def wait_all(self):
        self._q.join()


ENGINE = _Engine()


class Context:
    def __init__(self, kind: str = "cpu", index: int = 0):
        self.kind, self.index = kind, index

    def __repr__(self):
        return f"{self.kind}({self.index})"


class NDArray:
    def __init__(self, data, ctx: Context | None = None):
        self._data = np.array(data, dtype=np.float32, copy=True)
        self.context = ctx or Context()

    # -- sync points (the only ones the bridge may rely on) -------------
    def wait_to_read(self):
        ENGINE.wait_all()

    def asnumpy(self) -> np.ndarray:
        self.wait_to_read()
        return self._data.copy()

    # -- deferred ops ----------------------------------------------------
    @property
    def shape(self):
        ENGINE.wait_all()
        return self._data.shape

    @property
    def dtype(self):
        return self._data.dtype

    def __setitem__(self, key, value):
        src = value._data if isinstance(value, NDArray) else np.asarray(value)

        def run():
            if isinstance(value, NDArray):
                self._data[key] = value._data  # read dep resolved in-order
            else:
                self._data[key] = src

        ENGINE.push(run)

    def _inplace(self, other, op):
        o = other

        def run():
            rhs = o._data if isinstance(o, NDArray) else o
            op(self._data, rhs)

        ENGINE.push(run)
        return self

    def __imul__(self, other):
        return self._inplace(other, lambda a, b: a.__imul__(b))

    def __iadd__(self, other):
        return self._inplace(other, lambda a, b: a.__iadd__(b))

    def __isub__(self, other):
        return self._inplace(other, lambda a, b: a.__isub__(b))

    def _binary(self, other, op):
        out = NDArray(np.zeros_like(self._data), self.context)
        o = other

        def run():
            rhs = o._data if isinstance(o, NDArray) else o
            out._data = op(self._data, rhs)

        ENGINE.push(run)
        return out

    def __mul__(self, other):
        return self._binary(other, lambda a, b: a * b)

    def __add__(self, other):
        return self._binary(other, lambda a, b: a + b)

    def sum(self):
        return self._binary(0.0, lambda a, _: np.asarray(a.sum()))


class _ND:
    NDArray = NDArray

    @staticmethod
    def array(data, ctx=None, **_kw):
        return NDArray(np.asarray(data), ctx)

    @staticmethod
    def ones(shape, ctx=None, **_kw):
        return NDArray(np.ones(shape, np.float32), ctx)

    @staticmethod
    def zeros(shape, ctx=None, **_kw):
        return NDArray(np.zeros(shape, np.float32), ctx)


nd = _ND()
cpu = Context
__version__ = "0.0-fake-async"
