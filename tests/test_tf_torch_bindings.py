"""TensorFlow / PyTorch / Keras binding tests under real worker processes.

Mirrors the reference's parallel tier (``test/parallel/test_tensorflow.py``,
``test_torch.py``): same test bodies for collectives, gradient wrappers and
parameter broadcast, executed with a 2-process launcher.
"""

import pytest

from .helpers import run_distributed

tf = pytest.importorskip("tensorflow")
torch = pytest.importorskip("torch")


def test_tf_collectives_and_tape():
    out = run_distributed(2, """
import os
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import tensorflow as tf
import horovod_tpu.tensorflow as htf

t = tf.constant([1.0, 2.0]) * (rank + 1)
o = htf.allreduce(t, op=htf.Sum, name="t")
assert np.allclose(o.numpy(), [3.0, 6.0]), o

# averaging gradient tape
w = tf.Variable([[1.0 + rank]])
with htf.DistributedGradientTape(tf.GradientTape()) as tape:
    loss = tf.reduce_sum(w * w) * (rank + 1)
g = tape.gradient(loss, [w])
exp = np.mean([2 * (1.0 + r) * (r + 1) for r in range(size)])
assert np.allclose(g[0].numpy(), exp), (g[0].numpy(), exp)

# broadcast_variables handles scalars and arrays
v0 = tf.Variable(float(rank + 5))
v1 = tf.Variable(np.full((2, 2), float(rank), np.float32))
htf.broadcast_variables([v0, v1], root_rank=1)
assert np.allclose(v0.numpy(), 6.0) and np.allclose(v1.numpy(), 1.0)

# IndexedSlices take the allgather path
iv = tf.IndexedSlices(tf.ones([2, 3]) * (rank + 1),
                      tf.constant([0, 1]), tf.constant([4, 3]))
red = htf.allreduce(iv, op=htf.Average)
assert red.values.shape[0] == 2 * size
print("TFBIND_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TFBIND_OK {r}" in o


def test_tf_distributed_optimizer_keras_compile():
    """The dynamic-subclass optimizer passes Keras compile() validation and
    keeps ranks in lockstep through fit()."""
    out = run_distributed(2, """
import os
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import keras
import tensorflow as tf
import horovod_tpu.tensorflow as htf
import horovod_tpu.keras as hk

model = keras.Sequential([keras.layers.Input((4,)), keras.layers.Dense(2)])
opt = htf.DistributedOptimizer(keras.optimizers.SGD(0.1))
model.compile(optimizer=opt, loss="mse", run_eagerly=True)
rng = np.random.RandomState(rank)
x = rng.randn(32, 4).astype("float32")
y = np.zeros((32, 2), "float32")
model.fit(x, y, epochs=1, batch_size=16, verbose=0,
          callbacks=[hk.BroadcastGlobalVariablesCallback(0)])
w = model.get_weights()[0]
g = np.asarray(htf.allgather(tf.constant(w.ravel()[None]), name="wchk"))
assert np.allclose(g[0], g[1], atol=1e-6), "ranks diverged"
print("TFOPT_OK", rank, flush=True)
""", timeout=300)
    for r, o in enumerate(out):
        assert f"TFOPT_OK {r}" in o


def test_torch_wfbp_optimizer_and_state_broadcast():
    out = run_distributed(2, """
import torch
import torch.nn.functional as F
import horovod_tpu.torch as ht

torch.manual_seed(42 + rank)
model = torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.ReLU(),
                            torch.nn.Linear(16, 4))
opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
opt = ht.DistributedOptimizer(opt,
                              named_parameters=model.named_parameters())
ht.broadcast_parameters(model.state_dict(), root_rank=0)
ht.broadcast_optimizer_state(opt, root_rank=0)

x = torch.randn(16, 8) + rank
y = torch.randint(0, 4, (16,))
for _ in range(3):
    opt.zero_grad()
    F.cross_entropy(model(x), y).backward()
    opt.step()

p = list(model.parameters())[0].detach().numpy().ravel()[:8]
g = ht.allgather(torch.from_numpy(p[None, :]), name="chk").numpy()
assert np.allclose(g[0], g[1], atol=1e-6), "WFBP ranks diverged"

# zero_grad with outstanding handles raises (reference optimizer.py:202)
opt.zero_grad()
loss = F.cross_entropy(model(x), y)
loss.backward()           # hooks fire -> handles outstanding
try:
    opt.zero_grad()
    raise SystemExit("expected HorovodInternalError")
except Exception as e:
    assert "outstanding" in str(e), e
opt.step()
print("TORCHOPT_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TORCHOPT_OK {r}" in o


def test_torch_backward_passes_per_step():
    """Gradient accumulation: allreduce fires every Nth backward, hooks do
    not raise on intermediate passes."""
    out = run_distributed(2, """
import torch
import torch.nn.functional as F
import horovod_tpu.torch as ht

torch.manual_seed(7)
model = torch.nn.Linear(4, 2)
opt = torch.optim.SGD(model.parameters(), lr=0.1)
opt = ht.DistributedOptimizer(opt, named_parameters=model.named_parameters(),
                              backward_passes_per_step=2)
ht.broadcast_parameters(model.state_dict(), root_rank=0)
x = torch.randn(8, 4)
y = torch.zeros(8, 2)
for _ in range(2):   # two backwards per step
    F.mse_loss(model(x), y).backward()
opt.step()
opt.zero_grad()
p = list(model.parameters())[0].detach().numpy().ravel()
g = ht.allgather(torch.from_numpy(p[None, :]), name="chk").numpy()
assert np.allclose(g[0], g[1], atol=1e-6)
print("BPPS_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"BPPS_OK {r}" in o


def test_torch_inplace_and_alltoall():
    out = run_distributed(2, """
import torch
import horovod_tpu.torch as ht

t = torch.ones(4) * (rank + 1)
ht.allreduce_(t, op=ht.Sum, name="ip")
assert np.allclose(t.numpy(), 3.0)

a = torch.arange(4, dtype=torch.float32) + 10 * rank
o = ht.alltoall(a, name="a2a")
exp = np.concatenate([np.arange(2) + 2 * rank,
                      np.arange(2) + 2 * rank + 10])
assert np.allclose(o.numpy(), exp), (o, exp)
print("TINPLACE_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TINPLACE_OK {r}" in o


def test_torch_public_synchronize_honors_inplace():
    """synchronize(h) on an in-place handle must mutate the submitted
    tensor (reference mpi_ops.py: in-place op's output buffer IS the
    input) and drop the target-table entry."""
    out = run_distributed(2, """
import torch
import horovod_tpu.torch as ht

t = torch.ones(3) * (rank + 1)
h = ht.allreduce_async_(t, op=ht.Sum, name="ip2")
res = ht.synchronize(h)          # public, non-underscore spelling
assert np.allclose(t.numpy(), 3.0), t
assert res is t
from horovod_tpu.frameworks.torch import _INPLACE_TARGETS
assert not _INPLACE_TARGETS, _INPLACE_TARGETS
print("TSYNC_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TSYNC_OK {r}" in o


def test_tf_graph_mode_collectives():
    """Collectives inside @tf.function (symbolic tensors) run via
    tf.py_function (reference: graph mode via the custom op,
    mpi_ops.cc:371-425)."""
    out = run_distributed(2, """
import os
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import tensorflow as tf
import horovod_tpu.tensorflow as htf

@tf.function
def step(x):
    return htf.allreduce(x, op=htf.Sum, name="g1")

for i in range(3):  # repeated executions reuse the traced wire name
    o = step(tf.constant([1.0, 2.0]) * (rank + 1) * (i + 1))
    assert np.allclose(o.numpy(), np.array([3.0, 6.0]) * (i + 1)), o

@tf.function
def gstep(x):
    return htf.allgather(x, name="g2"), htf.broadcast(x, 0, name="g3")

g, b = gstep(tf.constant([[float(rank)]]))
assert g.shape == (2, 1) and np.allclose(g.numpy().ravel(), [0.0, 1.0])
assert np.allclose(b.numpy(), 0.0)
print("TFGRAPH_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TFGRAPH_OK {r}" in o


def test_tf_optimizer_bpps_graph_mode():
    """backward_passes_per_step accumulation must work when apply_gradients
    is traced into a tf.function (model.fit default): a Python counter
    would bake the skip-branch into the graph and never update weights."""
    out = run_distributed(2, """
import os
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import tensorflow as tf
import horovod_tpu.tensorflow as htf

opt = htf.DistributedOptimizer(
    tf.keras.optimizers.SGD(learning_rate=1.0), backward_passes_per_step=2)
v = tf.Variable([10.0])

@tf.function
def apply(g):
    opt.apply_gradients([(g, v)])

apply(tf.constant([float(rank + 1)]))      # pass 1: accumulate only
assert np.allclose(v.numpy(), 10.0), v.numpy()
apply(tf.constant([float(rank + 1)]))      # pass 2: allreduce + apply
# grad = mean_r(2*(r+1)/2) = mean(1,2) = 1.5 ; v = 10 - 1.5
assert np.allclose(v.numpy(), 8.5), v.numpy()
apply(tf.constant([1.0]))                  # next window accumulates again
assert np.allclose(v.numpy(), 8.5), v.numpy()
print("TFBPPS_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TFBPPS_OK {r}" in o


def test_torch_unused_param_keeps_none_grad():
    """A param whose hook never fired and whose grad is None must be
    zero-substituted on the WIRE only: p.grad stays None so the base
    optimizer's weight decay/momentum keeps skipping it."""
    out = run_distributed(2, """
import torch
import horovod_tpu.torch as ht

a = torch.nn.Parameter(torch.ones(2))
b = torch.nn.Parameter(torch.full((2,), 5.0))
opt = ht.DistributedOptimizer(
    torch.optim.SGD([a, b], lr=0.1, weight_decay=0.5),
    named_parameters=[("a", a), ("b", b)])
loss = (a * (rank + 1)).sum()   # b unused
loss.backward()
opt.step()
assert b.grad is None, b.grad
assert np.allclose(b.detach().numpy(), 5.0), b   # no decay drift
exp = 1.0 - 0.1 * (1.5 + 0.5)   # mean grad 1.5 + wd*1.0
assert np.allclose(a.detach().numpy(), exp), (a, exp)
print("TUNUSED_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TUNUSED_OK {r}" in o


def test_keras_load_model_preserves_optimizer_state(tmp_path):
    """hvd keras load_model must keep the checkpoint's optimizer slot
    variables and iteration count (in-place class swap, not from_config
    reconstruction)."""
    out = run_distributed(1, f"""
import os
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import keras
import horovod_tpu.keras as hk

model = keras.Sequential([keras.layers.Input((4,)), keras.layers.Dense(2)])
model.compile(optimizer=keras.optimizers.Adam(0.01), loss="mse")
x = np.random.RandomState(0).randn(16, 4).astype("float32")
y = np.random.RandomState(1).randn(16, 2).astype("float32")
model.fit(x, y, epochs=2, batch_size=8, verbose=0)
iters_before = int(model.optimizer.iterations.numpy())
assert iters_before > 0
path = {str(tmp_path)!r} + "/m.keras"
model.save(path)

loaded = hk.load_model(path)
assert type(loaded.optimizer).__name__.startswith("Distributed"), \\
    type(loaded.optimizer)
assert int(loaded.optimizer.iterations.numpy()) == iters_before, \\
    (int(loaded.optimizer.iterations.numpy()), iters_before)
# moments restored: at least one nonzero slot variable
slots = [v for v in loaded.optimizer.variables
         if "momentum" in v.path or "velocity" in v.path or "m" in v.name]
assert any(float(abs(np.asarray(v)).sum()) > 0 for v in slots), \\
    [v.path for v in loaded.optimizer.variables]
# and it still trains distributed
loaded.fit(x, y, epochs=1, batch_size=8, verbose=0)
print("KLOAD_OK", rank, flush=True)
""", timeout=240)
    assert "KLOAD_OK 0" in out[0]
