"""Steady-state fast-path tests.

Three properties of the np>1 eager plane's hot loop:

1. **Zero-payload cycles** — once a tensor's negotiation is cached, later
   cycles exchange bitvector mask frames only: no ``Request`` is serialized
   by any rank and no ``ResponseList`` is broadcast (the controller's
   ``serialized_request_count`` / ``fast_cycle_count`` hooks pin this).
2. **Pipelined negotiate/dispatch** — with microbatch overlap, a window's
   collectives negotiate + dispatch UNDER the next microbatch's compute, so
   overlap mode's flush (and whole window) is not slower than
   accumulate-then-reduce despite communicating every backward.
3. **Topology agreement** — rank 0's controller fan-out choice is published
   through the rendezvous store; a worker whose env derived a different
   choice fails loudly at bring-up instead of deadlocking the first round.
"""

import threading
import types

import numpy as np
import pytest

from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.common.topology import ProcessTopology
from horovod_tpu.core.controller import Controller
from horovod_tpu.core.messages import (
    DataType,
    Request,
    RequestType,
    ResponseType,
)
from horovod_tpu.transport import MemoryStore, TcpMesh

from .helpers import run_distributed


def _run_ranks(size, fn, timeout=60):
    from .helpers import _timeout_scale

    errs, results = [], [None] * size

    def wrap(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errs.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    budget = timeout * _timeout_scale()
    for t in threads:
        t.join(budget)
        assert not t.is_alive(), "rank thread hung"
    if errs:
        raise errs[0][1]
    return results


def _req(rank, name="t", shape=(4,)):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=list(shape))


def test_fully_cached_cycle_serializes_zero_requests():
    """Cycle 1 negotiates and caches; cycle 2 is all mask frames (zero
    Request serializations anywhere, coordinator answers with the agreed
    bitvector only); an idle cycle 3 is also a fast cycle."""
    store = MemoryStore()
    size = 2

    def body(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1")
        try:
            ctrl = Controller(ProcessTopology(rank=rank, size=size,
                                              local_rank=rank,
                                              local_size=size), mesh)
            # cycle 1: full negotiation, assigns a cache bit
            rl1 = ctrl.compute_response_list([_req(rank)], False)
            assert len(rl1.responses) == 1
            assert rl1.responses[0].response_type == ResponseType.ALLREDUCE
            assert ctrl.fast_cycle_count == 0
            base = ctrl.serialized_request_count

            # cycle 2: fully cached — the fast cycle
            rl2 = ctrl.compute_response_list([_req(rank)], False)
            assert len(rl2.responses) == 1
            assert rl2.responses[0].tensor_names == ["t"]
            assert rl2.responses[0].tensor_sizes == [4]
            assert ctrl.serialized_request_count == base, \
                "a Request was serialized during a fully-cached cycle"
            assert ctrl.fast_cycle_count == 1
            if rank != 0:
                assert ctrl.mask_only_sent_count >= 1

            # cycle 3: idle — still zero-payload, counted separately so
            # fast_cycle_count measures completed-work cycles only
            rl3 = ctrl.compute_response_list([], False)
            assert rl3.responses == []
            assert ctrl.serialized_request_count == base
            assert ctrl.fast_cycle_count == 1
            assert ctrl.idle_fast_cycle_count == 1
            return True
        finally:
            mesh.close()

    assert all(_run_ranks(size, body))


def test_cache_miss_after_fast_cycles_still_negotiates():
    """A new tensor (cache miss) after fast cycles goes through the full
    path — and both ranks still agree on the response order when a cached
    and an uncached tensor complete in the same cycle."""
    store = MemoryStore()
    size = 2

    def body(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1")
        try:
            ctrl = Controller(ProcessTopology(rank=rank, size=size,
                                              local_rank=rank,
                                              local_size=size), mesh)
            ctrl.compute_response_list([_req(rank, "a")], False)
            ctrl.compute_response_list([_req(rank, "a")], False)  # fast
            # mixed cycle: cached "a" + brand-new "b"
            rl = ctrl.compute_response_list(
                [_req(rank, "a"), _req(rank, "b", shape=(8,))], False)
            names = sorted(n for r in rl.responses for n in r.tensor_names)
            assert names == ["a", "b"], names
            # and the next all-cached cycle is fast again
            base = ctrl.serialized_request_count
            ctrl.compute_response_list(
                [_req(rank, "a"), _req(rank, "b", shape=(8,))], False)
            assert ctrl.serialized_request_count == base
            return True
        finally:
            mesh.close()

    assert all(_run_ranks(size, body))


def test_overlap_window_not_slower_than_accumulate_np4():
    """np=4: with real compute between microbatches (stood in by sleeps,
    which release the CPU exactly like a device-bound backward), overlap
    mode's window must not be slower than accumulate mode — its
    collectives negotiate and dispatch UNDER the sleeps, while accumulate
    pays the whole negotiate+collective after them.  This is the pipelined
    schedule the reference's WFBP exists to win (torch/optimizer.py:
    103-149) and the regression the eager_np8 baseline showed (overlap
    36.6% SLOWER)."""
    out = run_distributed(4, """
import time
import statistics
import jax
import jax.numpy as jnp
import optax
from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer
from horovod_tpu.core.state import global_state

SLEEP = 0.3
params = {"w": jnp.ones((64, 64), jnp.float32)}
grads = {"w": jnp.full((64, 64), float(rank + 1), jnp.float32)}

def run_windows(overlap, n_windows=5):
    tx = optax.sgd(0.1)
    dopt = DistributedOptimizer(tx, backward_passes_per_step=2,
                                overlap=overlap)
    st = dopt.init(params)
    walls, flushes = [], []
    for w in range(n_windows):
        t0 = time.perf_counter()
        for mb in range(2):
            time.sleep(SLEEP)            # stands in for backward compute
            t1 = time.perf_counter()
            upd, st = dopt.update(grads, st, params)
            dt = time.perf_counter() - t1
        jax.block_until_ready(upd["w"])
        walls.append(time.perf_counter() - t0)
        flushes.append(dt)               # the window-flush call
    return walls[1:], flushes[1:]        # window 0 warms compiles + cache

acc_walls, acc_flush = run_windows(False)
ov_walls, ov_flush = run_windows(True)
# min, not median: host-load spikes only ADD time, so the fastest window
# of each mode is the clean measurement; a genuine pipelining regression
# (the r5 baseline's 36.6% loss) shifts every window, min included.
acc_w, ov_w = min(acc_walls), min(ov_walls)
print("WINDOWS", rank, round(acc_w, 3), round(ov_w, 3),
      round(statistics.median(acc_flush), 4),
      round(statistics.median(ov_flush), 4), flush=True)
# overlap >= accumulate: the overlapped window must not be slower
# (10% + 80ms slack absorbs residual scheduler noise on a loaded core).
assert ov_w <= acc_w * 1.10 + 0.08, (ov_w, acc_w)
ctrl = global_state().controller
assert ctrl.fast_cycle_count > 0, "steady-state cycles never went fast"
print("OVERLAP_OK", rank, flush=True)
""", timeout=300)
    for r, o in enumerate(out):
        assert f"OVERLAP_OK {r}" in o


def test_controller_topology_mismatch_is_loud():
    """A worker whose env derived a different fan-out than rank 0
    published must raise a HorovodInternalError naming the knob — not
    deadlock the first negotiation round (ADVICE r5)."""
    from horovod_tpu.core.state import HorovodGlobalState

    store = MemoryStore()

    def fake_state(rank, fanout):
        st = HorovodGlobalState()
        st.topo = ProcessTopology(rank=rank, size=2, local_rank=rank,
                                  local_size=2)
        st.controller = types.SimpleNamespace(fanout_topology=fanout,
                                              configure_fanin=lambda plan: None)
        return st

    fake_state(0, "star")._sync_controller_topology(store, 0, timeout=5)
    # agreeing worker: fine
    fake_state(1, "star")._sync_controller_topology(store, 0, timeout=5)
    # disagreeing worker: loud
    with pytest.raises(HorovodInternalError,
                       match="HOROVOD_CONTROLLER_TOPOLOGY"):
        fake_state(1, "tree")._sync_controller_topology(store, 0, timeout=5)


def test_wake_event_cuts_idle_latency():
    """An enqueue while the background loop is parked must start the next
    cycle immediately: with a deliberately huge cycle time, a round trip
    still completes far inside one cycle period."""
    out = run_distributed(2, """
import time
x = np.ones(16, np.float32)
# warm (negotiate + cache)
hvd.allreduce(x, op=hvd.Sum, name="wake.t")
t0 = time.perf_counter()
for i in range(3):
    hvd.allreduce(x, op=hvd.Sum, name="wake.t")
dt = (time.perf_counter() - t0) / 3
# cycle time is 500 ms: without the wake event each op waits out the
# remainder of a sleep; with it the three ops must finish well inside
# ONE cycle period each (generous 450 ms bound for loaded boxes).
assert dt < 0.45, f"enqueue->complete took {dt:.3f}s with 500ms cycles"
print("WAKE_OK", rank, flush=True)
""", extra_env={"HOROVOD_CYCLE_TIME": "500"}, timeout=240)
    for r, o in enumerate(out):
        assert f"WAKE_OK {r}" in o
