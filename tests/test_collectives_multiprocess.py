"""End-to-end multi-process collective tests — the parallel tier.

Modeled on the reference's ``test/parallel/test_torch.py`` /
``test_tensorflow.py`` structure: rank-dependent inputs so wrong-rank bugs
change results; closed-form expectations; error-path tests for cross-rank
mismatches (reference ``test_tensorflow.py:603-673``)."""

import pytest

from .helpers import run_distributed


@pytest.mark.parametrize("n", [2, 4])
def test_allreduce_average(n):
    run_distributed(n, """
x = np.arange(8, dtype=np.float32) * (rank + 1)
out = hvd.allreduce(x, average=True, name="avg0")
expected = np.arange(8, dtype=np.float32) * (sum(r + 1 for r in range(size)) / size)
np.testing.assert_allclose(out, expected, rtol=1e-6)
""")


def test_allreduce_sum_and_scales():
    run_distributed(2, """
x = np.ones(5, dtype=np.float64) * (rank + 1)
out = hvd.allreduce(x, op=hvd.Sum, name="sum0")
np.testing.assert_allclose(out, np.ones(5) * 3.0)

out = hvd.allreduce(x, op=hvd.Sum, name="scaled",
                    prescale_factor=2.0, postscale_factor=0.5)
np.testing.assert_allclose(out, np.ones(5) * 3.0)
""")


def test_allreduce_fused_many_tensors():
    # several tensors in flight at once — exercises controller fusion
    run_distributed(2, """
handles = [hvd.allreduce_async(np.full(100, float(i + rank), np.float32),
                               op=hvd.Sum, name=f"t{i}") for i in range(10)]
for i, h in enumerate(handles):
    out = hvd.synchronize(h)
    np.testing.assert_allclose(out, np.full(100, float(2 * i + 1), np.float32))
""")


def test_allreduce_bfloat16():
    run_distributed(2, """
import ml_dtypes
x = (np.arange(16) % 8).astype(ml_dtypes.bfloat16) * (rank + 1)
out = hvd.allreduce(x, op=hvd.Sum, name="bf16")
expected = ((np.arange(16) % 8) * 3).astype(ml_dtypes.bfloat16)
assert out.dtype == x.dtype
np.testing.assert_allclose(out.astype(np.float32), expected.astype(np.float32))
""")


@pytest.mark.parametrize("n", [2, 3])
def test_allgather_variable_size(n):
    run_distributed(n, """
x = np.full((rank + 1, 3), float(rank), np.float32)
out = hvd.allgather(x, name="ag")
assert out.shape == (sum(r + 1 for r in range(size)), 3)
offset = 0
for r in range(size):
    np.testing.assert_allclose(out[offset:offset + r + 1], float(r))
    offset += r + 1
""")


def test_broadcast_from_nonzero_root():
    run_distributed(3, """
x = np.arange(6, dtype=np.int64) * (rank + 10)
out = hvd.broadcast(x, root_rank=1, name="bc")
np.testing.assert_array_equal(out, np.arange(6, dtype=np.int64) * 11)
""")


def test_alltoall_uneven_splits():
    run_distributed(2, """
# rank 0 sends [1 row to r0, 2 rows to r1]; rank 1 sends [3 rows to r0, 1 to r1]
splits = [[1, 2], [3, 1]][rank]
rows = sum(splits)
x = np.full((rows, 2), float(rank), np.float32)
out, rsplits = hvd.alltoall(x, splits=splits, name="a2a",
                            return_received_splits=True)
expected_rsplits = [[1, 3], [2, 1]][rank]
assert rsplits == expected_rsplits, (rsplits, expected_rsplits)
assert out.shape == (sum(expected_rsplits), 2)
offset = 0
for r, cnt in enumerate(expected_rsplits):
    np.testing.assert_allclose(out[offset:offset + cnt], float(r))
    offset += cnt
""")


def test_shape_mismatch_raises_everywhere():
    run_distributed(2, """
from horovod_tpu.common.exceptions import HorovodInternalError
x = np.ones(3 + rank, np.float32)  # different shapes
try:
    hvd.allreduce(x, name="bad_shape")
    raise SystemExit("expected HorovodInternalError")
except HorovodInternalError as e:
    assert "shape" in str(e).lower(), str(e)
# runtime must still be healthy afterwards
out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="after_err")
np.testing.assert_allclose(out, 2 * np.ones(4))
""")


def test_dtype_mismatch_raises():
    run_distributed(2, """
from horovod_tpu.common.exceptions import HorovodInternalError
x = np.ones(4, np.float32 if rank == 0 else np.float64)
try:
    hvd.allreduce(x, name="bad_dtype")
    raise SystemExit("expected HorovodInternalError")
except HorovodInternalError as e:
    assert "data type" in str(e).lower().replace("dtype", "data type"), str(e)
""")


def test_join_uneven_steps():
    # rank r performs (r+1) allreduces, then joins; joined ranks contribute
    # zeros (reference Join semantics, collective_operations.cc:257)
    run_distributed(3, """
for i in range(rank + 1):
    out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name=f"step{i}")
    # ranks still active at step i: those with r >= i → size - i
    expected = float(size - i)
    np.testing.assert_allclose(out, expected)
hvd.join()
""")


def test_barrier_and_duplicate_names():
    run_distributed(2, """
from horovod_tpu.common.exceptions import DuplicateNameError
hvd.barrier(name="b1")
h1 = hvd.allreduce_async(np.ones(1000000, np.float32), name="dup")
try:
    hvd.allreduce_async(np.ones(4, np.float32), name="dup")
    raise SystemExit("expected DuplicateNameError")
except DuplicateNameError:
    pass
hvd.synchronize(h1)
""")


def test_jax_array_roundtrip():
    run_distributed(2, """
import jax.numpy as jnp
import jax
x = jnp.arange(8, dtype=jnp.float32) * (rank + 1)
out = hvd.allreduce(x, op=hvd.Sum, name="jax0")
assert isinstance(out, jax.Array)
np.testing.assert_allclose(np.asarray(out), np.arange(8) * 3.0)
""")
