"""TF gradient registration + TF/Torch SyncBatchNorm.

Mirrors the reference's gradient-correctness tests
(``test_tensorflow.py:674-825`` style: differentiate THROUGH the
collective, compare against the closed form) and the sync-BN contract
(N ranks with per-rank batches normalize exactly like one rank with the
concatenated batch).
"""

from __future__ import annotations

import textwrap

import pytest

from tests.helpers import run_distributed


def test_tf_allreduce_gradient_two_ranks():
    """d/dx of sum(allreduce(x, Sum)) == size (each rank's x contributes to
    every rank's output once; custom gradient = allreduce of upstream)."""
    body = textwrap.dedent("""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvdtf

    x = tf.constant([1.0, 2.0, 3.0]) * (rank + 1)
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvdtf.allreduce(x, op=hvdtf.Sum, name="g.ar")
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, x)
    # loss = sum_r sum(x_r) on every rank; dL/dx = allreduce(ones, Sum) = size
    assert np.allclose(g.numpy(), 2.0), g.numpy()
    print("AR_GRAD_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "AR_GRAD_OK" in out


def test_tf_broadcast_and_allgather_gradients_two_ranks():
    body = textwrap.dedent("""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvdtf

    # broadcast: grad accumulates on root, zero elsewhere
    x = tf.constant([1.0, 2.0])
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvdtf.broadcast(x, root_rank=0, name="g.bc")
        loss = tf.reduce_sum(y * (rank + 1.0))
    g = tape.gradient(loss, x).numpy()
    if rank == 0:
        # every rank's upstream (rank+1) sums: 1 + 2 = 3
        assert np.allclose(g, 3.0), g
    else:
        assert np.allclose(g, 0.0), g

    # allgather: grad is the rank's own slice of the summed upstream
    z = tf.constant([[1.0], [2.0]]) * (rank + 1)
    with tf.GradientTape() as tape:
        tape.watch(z)
        y = hvdtf.allgather(z, name="g.ag")      # [4, 1]
        w = tf.constant([[1.0], [2.0], [3.0], [4.0]]) * (rank + 1.0)
        loss = tf.reduce_sum(y * w)
    g = tape.gradient(loss, z).numpy()
    # upstream dy = w_r on rank r; summed over ranks = [1,2,3,4]*(1+2)=3*
    expected = np.array([[3.0], [6.0]]) if rank == 0 else np.array([[9.0], [12.0]])
    assert np.allclose(g, expected), (rank, g)
    print("BC_AG_GRAD_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "BC_AG_GRAD_OK" in out


def test_tf_allreduce_gradient_inside_tf_function():
    """Graph mode: the custom gradient must survive @tf.function tracing
    (the py_function path has no intrinsic gradient)."""
    body = textwrap.dedent("""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvdtf

    @tf.function
    def f(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            loss = tf.reduce_sum(hvdtf.allreduce(x, op=hvdtf.Sum, name="g.fn"))
        return tape.gradient(loss, x)

    g = f(tf.constant([1.0, 1.0]))
    assert np.allclose(g.numpy(), 2.0), g.numpy()
    print("FN_GRAD_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "FN_GRAD_OK" in out


def test_tf_sync_batch_norm_matches_big_batch():
    """2 ranks × batch 4 with SyncBatchNormalization == 1 process × batch 8
    with plain BatchNormalization (moments averaged across ranks)."""
    body = textwrap.dedent("""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvdtf

    rng = np.random.RandomState(42)
    full = rng.rand(8, 3).astype(np.float32) * 4 - 2
    local = full[rank * 4:(rank + 1) * 4]

    sbn = hvdtf.SyncBatchNormalization(momentum=0.5, epsilon=1e-5)
    out = sbn(tf.constant(local), training=True)

    # closed form on the FULL batch
    mean = full.mean(axis=0)
    var = full.var(axis=0)
    expected = (local - mean) / np.sqrt(var + 1e-5)
    assert np.allclose(out.numpy(), expected, atol=1e-4), \\
        np.abs(out.numpy() - expected).max()
    # running stats adopted the global moments
    assert np.allclose(sbn.moving_mean.numpy(), 0.5 * mean, atol=1e-4)
    print("TF_SBN_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "TF_SBN_OK" in out


def test_torch_sync_batch_norm_matches_big_batch():
    pytest.importorskip("torch")
    body = textwrap.dedent("""
    import torch
    import horovod_tpu.torch as hvdt

    rng = np.random.RandomState(7)
    full = rng.rand(8, 3, 2).astype(np.float32) * 4 - 2
    local = torch.tensor(full[rank * 4:(rank + 1) * 4], requires_grad=True)

    sbn = hvdt.SyncBatchNorm(3, momentum=0.5, eps=1e-5)
    sbn.train()
    out = sbn(local)

    flat = full.transpose(1, 0, 2).reshape(3, -1)
    mean = flat.mean(axis=1)
    var = flat.var(axis=1)
    expected = (full[rank*4:(rank+1)*4] - mean[None, :, None]) \\
        / np.sqrt(var[None, :, None] + 1e-5)
    assert np.allclose(out.detach().numpy(), expected, atol=1e-4), \\
        np.abs(out.detach().numpy() - expected).max()

    # gradient parity with the big-batch reference BN
    loss = (out * torch.tensor(full[rank*4:(rank+1)*4] + 1.0)).sum()
    loss.backward()

    ref_in = torch.tensor(full, requires_grad=True)
    bn = torch.nn.BatchNorm2d(3, momentum=0.5, eps=1e-5) if False else \\
        torch.nn.BatchNorm1d(3, momentum=0.5, eps=1e-5)
    ref_out = bn(ref_in)
    ref_loss = (ref_out * torch.tensor(full + 1.0)).sum()
    ref_loss.backward()
    ref_grad = ref_in.grad.numpy()[rank*4:(rank+1)*4]
    assert np.allclose(local.grad.numpy(), ref_grad, atol=1e-3), \\
        np.abs(local.grad.numpy() - ref_grad).max()

    # running stats match the big batch's (unbiased var)
    assert np.allclose(sbn.running_mean.numpy(), 0.5 * mean, atol=1e-4)
    print("TORCH_SBN_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=240):
        assert "TORCH_SBN_OK" in out


def test_torch_sync_bn_single_process_matches_plain_bn():
    """size=1: SyncBatchNorm must equal nn.BatchNorm exactly."""
    torch = pytest.importorskip("torch")
    import numpy as np

    import horovod_tpu.torch as hvdt

    hvdt.init()  # size() is runtime state, like the reference
    rng = np.random.RandomState(0)
    x = torch.tensor(rng.rand(6, 4).astype(np.float32), requires_grad=True)
    x2 = x.detach().clone().requires_grad_(True)

    sbn = hvdt.SyncBatchNorm(4, momentum=0.3)
    bn = torch.nn.BatchNorm1d(4, momentum=0.3)
    sbn.train(), bn.train()

    out_s = sbn(x)
    out_b = bn(x2)
    assert torch.allclose(out_s, out_b, atol=1e-5)

    out_s.sum().backward()
    out_b.sum().backward()
    assert torch.allclose(x.grad, x2.grad, atol=1e-5)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-5)
    hvdt.shutdown()


def test_tf_sync_bn_multiple_instances():
    """Two SyncBatchNormalization layers must coexist in one model
    (auto-naming; distinct wire names)."""
    tf = pytest.importorskip("tensorflow")
    import numpy as np

    import horovod_tpu.tensorflow as hvdtf

    hvdtf.init()  # _moments consults size(), runtime state like the reference
    model = tf.keras.Sequential([
        tf.keras.layers.Input((4,)),
        tf.keras.layers.Dense(4),
        hvdtf.SyncBatchNormalization(),
        tf.keras.layers.Dense(4),
        hvdtf.SyncBatchNormalization(),
    ])
    out = model(np.random.rand(6, 4).astype("float32"), training=True)
    assert out.shape == (6, 4)
    names = [l.name for l in model.layers if "batch" in l.name.lower()]
    assert len(set(names)) == 2, names
    hvdtf.shutdown()
