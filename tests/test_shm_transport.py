"""Shared-memory intra-host transport: parity with TCP, exact byte
accounting, segment hygiene, and the per-link selection seam.

The contract under test (docs/data_plane.md "Transports"): shm carries
the SAME frame discipline as TCP — flag bits, abort/control frames,
deadline semantics, fault sites — so every guard the zero-copy and
chaos suites assert on TCP holds verbatim on shm.  The shm-specific
additions are (a) data bytes count under ``shm_bytes_total``, never
``bytes_on_wire`` (they are not on a wire), and (b) segment lifecycle:
no ``/dev/shm`` residue after clean exit, abort, or a kill-mid-step
sweep.
"""

import glob
import threading

import numpy as np
import pytest

from horovod_tpu.backend import cpu_ring
from horovod_tpu.common import faults
from horovod_tpu.common.exceptions import (CoordinatedAbortError,
                                           FrameCorruptError,
                                           HorovodInternalError)
from horovod_tpu.core import metrics
from horovod_tpu.core.timeline import wire_stats
from horovod_tpu.transport import LinkMesh, MemoryStore
from horovod_tpu.transport.shm import SEG_PREFIX, sweep_dead_segments

from .helpers import run_distributed
from .test_transport import run_ranks

pytestmark = pytest.mark.smoke


def _residue():
    return set(glob.glob(f"/dev/shm/{SEG_PREFIX}*"))


@pytest.fixture(autouse=True)
def _hygiene():
    """Every test starts fault-free and must leave zero NEW segments in
    /dev/shm — leak detection is part of every test, not one test."""
    faults.reset()
    before = _residue()
    yield
    faults.reset()
    leaked = _residue() - before
    assert not leaked, f"test leaked shm segments: {sorted(leaked)}"


def _mesh(rank, size, store, **kw):
    kw.setdefault("policy", "auto")
    kw.setdefault("host_id", "testhost/0")
    return LinkMesh(rank, size, store, epoch=0, timeout=15,
                    bind_addr="127.0.0.1", advertise_addr="127.0.0.1",
                    **kw)


# ---------------------------------------------------------------------------
# fault-site grammar (HVD003: new sites must parse, and payload actions
# stay send-only — shm.recv:corrupt would silently inject nothing)
# ---------------------------------------------------------------------------

class TestShmFaultGrammar:
    def test_shm_sites_parse(self):
        faults.configure("shm.send:rank=1:nth=6:action=corrupt,1")
        faults.configure("shm.send:nth=2:action=truncate,4")
        faults.configure("shm.recv:action=hang")
        faults.configure("shm.recv:action=delay_ms,5")
        faults.reset()

    def test_payload_actions_rejected_on_shm_recv(self):
        for bad in ["shm.recv:action=corrupt,1",
                    "shm.recv:action=truncate,4",
                    "shm.recv:action=drop"]:
            with pytest.raises(ValueError):
                faults.configure(bad)


# ---------------------------------------------------------------------------
# the selection seam
# ---------------------------------------------------------------------------

def test_same_host_links_classify_shm():
    store = MemoryStore()

    def fn(rank):
        mesh = _mesh(rank, 2, store)
        try:
            assert mesh.route_table() == {1 - rank: "shm"}
            # data-plane sanity through the facade
            if rank == 0:
                mesh.send(1, b"ping")
                assert mesh.recv(1) == b"pong"
            else:
                assert mesh.recv(0) == b"ping"
                mesh.send(0, b"pong")
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)


def test_cross_host_links_classify_tcp():
    store = MemoryStore()

    def fn(rank):
        mesh = _mesh(rank, 2, store, host_id=f"host{rank}/0")
        try:
            assert mesh.route_table() == {1 - rank: "tcp"}
            if rank == 0:
                mesh.send(1, b"x")
            else:
                assert mesh.recv(0) == b"x"
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)


def test_forced_shm_across_hosts_is_a_loud_config_error():
    """HOROVOD_TRANSPORT=shm on a cross-host link must refuse, not
    silently widen to TCP (that would fake the perf being measured)."""
    store = MemoryStore()

    def fn(rank):
        with pytest.raises(HorovodInternalError, match="cannot carry"):
            _mesh(rank, 2, store, policy="shm", host_id=f"host{rank}/0")

    run_ranks(2, fn, timeout=30)


def test_transport_policy_typo_is_loud(monkeypatch):
    from horovod_tpu.transport.select import transport_policy

    monkeypatch.setenv("HOROVOD_TRANSPORT", "smh")
    with pytest.raises(HorovodInternalError, match="auto|tcp|shm"):
        transport_policy()


# ---------------------------------------------------------------------------
# zero-copy parity matrix: the test_data_plane_zero_copy guards, re-run
# with the ring riding shm through the selection facade
# ---------------------------------------------------------------------------

def _shm_ring_allreduce(arrays, fbms=None, timeout=60):
    size = len(arrays)
    store = MemoryStore()

    def fn(rank):
        mesh = _mesh(rank, size, store)
        try:
            buf = arrays[rank]
            wide = cpu_ring._accum_dtype(buf.dtype)
            fbm = fbms[rank] if fbms is not None else None
            group = list(range(size))
            bounds = cpu_ring._ring_reduce_scatter(
                mesh, buf, group, rank, wide, fbm)
            cpu_ring._ring_allgather_chunks(mesh, buf, group, rank, bounds)
        finally:
            mesh.close()

    run_ranks(size, fn, timeout=timeout)
    return arrays


def _expected_sum(inputs, dtype):
    acc = np.zeros(inputs[0].shape, np.float64)
    for x in inputs:
        acc += np.asarray(x, np.float64)
    return acc.astype(dtype)


def _int_valued(n, rank, dtype):
    return ((np.arange(n) + rank) % 5 + rank + 1).astype(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32],
                         ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("n", [1, 7, 1023])
def test_shm_ring_bit_exact(dtype, n):
    inputs = [_int_valued(n, r, dtype) for r in range(3)]
    outs = _shm_ring_allreduce([x.copy() for x in inputs])
    exp = _expected_sum(inputs, dtype)
    for o in outs:
        assert np.array_equal(o, exp)


def test_shm_steady_state_zero_heap_copies_and_exact_accounting():
    """The zero-copy matrix on shm: steady-state ring steps make ZERO
    heap materializations, shm moves exactly the predicted payload
    bytes under ``shm_bytes_total``, and ``bytes_on_wire`` does not move
    at all — shm frames must never launder into the TCP counter."""
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    fbms = [cpu_ring.FusionBufferManager() for _ in range(size)]
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    _shm_ring_allreduce([x.copy() for x in inputs], fbms)  # warm arenas

    before = wire_stats.snapshot()
    shm_before = metrics.registry.get_counter("shm_bytes_total")
    outs = _shm_ring_allreduce([x.copy() for x in inputs], fbms)
    after = wire_stats.snapshot()
    shm_after = metrics.registry.get_counter("shm_bytes_total")

    assert np.array_equal(outs[0], _expected_sum(inputs, dtype))
    assert after.get("heap_copies", 0) == before.get("heap_copies", 0), \
        "a steady-state shm ring step materialized payload bytes"
    assert after.get("bytes_on_wire", 0) == before.get("bytes_on_wire", 0), \
        "shm frames leaked into the TCP bytes_on_wire counter"

    # Exact accounting, same formula as the TCP twin: every rank sends
    # g-1 chunks per phase; sender and receiver both count.
    bounds = cpu_ring._chunk_bounds(n, size)
    sent_elems = 0
    for idx in range(size):
        for s in range(size - 1):
            c = (idx - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
            c = (idx + 1 - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
    expected = 2 * sent_elems * dtype.itemsize
    assert shm_after - shm_before == expected, \
        (shm_after - shm_before, expected)


def test_shm_sendrecv_into_bit_exact_both_directions():
    store = MemoryStore()
    n = 4096
    payloads = [(np.arange(n, dtype=np.float64) * (r + 1)) for r in range(2)]
    got = [None, None]

    def fn(rank):
        mesh = _mesh(rank, 2, store)
        try:
            dest = np.empty(n, np.float64)
            mesh.sendrecv_into(1 - rank, payloads[rank], 1 - rank, dest)
            got[rank] = dest
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)
    assert np.array_equal(got[0], payloads[1])
    assert np.array_equal(got[1], payloads[0])


# ---------------------------------------------------------------------------
# failure plane: CRC, truncation, abort propagation, PID liveness
# ---------------------------------------------------------------------------

def test_shm_crc_catches_injected_corruption(monkeypatch):
    """HOROVOD_SHM_CRC=1 + a one-byte flip on shm.send → typed
    FrameCorruptError on the receiver, exactly like tcp.send."""
    monkeypatch.setenv("HOROVOD_SHM_CRC", "1")
    faults.configure("shm.send:rank=1:nth=1:action=corrupt,1")
    store = MemoryStore()
    errs = [None, None]

    def fn(rank):
        mesh = _mesh(rank, 2, store)
        try:
            if rank == 1:
                mesh.send(0, np.ones(64, np.float32))
            else:
                try:
                    mesh.recv(1)
                except FrameCorruptError as e:
                    errs[0] = e
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)
    assert isinstance(errs[0], FrameCorruptError)
    assert "wire CRC" in str(errs[0])


def test_shm_truncated_frame_is_typed_misframe(monkeypatch):
    monkeypatch.setenv("HOROVOD_SHM_CRC", "1")
    faults.configure("shm.send:rank=1:nth=1:action=truncate,4")
    store = MemoryStore()
    errs = [None]

    def fn(rank):
        mesh = _mesh(rank, 2, store)
        try:
            if rank == 1:
                mesh.send(0, np.ones(64, np.float32))
            else:
                dest = np.empty(64, np.float32)
                try:
                    mesh.recv_into(1, dest)
                except HorovodInternalError as e:
                    errs[0] = e
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)
    assert errs[0] is not None and "misframed" in str(errs[0])


def test_abort_unblocks_peer_mid_ring_wait():
    """A rank blocked in an shm recv must observe a peer's send_abort as
    CoordinatedAbortError within the poll quantum — the in-band abort
    frame plus the nap Event, not a deadline expiry."""
    store = MemoryStore()
    errs = [None, None]

    def fn(rank):
        mesh = _mesh(rank, 2, store)
        try:
            if rank == 0:
                try:
                    mesh.recv(1)  # nothing ever sent: blocks
                except CoordinatedAbortError as e:
                    errs[0] = e
            else:
                mesh.send_abort("test abort", origin_rank=1)
        finally:
            mesh.close()

    run_ranks(2, fn, timeout=30)
    assert isinstance(errs[0], CoordinatedAbortError)
    assert "test abort" in str(errs[0])


def test_no_residue_after_clean_close_and_after_abort():
    """Segment lifecycle: the creator unlinks on close; neither a clean
    pass nor an aborted one may leave /dev/shm residue.  (The autouse
    fixture asserts it; this test exists so the property is exercised by
    name, under both exits.)"""
    test_same_host_links_classify_shm()
    test_abort_unblocks_peer_mid_ring_wait()
    assert True  # residue asserted by _hygiene on exit


def test_sweep_dead_segments_reclaims_by_creator_pid():
    """The runner's kill-mid-step backstop: segments named with a dead
    creator pid are unlinked; other pids' segments are untouched."""
    from multiprocessing import shared_memory

    fake_dead, fake_live = 4194000, 4194001
    names = [f"{SEG_PREFIX}{fake_dead}-e0-0x1-deadbeef",
             f"{SEG_PREFIX}{fake_live}-e0-0x1-cafecafe"]
    segs = [shared_memory.SharedMemory(name=n, create=True, size=64)
            for n in names]
    try:
        removed = sweep_dead_segments([fake_dead])
        assert removed == [names[0]]
        left = _residue()
        assert f"/dev/shm/{names[0]}" not in left
        assert f"/dev/shm/{names[1]}" in left
    finally:
        for seg in segs:
            seg.close()
            try:
                seg.unlink()
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# chaos: np=2 subprocess jobs riding shm under auto selection
# ---------------------------------------------------------------------------

# Mirrors test_fault_injection._FAST_DEADLINE but selects the shm path
# and arms its CRC so corruption is detectable; lockdep on throughout.
_SHM_CHAOS_ENV = {"HOROVOD_TCP_PROGRESS_DEADLINE_SECS": "3",
                  "HOROVOD_TRANSPORT": "auto",
                  "HOROVOD_SHM_CRC": "1",
                  "HOROVOD_LOCK_DEBUG": "1"}

_SURVIVOR_BODY = """
import os
print("PID", rank, os.getpid(), flush=True)
from horovod_tpu.common.exceptions import HorovodInternalError
try:
    for i in range(500):
        hvd.allreduce(np.ones(32, np.float32), name=f"t{i % 4}")
    print("NO_FAULT_SEEN", rank, flush=True)
except HorovodInternalError as e:
    print("SURVIVOR_ABORT", rank, str(e).replace("\\n", " "), flush=True)
"""


def _worker_pids(outs):
    pids = []
    for r, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith(f"PID {r} "):
                pids.append(int(line.split()[2]))
    return pids


@pytest.mark.timeout(150)
def test_shm_corrupt_frame_np2_coordinated_abort():
    """The TCP chaos headline, on shm: one flipped byte in a shared ring
    aborts BOTH ranks with the wire-CRC diagnosis — and the job leaves
    no segment residue (survivor unlink + post-exit sweep)."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_SHM_CHAOS_ENV,
                   "HOROVOD_FAULT_SPEC":
                       "shm.send:rank=1:nth=6:action=corrupt,1"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "wire CRC" in outs[0], outs[0]
    assert "SURVIVOR_ABORT 1" in outs[1], outs[1]
    sweep_dead_segments(_worker_pids(outs))


@pytest.mark.timeout(150)
def test_shm_kill_rank_mid_step_np2_survivor_aborts_and_sweep_cleans():
    """A rank hard-dying mid-collective while the data plane rides shm:
    the survivor's PID-liveness probe converts the stalled ring wait
    into a typed abort (no hang), and the launcher-side
    ``sweep_dead_segments`` backstop reclaims the victim's segments."""
    outs = run_distributed(
        2, _SURVIVOR_BODY, timeout=120, expect_failure=True, retries=0,
        extra_env={**_SHM_CHAOS_ENV,
                   "HOROVOD_FAULT_SPEC":
                       "dispatch.collective:rank=1:nth=8:action=exit,9"})
    assert "SURVIVOR_ABORT 0" in outs[0], outs[0]
    assert "NO_FAULT_SEEN" not in outs[0], outs[0]
    pids = _worker_pids(outs)
    assert len(pids) == 2, outs
    # the exact call runner/launch.py makes after reaping its workers
    sweep_dead_segments(pids)
    left = {p for p in _residue()
            for pid in pids if f"/{SEG_PREFIX}{pid}-" in p}
    assert not left, f"kill-mid-step left segments: {sorted(left)}"


# ---------------------------------------------------------------------------
# the headline: HierarchicalAllreduce rides shm intra-host + TCP
# cross-host through the seam, bit-identical to all-TCP
# ---------------------------------------------------------------------------

_HIER_BODY = """
import hashlib
x = (np.arange(4096, dtype=np.float32) % 7) * (rank + 1) + rank
o = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="h"))
print("SUM", rank, hashlib.sha1(o.tobytes()).hexdigest(), flush=True)
from horovod_tpu.core import metrics as _m
print("LINKS", rank,
      int(_m.registry.get_counter("transport_links_total", transport="shm")),
      int(_m.registry.get_counter("transport_links_total", transport="tcp")),
      flush=True)
"""


def _sums(outs):
    got = {}
    for r, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith(f"SUM {r} "):
                got[r] = line.split()[2]
    return got


@pytest.mark.timeout(300)
def test_hierarchical_np4_shm_intra_tcp_cross_bit_identical():
    """4 ranks as 2 simulated hosts x 2 slots: under ``auto`` every rank
    must classify exactly 1 intra-host link as shm and 2 cross-host
    links as TCP (cross_rank folds into the host identity), and the
    hierarchical allreduce result must be BIT-identical to the same job
    forced all-TCP."""
    auto = run_distributed(4, _HIER_BODY, timeout=240, local_size=2,
                           extra_env={"HOROVOD_TRANSPORT": "auto"})
    tcp = run_distributed(4, _HIER_BODY, timeout=240, local_size=2,
                          extra_env={"HOROVOD_TRANSPORT": "tcp"})
    sums_auto, sums_tcp = _sums(auto), _sums(tcp)
    assert len(sums_auto) == len(sums_tcp) == 4, (auto, tcp)
    assert len(set(sums_auto.values())) == 1, sums_auto  # ranks agree
    assert sums_auto == sums_tcp, (sums_auto, sums_tcp)  # transports agree
    for r, out in enumerate(auto):
        assert f"LINKS {r} 1 2" in out, (r, out)
    for r, out in enumerate(tcp):
        # forced tcp takes the pre-seam TcpMesh path: no links classified
        assert f"LINKS {r} 0 0" in out, (r, out)
