"""Zero-copy, segment-pipelined host ring: bit-exactness and the
no-heap-copy counter guard.

The guard is DETERMINISTIC: steady-state ring steps must perform zero
payload materializations, asserted through the ``wire_stats.heap_copies``
counter (``core/timeline.py``) — never through wall-clock thresholds,
which this box's ±20% bench noise would make flaky.  Bit-exactness uses
integer-valued floats so the ring's reduction order cannot perturb the
reference ``np.sum``.
"""

import threading

import numpy as np
import pytest

from horovod_tpu.backend import cpu_ring
from horovod_tpu.common import env as env_mod
from horovod_tpu.core.tensor_queue import TensorTableEntry
from horovod_tpu.core.timeline import wire_stats
from horovod_tpu.transport import MemoryStore, TcpMesh

from .test_transport import run_ranks

pytestmark = pytest.mark.smoke


def _entry(tensor):
    return TensorTableEntry(tensor_name="t", tensor=tensor,
                            callback=lambda s, e: None)


def _ring_allreduce_threads(arrays, fbms=None, timeout=60):
    """Drive the pipelined ring primitives directly: len(arrays) thread
    ranks over an in-process mesh, each reducing+allgathering its buffer
    in place (the exact code path ``RingAllreduce._ring_allreduce``
    runs)."""
    size = len(arrays)
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=15)
        try:
            buf = arrays[rank]
            wide = cpu_ring._accum_dtype(buf.dtype)
            fbm = fbms[rank] if fbms is not None else None
            group = list(range(size))
            bounds = cpu_ring._ring_reduce_scatter(
                mesh, buf, group, rank, wide, fbm)
            cpu_ring._ring_allgather_chunks(mesh, buf, group, rank, bounds)
        finally:
            mesh.close()

    run_ranks(size, fn, timeout=timeout)
    return arrays


def _expected_sum(inputs, dtype):
    """Reference: exact elementwise sum (fp64 accumulate), cast back."""
    acc = np.zeros(inputs[0].shape, np.float64)
    for x in inputs:
        acc += np.asarray(x, np.float64)
    return acc.astype(dtype)


def _int_valued(n, rank, dtype):
    """Integer-valued payloads: exactly representable in every tested
    dtype (fp16/bf16 included), so any reduction ORDER gives the same
    bits and the ring can be compared against np.sum bit-for-bit."""
    return ((np.arange(n) + rank) % 5 + rank + 1).astype(dtype)


_DTYPES = [np.float32, np.float64, np.float16, np.int32, np.int64]
try:
    import ml_dtypes

    # The narrow-wire extension dtype: no PEP-3118 buffer format, so it
    # exercises the uint8-reinterpret _byte_view fallback.
    _DTYPES.append(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


@pytest.mark.parametrize("dtype", _DTYPES, ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("n", [1, 7, 1023])
def test_pipelined_ring_bit_exact(dtype, n):
    """Pipelined ring allreduce == np.sum, bit for bit, across dtypes
    (including the fp16/bf16 narrow-wire paths) and element counts that
    divide evenly by neither the world size nor the segment size."""
    size = 3
    dtype = np.dtype(dtype)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]
    expected = _expected_sum(inputs, dtype)
    outs = _ring_allreduce_threads([x.copy() for x in inputs])
    for r in range(size):
        got = np.asarray(outs[r], np.float64)
        want = np.asarray(expected, np.float64)
        assert np.array_equal(got, want), (r, got[:8], want[:8])


@pytest.mark.parametrize("seg_bytes", ["1", str(1 << 30)])
def test_segment_size_edge_cases(monkeypatch, seg_bytes):
    """The knob's extremes both reduce correctly: 1 byte (clamped to one
    element per segment — maximal pipelining) and larger than the chunk
    (degrades to the unpipelined single-frame step)."""
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, seg_bytes)
    size, n = 2, 13
    inputs = [_int_valued(n, r, np.float32) for r in range(size)]
    expected = _expected_sum(inputs, np.float32)
    outs = _ring_allreduce_threads([x.copy() for x in inputs])
    for out in outs:
        assert np.array_equal(out, expected)


def test_one_element_segments_really_segment(monkeypatch):
    """HOROVOD_RING_SEGMENT_BYTES=1 clamps to one element — sanity that
    the clamp math holds for every itemsize."""
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, "1")
    assert cpu_ring._segment_elems(np.dtype(np.float64)) == 1
    assert cpu_ring._segment_elems(np.dtype(np.float16)) == 1
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, "1024")
    assert cpu_ring._segment_elems(np.dtype(np.float32)) == 256


def test_large_payload_pipeline_no_deadlock():
    """Segments beyond socket-buffer capacity must stream, not deadlock:
    the exchange posts its receive before each send (and the recvs run on
    the helper thread), so every rank always drains while it pushes."""
    size, n = 3, 1_500_001  # ~6 MB/rank of float32, odd on purpose
    inputs = [np.full(n, float(r + 1), np.float32) for r in range(size)]
    outs = _ring_allreduce_threads([x.copy() for x in inputs], timeout=120)
    for out in outs:
        assert np.array_equal(out, np.full(n, 6.0, np.float32))


def test_steady_state_ring_step_zero_heap_copies():
    """THE zero-copy guard: after one warm allreduce (staging arenas
    allocated), a steady-state ring pass performs ZERO heap
    materializations of payload bytes — and moves exactly the predicted
    number of payload bytes over the wire.  Counter-asserted; no timing
    anywhere."""
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    fbms = [cpu_ring.FusionBufferManager() for _ in range(size)]
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    # Warm pass: allocates per-rank staging arenas inside the managers.
    _ring_allreduce_threads([x.copy() for x in inputs], fbms)

    before = wire_stats.snapshot()
    outs = _ring_allreduce_threads([x.copy() for x in inputs], fbms)
    after = wire_stats.snapshot()

    assert np.array_equal(outs[0], _expected_sum(inputs, dtype))
    assert after.get("heap_copies", 0) == before.get("heap_copies", 0), \
        "a steady-state ring step materialized payload bytes on the heap"

    # Exact wire accounting: every rank sends g-1 chunks in each phase;
    # sender and receiver both count, and all ranks share this process.
    bounds = cpu_ring._chunk_bounds(n, size)
    sent_elems = 0
    for idx in range(size):
        for s in range(size - 1):
            c = (idx - s) % size            # reduce-scatter send chunk
            sent_elems += int(bounds[c + 1] - bounds[c])
            c = (idx + 1 - s) % size        # allgather send chunk
            sent_elems += int(bounds[c + 1] - bounds[c])
    expected_wire = 2 * sent_elems * dtype.itemsize  # send + recv counts
    got_wire = after.get("bytes_on_wire", 0) - before.get("bytes_on_wire", 0)
    assert got_wire == expected_wire, (got_wire, expected_wire)


# ---------------------------------------------------------------------------
# fuse/unfuse copy discipline (satellite: the single-entry double-copy)
# ---------------------------------------------------------------------------


def test_fuse_single_entry_one_copy_no_alias():
    """Single-entry fuse makes exactly ONE copy (counter-asserted) and
    never aliases the user's tensor — for contiguous, transposed, and
    Fortran-ordered inputs alike."""
    for t in (np.arange(12, dtype=np.float32).reshape(3, 4),
              np.arange(12, dtype=np.float32).reshape(3, 4).T,
              np.asfortranarray(
                  np.arange(12, dtype=np.float64).reshape(3, 4))):
        before = wire_stats.get("heap_copies")
        out = cpu_ring.fuse_entries([_entry(t)], t.dtype)
        assert wire_stats.get("heap_copies") == before + 1
        assert np.array_equal(out, np.asarray(t).ravel())
        assert not np.shares_memory(out, t), "fuse returned a view"
        # the ravel after astype must be a VIEW (the one copy already
        # happened); a second materialization would hide here
        assert out.base is not None
        out[...] = -1.0
        assert float(np.asarray(t).ravel()[0]) != -1.0


def test_fuse_single_entry_casts_once():
    t = np.arange(6, dtype=np.float64)
    out = cpu_ring.fuse_entries([_entry(t)], np.dtype(np.float32))
    assert out.dtype == np.float32
    assert np.array_equal(out, t.astype(np.float32))


def test_unfuse_staged_outputs_do_not_alias_arena():
    """The aliasing contract: when the fused buffer is the persistent
    arena, ``unfuse_entries(..., copy=True)`` must hand out OWNED
    outputs — the next fused response overwrites the arena."""
    fbm = cpu_ring.FusionBufferManager()
    e1 = _entry(np.ones(8, np.float32))
    e2 = _entry(np.full(8, 2.0, np.float32))
    buf = cpu_ring.fuse_entries([e1, e2], np.dtype(np.float32), fbm)
    assert buf.base is not None  # staged into the arena
    cpu_ring.unfuse_entries(buf, [e1, e2], copy=True)
    arena = fbm.get(np.dtype(np.float32), 16)
    assert not np.shares_memory(e1.output, arena)
    assert not np.shares_memory(e2.output, arena)
    arena[:] = 99.0  # next cycle reuses the arena...
    assert np.array_equal(e1.output, np.ones(8, np.float32))
    assert np.array_equal(e2.output, np.full(8, 2.0, np.float32))


def test_fusion_buffer_keys_are_disjoint():
    """The ring's receive staging must never alias the fusion buffer the
    work payload lives in — keyed arenas guarantee it."""
    fbm = cpu_ring.FusionBufferManager()
    fusion = fbm.get(np.dtype(np.float32), 64)
    stage = fbm.get(np.dtype(np.float32), 64, key="ring-stage")
    assert not np.shares_memory(fusion, stage)
    # same key + dtype still shares one arena
    again = fbm.get(np.dtype(np.float32), 32, key="ring-stage")
    assert np.shares_memory(stage, again)


def test_byte_view_refuses_noncontiguous():
    """_byte_view must raise on strided views, never silently copy."""
    arr = np.arange(16, dtype=np.float32)[::2]
    with pytest.raises((ValueError, AttributeError)):
        cpu_ring._byte_view(arr)


def test_byte_view_covers_extension_dtypes():
    ml = pytest.importorskip("ml_dtypes")
    arr = np.arange(8, dtype=ml.bfloat16)
    view = cpu_ring._byte_view(arr)
    assert len(view) == arr.size * arr.dtype.itemsize
    assert not view.readonly
