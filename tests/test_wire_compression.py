"""Cast-on-the-wire compression: bit-exactness across ranks, the
halved-bytes counter contract, and the zero-copy guard with compression
on.

Cross-rank bit-identity is the hard requirement (elastic recovery
snapshots compare rank outputs bit for bit): after reduce-scatter each
owner quantizes its own chunk through the wire dtype before allgather,
so no rank keeps wide precision the others never saw.  Payloads are
integer-valued and small so fp16/bf16 represent every partial sum
exactly — making ``np.sum`` in float64 a legal bit-for-bit reference
(and keeping fp16 off its pathological overflow-cast path).
"""

import numpy as np
import pytest

from horovod_tpu.backend import cpu_ring
from horovod_tpu.backend import compression as comp_mod
from horovod_tpu.backend.compression import (WIRE_DTYPE_BF16,
                                             WIRE_DTYPE_FP16,
                                             wire_compressor_for)
from horovod_tpu.common import env as env_mod
from horovod_tpu.core.timeline import wire_stats
from horovod_tpu.transport import MemoryStore, TcpMesh

from .test_transport import run_ranks

pytestmark = pytest.mark.smoke

_HAS_BF16 = True
try:
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    _HAS_BF16 = False

_MODES = ["fp16"] + (["bf16"] if _HAS_BF16 else [])


def _int_valued(n, rank, dtype):
    return ((np.arange(n) + rank) % 5 + rank + 1).astype(dtype)


def _expected_sum(inputs, dtype):
    acc = np.zeros(inputs[0].shape, np.float64)
    for x in inputs:
        acc += np.asarray(x, np.float64)
    return acc.astype(dtype)


def _compressed_allreduce(arrays, fbms=None, timeout=60, efs=None):
    """Drive the exact RingAllreduce._ring_allreduce sequence — RS with
    compression, owner-chunk quantization (casts) or byte-forwarding
    allgather (lossy codecs), AG with compression — as thread ranks over
    an in-process mesh.  ``efs`` is an optional per-rank list of
    :class:`EfState` (thread ranks share process globals, so EF state
    must be explicit per rank here, exactly as each op instance owns its
    own in production)."""
    size = len(arrays)
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=15)
        try:
            buf = arrays[rank]
            wide = cpu_ring._accum_dtype(buf.dtype)
            comp = wire_compressor_for(buf.dtype)
            fbm = fbms[rank] if fbms is not None else None
            lossy = comp is not None and comp.lossy
            ef = efs[rank] if efs is not None and lossy else None
            if ef is not None:
                ef.begin(("t",))
            group = list(range(size))
            bounds = cpu_ring._ring_reduce_scatter(
                mesh, buf, group, rank, wide, fbm, compressor=comp,
                ef=ef)
            if lossy:
                cpu_ring._ring_allgather_bytes(
                    mesh, buf, group, rank, bounds, comp, fbm)
            else:
                if comp is not None:
                    own = (rank + 1) % size
                    cpu_ring._quantize_owned(
                        comp, buf[bounds[own]:bounds[own + 1]], fbm)
                cpu_ring._ring_allgather_chunks(
                    mesh, buf, group, rank, bounds, fbm, compressor=comp)
        finally:
            mesh.close()

    run_ranks(size, fn, timeout=timeout)
    return arrays


# ---------------------------------------------------------------------------
# compressor unit behavior
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", _MODES)
@pytest.mark.parametrize("work", [np.float32, np.float64],
                         ids=lambda d: np.dtype(d).name)
def test_compress_decompress_round_trip(monkeypatch, mode, work):
    """Integer-valued payloads survive wide→narrow→wide exactly, for
    both decompress flavors (reduce-add and allgather-restore)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    c = wire_compressor_for(np.dtype(work))
    assert c is not None and c.name == mode
    src = _int_valued(257, 1, work)
    arena = np.empty(512, c.wire_dtype)
    narrow = c.compress(src, arena)
    assert narrow.dtype == c.wire_dtype and narrow.size == src.size

    out = np.zeros_like(src)
    c.decompress_add(narrow, out)
    assert np.array_equal(out, src)
    c.decompress_add(narrow, out)  # reduce semantics: accumulates
    assert np.array_equal(out, src * 2)

    restored = np.empty_like(src)
    c.decompress_into(narrow, restored)
    assert np.array_equal(restored, src)


@pytest.mark.parametrize("mode", _MODES)
def test_quantize_inplace_is_idempotent(monkeypatch, mode):
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    c = wire_compressor_for(np.dtype(np.float32))
    chunk = (np.arange(100, dtype=np.float32) / 7.0) + 0.1
    arena = np.empty(128, c.wire_dtype)
    c.quantize_inplace(chunk, arena)
    once = chunk.copy()
    c.quantize_inplace(chunk, arena)
    assert np.array_equal(chunk, once), "quantize must be idempotent"


def test_fp16_saturates_not_raises(monkeypatch):
    """fp16's documented contract: out-of-range f32 saturates to inf
    without warnings — loud failure is the job of NaN/inf checks upstream,
    not a per-segment RuntimeWarning storm."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    c = wire_compressor_for(np.dtype(np.float32))
    src = np.array([1.0, 1e38, -1e38], np.float32)
    arena = np.empty(4, c.wire_dtype)
    narrow = c.compress(src, arena)
    assert np.isinf(narrow[1]) and np.isinf(narrow[2])


def test_raw_dtypes_and_off_knob_pass_through(monkeypatch):
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    for dt in (np.int32, np.int64, np.float16):
        assert wire_compressor_for(np.dtype(dt)) is None
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "none")
    assert wire_compressor_for(np.dtype(np.float32)) is None
    monkeypatch.delenv(env_mod.HOROVOD_WIRE_COMPRESSION)
    assert wire_compressor_for(np.dtype(np.float32)) is None


def test_unknown_compression_name_raises(monkeypatch):
    from horovod_tpu.common.exceptions import HorovodInternalError

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "zstd")
    with pytest.raises(HorovodInternalError, match="HOROVOD_WIRE_COMPRESSION"):
        wire_compressor_for(np.dtype(np.float32))


def test_wire_dtype_codes_are_frame_header_stable():
    """The codes ride in frame headers — renumbering them is a wire
    protocol break, so they are pinned here."""
    assert comp_mod.WIRE_DTYPE_RAW == 0
    assert WIRE_DTYPE_FP16 == 1
    assert WIRE_DTYPE_BF16 == 2
    assert comp_mod.WIRE_DTYPE_INT8 == 3
    assert comp_mod.WIRE_DTYPE_ONEBIT == 4
    assert comp_mod.WIRE_DTYPE_TOPK == 5


# ---------------------------------------------------------------------------
# ring allreduce end to end
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", _MODES)
@pytest.mark.parametrize("work", [np.float32, np.float64],
                         ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("n", [1, 7, 1023])
def test_compressed_ring_allreduce_bit_exact(monkeypatch, mode, work, n):
    """np=3 compressed ring allreduce == the wide-precision reference,
    bit for bit on EVERY rank, for odd counts that divide evenly by
    neither the world size nor the segment size."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size = 3
    dtype = np.dtype(work)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]
    expected = _expected_sum(inputs, dtype)
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for r in range(size):
        assert np.array_equal(outs[r], expected), r
    for r in range(1, size):
        assert outs[r].tobytes() == outs[0].tobytes(), \
            f"rank {r} bit-diverged from rank 0"


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_ring_tiny_segments(monkeypatch, mode):
    """HOROVOD_RING_SEGMENT_BYTES=1 (clamped to one element) exercises
    every segment-boundary edge in the compressed exchange."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, "1")
    size, n = 3, 13
    inputs = [_int_valued(n, r, np.float32) for r in range(size)]
    expected = _expected_sum(inputs, np.float32)
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for out in outs:
        assert np.array_equal(out, expected)


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_wire_bytes_are_half(monkeypatch, mode):
    """THE bandwidth claim, counter-asserted: f32 allreduce with a
    2-byte wire dtype puts exactly HALF the uncompressed payload bytes
    on the wire (digest-check frames are excluded from bytes_on_wire by
    design, so the ratio is exact, not approximate)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    before = wire_stats.snapshot()
    _compressed_allreduce([x.copy() for x in inputs])
    after = wire_stats.snapshot()

    bounds = cpu_ring._chunk_bounds(n, size)
    sent_elems = 0
    for idx in range(size):
        for s in range(size - 1):
            c = (idx - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
            c = (idx + 1 - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
    uncompressed = 2 * sent_elems * dtype.itemsize
    got = after.get("bytes_on_wire", 0) - before.get("bytes_on_wire", 0)
    assert got == uncompressed // 2, (got, uncompressed)
    comp_bytes = (after.get("compressed_bytes", 0)
                  - before.get("compressed_bytes", 0))
    assert comp_bytes >= got  # every wire byte passed through a cast


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_steady_state_zero_heap_copies(monkeypatch, mode):
    """The zero-copy guard holds WITH compression: casts go through
    persistent keyed arenas ("wire-send"/"wire-recv"/"wire-quant"), so a
    steady-state compressed ring step still materializes nothing."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    fbms = [cpu_ring.FusionBufferManager() for _ in range(size)]
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    _compressed_allreduce([x.copy() for x in inputs], fbms)  # warm

    before = wire_stats.snapshot()
    outs = _compressed_allreduce([x.copy() for x in inputs], fbms)
    after = wire_stats.snapshot()

    assert np.array_equal(outs[0], _expected_sum(inputs, dtype))
    assert after.get("heap_copies", 0) == before.get("heap_copies", 0), \
        "a compressed steady-state ring step materialized payload bytes"


# ---------------------------------------------------------------------------
# lossy codecs: int8 / onebit / topk<K> with error feedback
# ---------------------------------------------------------------------------

_LOSSY_MODES = ["int8", "onebit", "topk10"]


def _lossy_wire_bytes(n, size, dtype, comp):
    """Exact bytes-on-wire for one np=``size`` lossy allreduce: RS sends
    are per-SEGMENT encodes, AG sends are whole-chunk byte blobs (the
    byte-forwarding allgather), and wire_stats counts each data frame at
    BOTH endpoints."""
    bounds = cpu_ring._chunk_bounds(n, size)
    seg = cpu_ring._segment_elems(np.dtype(dtype))
    total = 0
    for idx in range(size):
        for s in range(size - 1):
            cn = int(bounds[(idx - s) % size + 1] - bounds[(idx - s) % size])
            for k in range(-(-cn // seg)):
                total += comp.wire_nbytes(
                    min(cn, (k + 1) * seg) - k * seg, np.dtype(dtype))
            cn = int(bounds[(idx + 1 - s) % size + 1]
                     - bounds[(idx + 1 - s) % size])
            if cn:
                total += comp.wire_nbytes(cn, np.dtype(dtype))
    return 2 * total


@pytest.mark.parametrize("mode", _LOSSY_MODES)
@pytest.mark.parametrize("work", [np.float32, np.float64],
                         ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("n", [1, 7, 1023])
def test_lossy_ring_allreduce_bit_identical(monkeypatch, mode, work, n):
    """np=3 lossy allreduce: every rank finishes BIT-IDENTICAL (the
    byte-forwarding allgather guarantee), including the variable-length
    topk path, and int8 lands within its quantization-error bound of the
    true sum."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size = 3
    dtype = np.dtype(work)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]
    expected = _expected_sum(inputs, dtype)
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for r in range(1, size):
        assert outs[r].tobytes() == outs[0].tobytes(), \
            f"rank {r} bit-diverged from rank 0 under {mode}"
    if mode == "int8":
        # ≤ scale/2 rounding error per encode, ≤ 4 encodes on any
        # element's path (2 RS hops + owner AG encode, with margin).
        atol = 4 * (float(np.abs(expected).max()) / 127.0) / 2 + 1e-6
        assert np.allclose(outs[0], expected, atol=atol), \
            (np.abs(outs[0] - expected).max(), atol)


@pytest.mark.parametrize("mode", _LOSSY_MODES)
def test_lossy_tiny_segments_bit_identical(monkeypatch, mode):
    """One-element segments exercise every per-segment size derivation
    in the lossy exchange (each segment carries its own scale/means/k)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, "1")
    size, n = 3, 13
    inputs = [_int_valued(n, r, np.float32) for r in range(size)]
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for r in range(1, size):
        assert outs[r].tobytes() == outs[0].tobytes(), r


@pytest.mark.parametrize("mode,ratio_bound", [
    ("int8", 0.30),     # ~1/4 + <f4 scale> per segment
    ("onebit", 0.08),   # ~1/32 + 8-byte means per segment
    ("topk10", 0.25),   # 10% density × 8-byte pairs on f32 = ~0.2
])
def test_lossy_wire_bytes_exact(monkeypatch, mode, ratio_bound):
    """THE bandwidth claim per codec, counter-asserted EXACTLY: every
    byte on the wire is derived from ``wire_nbytes`` over the shared
    bounds — and the achieved ratio beats the codec's coarse bound."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    comp = wire_compressor_for(dtype)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    before = wire_stats.snapshot()
    _compressed_allreduce([x.copy() for x in inputs])
    after = wire_stats.snapshot()

    got = after.get("bytes_on_wire", 0) - before.get("bytes_on_wire", 0)
    assert got == _lossy_wire_bytes(n, size, dtype, comp), mode

    bounds = cpu_ring._chunk_bounds(n, size)
    raw_elems = 0
    for idx in range(size):
        for s in range(size - 1):
            raw_elems += int(bounds[(idx - s) % size + 1]
                             - bounds[(idx - s) % size])
            raw_elems += int(bounds[(idx + 1 - s) % size + 1]
                             - bounds[(idx + 1 - s) % size])
    assert got <= 2 * raw_elems * dtype.itemsize * ratio_bound, \
        (mode, got, raw_elems)


def test_ef_accumulator_carries_forward():
    """Error feedback is load-bearing at the codec level: over repeated
    encodes of the SAME segment, the running mean of EF decodes converges
    to the true values while raw (no-EF) decodes keep the full one-shot
    quantization bias."""
    comp = comp_mod.OneBitCompressor()
    src = np.linspace(-1.0, 2.0, 64).astype(np.float32)
    ef = comp_mod.EfState()
    nb = comp.wire_nbytes(src.size, src.dtype)
    tot_ef = np.zeros_like(src)
    tot_raw = np.zeros_like(src)
    steps = 50
    for _ in range(steps):
        ef.begin(("t",))
        out = np.empty(nb, np.uint8)
        comp.encode(src, out, ef)
        dec = np.empty_like(src)
        comp.decode_into(out, dec)
        tot_ef += dec
        comp.encode(src, out)
        comp.decode_into(out, dec)
        tot_raw += dec
    err_ef = float(np.abs(tot_ef / steps - src).mean())
    err_raw = float(np.abs(tot_raw / steps - src).mean())
    assert err_ef < err_raw / 5, (err_ef, err_raw)


def test_ef_state_resets_on_shape_change():
    """A re-fused/re-sharded tensor must not absorb a stale residual:
    same slot, different segment shape or dtype → fresh zeros."""
    ef = comp_mod.EfState()
    ef.begin(("t",))
    r = ef.take(8, np.dtype(np.float32))
    r[:] = 1.0
    ef.begin(("t",))
    assert np.array_equal(ef.take(8, np.dtype(np.float32)),
                          np.ones(8, np.float32))  # carried
    ef.begin(("t",))
    assert not ef.take(9, np.dtype(np.float32)).any()  # size change
    ef.begin(("t",))
    assert not ef.take(9, np.dtype(np.float64)).any()  # dtype change
    ef.begin(("u",))
    assert not ef.take(9, np.dtype(np.float64)).any()  # new tensor key


@pytest.mark.parametrize("bad", ["topk0", "topk101", "topk999"])
def test_topk_density_out_of_range_raises(monkeypatch, bad):
    from horovod_tpu.common.exceptions import HorovodInternalError

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, bad)
    with pytest.raises(HorovodInternalError, match="topk density"):
        wire_compressor_for(np.dtype(np.float32))


def test_topk_density_knob_parses(monkeypatch):
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "topk27")
    c = wire_compressor_for(np.dtype(np.float32))
    assert c.name == "topk27" and c.density_pct == 27 and c.lossy


@pytest.mark.parametrize("code", [comp_mod.WIRE_DTYPE_INT8,
                                  comp_mod.WIRE_DTYPE_ONEBIT,
                                  comp_mod.WIRE_DTYPE_TOPK])
def test_lossy_wire_dtype_skew_fails_loudly(code):
    """Each new wire-dtype code trips the same header-bit skew detector
    as fp16: a receiver configured for raw must abort, never mis-decode
    a codec byte blob."""
    store = MemoryStore()

    def make(rank):
        return TcpMesh(rank, 2, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)

    m0, m1 = run_ranks(2, make)
    try:
        sdig, rdig = m0.new_digest(), m1.new_digest()
        blob = np.arange(36, dtype=np.uint8)
        m0.send(1, memoryview(blob).cast("B"), digest=sdig,
                wire_dtype=code)
        dest = np.empty_like(blob)
        with pytest.raises(Exception) as ei:
            m1.recv_into(0, memoryview(dest).cast("B"), digest=rdig,
                         wire_dtype=0)
        assert "HOROVOD_WIRE_COMPRESSION" in str(ei.value)
    finally:
        m0.close()
        m1.close()


def _train_np2(mode, ef_on, steps=80, lr=0.2):
    """np=2 data-parallel linear regression through the REAL ring
    machinery (one mesh per rank for the whole run, per-rank EfState as
    the op owns in production).  Returns the final full-batch MSE —
    asserted identical across ranks first, because the weights must stay
    bit-identical whatever the codec does."""
    size = 2
    rng = np.random.default_rng(7)
    X = rng.standard_normal((64, 16)).astype(np.float32)
    w_true = rng.standard_normal(16).astype(np.float32)
    y = X @ w_true
    final = [None] * size
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=20)
        try:
            dtype = np.dtype(np.float32)
            comp = wire_compressor_for(dtype)
            lossy = comp is not None and comp.lossy
            ef = comp_mod.EfState() if (ef_on and lossy) else None
            wide = cpu_ring._accum_dtype(dtype)
            group = list(range(size))
            Xr, yr = X[rank::size], y[rank::size]
            w = np.zeros(16, np.float32)
            for _ in range(steps):
                g = (Xr.T @ (Xr @ w - yr)).astype(np.float32)
                buf = g.copy()
                if ef is not None:
                    ef.begin(("w",))
                bounds = cpu_ring._ring_reduce_scatter(
                    mesh, buf, group, rank, wide, None, compressor=comp,
                    ef=ef)
                if lossy:
                    cpu_ring._ring_allgather_bytes(
                        mesh, buf, group, rank, bounds, comp, None)
                else:
                    if comp is not None:
                        own = (rank + 1) % size
                        cpu_ring._quantize_owned(
                            comp, buf[bounds[own]:bounds[own + 1]], None)
                    cpu_ring._ring_allgather_chunks(
                        mesh, buf, group, rank, bounds, None,
                        compressor=comp)
                w -= (lr / len(y)) * buf
            final[rank] = float(np.mean((X @ w - y) ** 2))
        finally:
            mesh.close()

    run_ranks(size, fn, timeout=120)
    assert final[0] == final[1], "ranks bit-diverged during training"
    return final[0]


def test_np2_convergence_ef_is_load_bearing(monkeypatch):
    """The tentpole's convergence proof: onebit-with-EF trains to within
    tolerance of the uncompressed run; forcing EF off leaves the
    quantization bias uncorrected and the loss detectably worse — the
    accumulator is load-bearing, not decorative."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "none")
    base = _train_np2("none", ef_on=False)

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "onebit")
    with_ef = _train_np2("onebit", ef_on=True)
    without_ef = _train_np2("onebit", ef_on=False)

    assert base < 1e-3, f"uncompressed baseline failed to converge: {base}"
    assert with_ef < base + 0.05, \
        f"EF run out of tolerance: {with_ef} vs base {base}"
    assert without_ef > 10 * max(with_ef, 1e-6) and without_ef > 0.01, \
        f"EF-off control not detectably worse: {without_ef} vs {with_ef}"


def test_compression_with_crc_and_chaos_corrupt(monkeypatch):
    """Corrupt injected on a COMPRESSED deferred frame is still caught by
    the step digest: integrity composes with compression."""
    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import (CoordinatedAbortError,
                                               FrameCorruptError,
                                               HorovodInternalError)

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    size = 2
    inputs = [_int_valued(101, r, np.float32) for r in range(size)]
    arrays = [x.copy() for x in inputs]
    store = MemoryStore()
    faults.configure("tcp.send:rank=0:nth=1:action=corrupt,2")
    try:
        def fn(rank):
            mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                           advertise_addr="127.0.0.1", timeout=10)
            try:
                buf = arrays[rank]
                wide = cpu_ring._accum_dtype(buf.dtype)
                comp = wire_compressor_for(buf.dtype)
                group = list(range(size))
                bounds = cpu_ring._ring_reduce_scatter(
                    mesh, buf, group, rank, wide, None, compressor=comp)
                own = (rank + 1) % size
                cpu_ring._quantize_owned(
                    comp, buf[bounds[own]:bounds[own + 1]], None)
                cpu_ring._ring_allgather_chunks(
                    mesh, buf, group, rank, bounds, None, compressor=comp)
            finally:
                mesh.close()

        with pytest.raises((FrameCorruptError, CoordinatedAbortError,
                            HorovodInternalError)) as ei:
            run_ranks(size, fn, timeout=30)
        assert "wire CRC" in str(ei.value) or "abort" in str(ei.value)
    finally:
        faults.reset()
