"""Cast-on-the-wire compression: bit-exactness across ranks, the
halved-bytes counter contract, and the zero-copy guard with compression
on.

Cross-rank bit-identity is the hard requirement (elastic recovery
snapshots compare rank outputs bit for bit): after reduce-scatter each
owner quantizes its own chunk through the wire dtype before allgather,
so no rank keeps wide precision the others never saw.  Payloads are
integer-valued and small so fp16/bf16 represent every partial sum
exactly — making ``np.sum`` in float64 a legal bit-for-bit reference
(and keeping fp16 off its pathological overflow-cast path).
"""

import numpy as np
import pytest

from horovod_tpu.backend import cpu_ring
from horovod_tpu.backend import compression as comp_mod
from horovod_tpu.backend.compression import (WIRE_DTYPE_BF16,
                                             WIRE_DTYPE_FP16,
                                             wire_compressor_for)
from horovod_tpu.common import env as env_mod
from horovod_tpu.core.timeline import wire_stats
from horovod_tpu.transport import MemoryStore, TcpMesh

from .test_transport import run_ranks

pytestmark = pytest.mark.smoke

_HAS_BF16 = True
try:
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    _HAS_BF16 = False

_MODES = ["fp16"] + (["bf16"] if _HAS_BF16 else [])


def _int_valued(n, rank, dtype):
    return ((np.arange(n) + rank) % 5 + rank + 1).astype(dtype)


def _expected_sum(inputs, dtype):
    acc = np.zeros(inputs[0].shape, np.float64)
    for x in inputs:
        acc += np.asarray(x, np.float64)
    return acc.astype(dtype)


def _compressed_allreduce(arrays, fbms=None, timeout=60):
    """Drive the exact RingAllreduce._ring_allreduce sequence — RS with
    compression, owner-chunk quantization, AG with compression — as
    thread ranks over an in-process mesh."""
    size = len(arrays)
    store = MemoryStore()

    def fn(rank):
        mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=15)
        try:
            buf = arrays[rank]
            wide = cpu_ring._accum_dtype(buf.dtype)
            comp = wire_compressor_for(buf.dtype)
            fbm = fbms[rank] if fbms is not None else None
            group = list(range(size))
            bounds = cpu_ring._ring_reduce_scatter(
                mesh, buf, group, rank, wide, fbm, compressor=comp)
            if comp is not None:
                own = (rank + 1) % size
                cpu_ring._quantize_owned(
                    comp, buf[bounds[own]:bounds[own + 1]], fbm)
            cpu_ring._ring_allgather_chunks(
                mesh, buf, group, rank, bounds, fbm, compressor=comp)
        finally:
            mesh.close()

    run_ranks(size, fn, timeout=timeout)
    return arrays


# ---------------------------------------------------------------------------
# compressor unit behavior
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", _MODES)
@pytest.mark.parametrize("work", [np.float32, np.float64],
                         ids=lambda d: np.dtype(d).name)
def test_compress_decompress_round_trip(monkeypatch, mode, work):
    """Integer-valued payloads survive wide→narrow→wide exactly, for
    both decompress flavors (reduce-add and allgather-restore)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    c = wire_compressor_for(np.dtype(work))
    assert c is not None and c.name == mode
    src = _int_valued(257, 1, work)
    arena = np.empty(512, c.wire_dtype)
    narrow = c.compress(src, arena)
    assert narrow.dtype == c.wire_dtype and narrow.size == src.size

    out = np.zeros_like(src)
    c.decompress_add(narrow, out)
    assert np.array_equal(out, src)
    c.decompress_add(narrow, out)  # reduce semantics: accumulates
    assert np.array_equal(out, src * 2)

    restored = np.empty_like(src)
    c.decompress_into(narrow, restored)
    assert np.array_equal(restored, src)


@pytest.mark.parametrize("mode", _MODES)
def test_quantize_inplace_is_idempotent(monkeypatch, mode):
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    c = wire_compressor_for(np.dtype(np.float32))
    chunk = (np.arange(100, dtype=np.float32) / 7.0) + 0.1
    arena = np.empty(128, c.wire_dtype)
    c.quantize_inplace(chunk, arena)
    once = chunk.copy()
    c.quantize_inplace(chunk, arena)
    assert np.array_equal(chunk, once), "quantize must be idempotent"


def test_fp16_saturates_not_raises(monkeypatch):
    """fp16's documented contract: out-of-range f32 saturates to inf
    without warnings — loud failure is the job of NaN/inf checks upstream,
    not a per-segment RuntimeWarning storm."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    c = wire_compressor_for(np.dtype(np.float32))
    src = np.array([1.0, 1e38, -1e38], np.float32)
    arena = np.empty(4, c.wire_dtype)
    narrow = c.compress(src, arena)
    assert np.isinf(narrow[1]) and np.isinf(narrow[2])


def test_raw_dtypes_and_off_knob_pass_through(monkeypatch):
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    for dt in (np.int32, np.int64, np.float16):
        assert wire_compressor_for(np.dtype(dt)) is None
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "none")
    assert wire_compressor_for(np.dtype(np.float32)) is None
    monkeypatch.delenv(env_mod.HOROVOD_WIRE_COMPRESSION)
    assert wire_compressor_for(np.dtype(np.float32)) is None


def test_unknown_compression_name_raises(monkeypatch):
    from horovod_tpu.common.exceptions import HorovodInternalError

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "zstd")
    with pytest.raises(HorovodInternalError, match="HOROVOD_WIRE_COMPRESSION"):
        wire_compressor_for(np.dtype(np.float32))


def test_wire_dtype_codes_are_frame_header_stable():
    """The codes ride in frame headers — renumbering them is a wire
    protocol break, so they are pinned here."""
    assert comp_mod.WIRE_DTYPE_RAW == 0
    assert WIRE_DTYPE_FP16 == 1
    assert WIRE_DTYPE_BF16 == 2


# ---------------------------------------------------------------------------
# ring allreduce end to end
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", _MODES)
@pytest.mark.parametrize("work", [np.float32, np.float64],
                         ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("n", [1, 7, 1023])
def test_compressed_ring_allreduce_bit_exact(monkeypatch, mode, work, n):
    """np=3 compressed ring allreduce == the wide-precision reference,
    bit for bit on EVERY rank, for odd counts that divide evenly by
    neither the world size nor the segment size."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size = 3
    dtype = np.dtype(work)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]
    expected = _expected_sum(inputs, dtype)
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for r in range(size):
        assert np.array_equal(outs[r], expected), r
    for r in range(1, size):
        assert outs[r].tobytes() == outs[0].tobytes(), \
            f"rank {r} bit-diverged from rank 0"


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_ring_tiny_segments(monkeypatch, mode):
    """HOROVOD_RING_SEGMENT_BYTES=1 (clamped to one element) exercises
    every segment-boundary edge in the compressed exchange."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    monkeypatch.setenv(env_mod.HOROVOD_RING_SEGMENT_BYTES, "1")
    size, n = 3, 13
    inputs = [_int_valued(n, r, np.float32) for r in range(size)]
    expected = _expected_sum(inputs, np.float32)
    outs = _compressed_allreduce([x.copy() for x in inputs])
    for out in outs:
        assert np.array_equal(out, expected)


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_wire_bytes_are_half(monkeypatch, mode):
    """THE bandwidth claim, counter-asserted: f32 allreduce with a
    2-byte wire dtype puts exactly HALF the uncompressed payload bytes
    on the wire (digest-check frames are excluded from bytes_on_wire by
    design, so the ratio is exact, not approximate)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    before = wire_stats.snapshot()
    _compressed_allreduce([x.copy() for x in inputs])
    after = wire_stats.snapshot()

    bounds = cpu_ring._chunk_bounds(n, size)
    sent_elems = 0
    for idx in range(size):
        for s in range(size - 1):
            c = (idx - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
            c = (idx + 1 - s) % size
            sent_elems += int(bounds[c + 1] - bounds[c])
    uncompressed = 2 * sent_elems * dtype.itemsize
    got = after.get("bytes_on_wire", 0) - before.get("bytes_on_wire", 0)
    assert got == uncompressed // 2, (got, uncompressed)
    comp_bytes = (after.get("compressed_bytes", 0)
                  - before.get("compressed_bytes", 0))
    assert comp_bytes >= got  # every wire byte passed through a cast


@pytest.mark.parametrize("mode", _MODES)
def test_compressed_steady_state_zero_heap_copies(monkeypatch, mode):
    """The zero-copy guard holds WITH compression: casts go through
    persistent keyed arenas ("wire-send"/"wire-recv"/"wire-quant"), so a
    steady-state compressed ring step still materializes nothing."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, mode)
    size, n = 3, 999
    dtype = np.dtype(np.float32)
    fbms = [cpu_ring.FusionBufferManager() for _ in range(size)]
    inputs = [_int_valued(n, r, dtype) for r in range(size)]

    _compressed_allreduce([x.copy() for x in inputs], fbms)  # warm

    before = wire_stats.snapshot()
    outs = _compressed_allreduce([x.copy() for x in inputs], fbms)
    after = wire_stats.snapshot()

    assert np.array_equal(outs[0], _expected_sum(inputs, dtype))
    assert after.get("heap_copies", 0) == before.get("heap_copies", 0), \
        "a compressed steady-state ring step materialized payload bytes"


def test_compression_with_crc_and_chaos_corrupt(monkeypatch):
    """Corrupt injected on a COMPRESSED deferred frame is still caught by
    the step digest: integrity composes with compression."""
    from horovod_tpu.common import faults
    from horovod_tpu.common.exceptions import (CoordinatedAbortError,
                                               FrameCorruptError,
                                               HorovodInternalError)

    monkeypatch.setenv(env_mod.HOROVOD_WIRE_COMPRESSION, "fp16")
    size = 2
    inputs = [_int_valued(101, r, np.float32) for r in range(size)]
    arrays = [x.copy() for x in inputs]
    store = MemoryStore()
    faults.configure("tcp.send:rank=0:nth=1:action=corrupt,2")
    try:
        def fn(rank):
            mesh = TcpMesh(rank, size, store, bind_addr="127.0.0.1",
                           advertise_addr="127.0.0.1", timeout=10)
            try:
                buf = arrays[rank]
                wide = cpu_ring._accum_dtype(buf.dtype)
                comp = wire_compressor_for(buf.dtype)
                group = list(range(size))
                bounds = cpu_ring._ring_reduce_scatter(
                    mesh, buf, group, rank, wide, None, compressor=comp)
                own = (rank + 1) % size
                cpu_ring._quantize_owned(
                    comp, buf[bounds[own]:bounds[own + 1]], None)
                cpu_ring._ring_allgather_chunks(
                    mesh, buf, group, rank, bounds, None, compressor=comp)
            finally:
                mesh.close()

        with pytest.raises((FrameCorruptError, CoordinatedAbortError,
                            HorovodInternalError)) as ei:
            run_ranks(size, fn, timeout=30)
        assert "wire CRC" in str(ei.value) or "abort" in str(ei.value)
    finally:
        faults.reset()
