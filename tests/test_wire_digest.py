"""Shadow (deferred) wire digests: algorithm properties and the
transport's digest-check frame protocol.

The load-bearing property for ``HOROVOD_WIRE_DIGEST=crc32`` is that a
chain of per-frame ``zlib.crc32`` updates over ANY segmentation equals
the crc32 of the concatenated bytes — that is what lets sender and
receiver agree without ever materializing the whole transfer.  fold64 is
not a streaming digest (it chains per-frame digests), so its contract is
different: both endpoints fold the same frame boundaries, and any
corruption/reorder/split change flips the value.
"""

import random
import zlib

import numpy as np
import pytest

from horovod_tpu.common import env as env_mod
from horovod_tpu.common.exceptions import (FrameCorruptError,
                                           HorovodInternalError)
from horovod_tpu.transport import digest as digest_mod
from horovod_tpu.transport.digest import (ALGO_CRC32, ALGO_FOLD64,
                                          StreamDigest, algo_from_name)

pytestmark = pytest.mark.smoke


def _random_splits(rng, data):
    """Cut `data` into a random number of contiguous frames (some may be
    empty — zero-length frames never go on the wire, but the digest must
    still tolerate short tails and single-byte frames)."""
    cuts = sorted(rng.randrange(len(data) + 1)
                  for _ in range(rng.randrange(1, 8)))
    bounds = [0] + cuts + [len(data)]
    return [data[bounds[i]:bounds[i + 1]] for i in range(len(bounds) - 1)
            if bounds[i + 1] > bounds[i]]


def test_crc32_chain_equals_whole_buffer_digest():
    """THE property: chained per-frame crc32 == crc32 of the concatenated
    payload, for random payloads cut at random frame boundaries."""
    rng = random.Random(0x9E37)
    for trial in range(50):
        data = rng.randbytes(rng.randrange(1, 4096))
        whole = zlib.crc32(data) & 0xFFFFFFFF
        dig = StreamDigest(ALGO_CRC32)
        for frame in _random_splits(rng, data):
            dig.update(frame)
        assert dig.value() == whole, trial


def test_crc32_chain_matches_across_different_segmentations():
    rng = random.Random(7)
    data = rng.randbytes(10_000)
    values = set()
    for _ in range(10):
        dig = StreamDigest(ALGO_CRC32)
        for frame in _random_splits(rng, data):
            dig.update(frame)
        values.add(dig.value())
    assert values == {zlib.crc32(data) & 0xFFFFFFFF}


def test_fold64_same_frames_agree():
    """Sender and receiver fold identical frame boundaries — the chains
    must agree, including odd tails that exercise the zero-padded word."""
    rng = random.Random(1)
    for n in (1, 7, 8, 9, 63, 64, 65, 4096, 4099):
        data = rng.randbytes(n)
        frames = _random_splits(rng, data)
        a, b = StreamDigest(ALGO_FOLD64), StreamDigest(ALGO_FOLD64)
        for f in frames:
            a.update(f)
            b.update(f)
        assert a.value() == b.value()
        assert a.frames == b.frames == len(frames)


def test_fold64_detects_single_bit_flips():
    rng = random.Random(2)
    data = bytearray(rng.randbytes(1024))
    ref = StreamDigest(ALGO_FOLD64)
    ref.update(bytes(data))
    for _ in range(64):
        i = rng.randrange(len(data))
        bit = 1 << rng.randrange(8)
        data[i] ^= bit
        dig = StreamDigest(ALGO_FOLD64)
        dig.update(bytes(data))
        assert dig.value() != ref.value(), f"missed flip at byte {i}"
        data[i] ^= bit  # restore


def test_fold64_is_order_sensitive():
    """Swapped frames must change the chain (the multiplicative chain
    step exists exactly for this — a plain sum would commute)."""
    a, b = b"x" * 100, b"y" * 100
    d1, d2 = StreamDigest(ALGO_FOLD64), StreamDigest(ALGO_FOLD64)
    d1.update(a)
    d1.update(b)
    d2.update(b)
    d2.update(a)
    assert d1.value() != d2.value()


def test_fold64_framing_is_part_of_the_digest():
    """The same bytes split differently give a different fold64 chain —
    frame boundaries are protocol state, so a misframed stream cannot
    collide with the honest one by construction."""
    data = b"q" * 256
    d1, d2 = StreamDigest(ALGO_FOLD64), StreamDigest(ALGO_FOLD64)
    d1.update(data)
    d2.update(data[:100])
    d2.update(data[100:])
    assert d1.value() != d2.value()


def test_fold64_low_entropy_payloads_spread():
    """All-zeros vs all-ones vs length variants must not collide (the
    golden-ratio mix term covers degenerate word sums)."""
    vals = set()
    for payload in (b"\x00" * 64, b"\x00" * 72, b"\xff" * 64, b"\x01" * 64):
        d = StreamDigest(ALGO_FOLD64)
        d.update(payload)
        vals.add(d.value())
    assert len(vals) == 4


def test_digest_accepts_views_and_arrays():
    arr = np.arange(16, dtype=np.float64)
    d1, d2 = StreamDigest(ALGO_FOLD64), StreamDigest(ALGO_FOLD64)
    d1.update(arr.tobytes())
    d2.update(memoryview(arr).cast("B"))
    assert d1.value() == d2.value()


def test_algo_names_round_trip():
    assert algo_from_name("crc32") == ALGO_CRC32
    assert algo_from_name("fold64") == ALGO_FOLD64
    with pytest.raises(HorovodInternalError):
        algo_from_name("md5")
    with pytest.raises(HorovodInternalError):
        StreamDigest(99)


# ---------------------------------------------------------------------------
# transport protocol: deferred frames + the digest-check frame
# ---------------------------------------------------------------------------


def _mesh_pair():
    from horovod_tpu.transport import MemoryStore, TcpMesh

    from .test_transport import run_ranks

    store = MemoryStore()

    def make(rank):
        return TcpMesh(rank, 2, store, bind_addr="127.0.0.1",
                       advertise_addr="127.0.0.1", timeout=10)

    return run_ranks(2, make)


@pytest.mark.parametrize("algo", ["fold64", "crc32"])
def test_deferred_frames_round_trip_and_verify(monkeypatch, algo):
    """Segment frames with deferred digests land correctly and the
    digest-check frame closes the step cleanly for both algorithms."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_DIGEST, algo)
    m0, m1 = _mesh_pair()
    try:
        assert m0.deferred_digests and m1.deferred_digests
        payloads = [np.arange(64, dtype=np.float32) * (i + 1)
                    for i in range(3)]

        sdig, rdig = m0.new_digest(), m1.new_digest()
        for p in payloads:
            m0.send(1, memoryview(p).cast("B"), digest=sdig)
        m0.send_step_digest(1, sdig, len(payloads))

        for p in payloads:
            dest = np.empty_like(p)
            m1.recv_into(0, memoryview(dest).cast("B"), digest=rdig)
            assert np.array_equal(dest, p)
        m1.verify_step_digest(0, rdig, len(payloads))  # must not raise
    finally:
        m0.close()
        m1.close()


def test_deferred_digest_catches_corruption():
    """A corrupt injected on a deferred frame's wire bytes sails through
    the (absent) inline CRC but MUST be caught by the step digest —
    detection granularity changed, the guarantee did not."""
    from horovod_tpu.common import faults

    m0, m1 = _mesh_pair()
    try:
        faults.configure("tcp.send:rank=0:nth=2:action=corrupt,3")
        sdig, rdig = m0.new_digest(), m1.new_digest()
        payloads = [np.full(32, float(i), np.float32) for i in range(3)]
        for p in payloads:
            m0.send(1, memoryview(p).cast("B"), digest=sdig)
        m0.send_step_digest(1, sdig, len(payloads))
        for p in payloads:
            dest = np.empty_like(p)
            m1.recv_into(0, memoryview(dest).cast("B"), digest=rdig)
        with pytest.raises(FrameCorruptError) as ei:
            m1.verify_step_digest(0, rdig, len(payloads))
        assert "wire CRC" in str(ei.value)
    finally:
        faults.reset()
        m0.close()
        m1.close()


def test_shadow_knob_skew_fails_loudly(monkeypatch):
    """One peer deferring while the other expects inline CRC must poison
    the stream (mixed-config mesh), not silently mis-read."""
    m0, m1 = _mesh_pair()
    try:
        sdig = m0.new_digest()
        p = np.arange(16, dtype=np.float32)
        m0.send(1, memoryview(p).cast("B"), digest=sdig)  # deferred frame
        dest = np.empty_like(p)
        with pytest.raises(Exception) as ei:
            m1.recv_into(0, memoryview(dest).cast("B"))  # expects inline
        assert "HOROVOD_WIRE_CRC_SHADOW" in str(ei.value)
    finally:
        m0.close()
        m1.close()


def test_wire_dtype_skew_fails_loudly():
    """A frame stamped with a wire dtype the receiver is not configured
    for must abort (HOROVOD_WIRE_COMPRESSION skew), never mis-decode."""
    m0, m1 = _mesh_pair()
    try:
        sdig, rdig = m0.new_digest(), m1.new_digest()
        p = np.arange(16, dtype=np.float16)
        m0.send(1, memoryview(p).cast("B"), digest=sdig, wire_dtype=1)
        dest = np.empty_like(p)
        with pytest.raises(Exception) as ei:
            m1.recv_into(0, memoryview(dest).cast("B"), digest=rdig,
                         wire_dtype=0)
        assert "HOROVOD_WIRE_COMPRESSION" in str(ei.value)
    finally:
        m0.close()
        m1.close()


def test_digest_algo_skew_fails_loudly(monkeypatch):
    """The check frame carries the algorithm code; a peer verifying with
    a different HOROVOD_WIRE_DIGEST must abort loudly."""
    m0, m1 = _mesh_pair()
    try:
        sdig = digest_mod.StreamDigest(ALGO_CRC32)
        rdig = m1.new_digest()  # fold64 default
        assert rdig.algo == ALGO_FOLD64
        p = np.arange(8, dtype=np.float32)
        m0.send(1, memoryview(p).cast("B"), digest=sdig)
        m0.send_step_digest(1, sdig, 1)
        dest = np.empty_like(p)
        m1.recv_into(0, memoryview(dest).cast("B"), digest=rdig)
        with pytest.raises(Exception) as ei:
            m1.verify_step_digest(0, rdig, 1)
        assert "HOROVOD_WIRE_DIGEST" in str(ei.value)
    finally:
        m0.close()
        m1.close()


def test_shadow_off_restores_inline_crc(monkeypatch):
    """HOROVOD_WIRE_CRC_SHADOW=0: the ring passes no digests and every
    frame carries the inline CRC again (the PR-4 behavior)."""
    monkeypatch.setenv(env_mod.HOROVOD_WIRE_CRC_SHADOW, "0")
    m0, m1 = _mesh_pair()
    try:
        assert not m0.deferred_digests
        assert m0.new_digest() is not None  # digests still constructible
        p = np.arange(16, dtype=np.float32)
        m0.send(1, memoryview(p).cast("B"))
        dest = np.empty_like(p)
        m1.recv_into(0, memoryview(dest).cast("B"))
        assert np.array_equal(dest, p)
    finally:
        m0.close()
        m1.close()
