"""TPU process-per-chip launch model: pod-slice discovery, per-slot chip
visibility env, and the --start-timeout watchdog.

Reference role: ``runner/gloo_run.py:65-76`` per-slot env construction; on
TPU the launcher additionally carves chips into one-per-process windows
(no reference equivalent — NCCL jobs use CUDA_VISIBLE_DEVICES instead)."""

import os
import subprocess
import sys
import textwrap
import time

from horovod_tpu.runner import tpu_topology
from horovod_tpu.runner.tpu_topology import (
    discover,
    parse_accelerator_type,
    slot_tpu_env,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_accelerator_type():
    # v5e counts chips directly; v4 counts TensorCores (2/chip).
    assert parse_accelerator_type("v5litepod-16") == (16, 4)
    assert parse_accelerator_type("v5litepod-4") == (4, 4)
    assert parse_accelerator_type("v4-32") == (16, 4)
    assert parse_accelerator_type("v3-8") == (4, 4)
    assert parse_accelerator_type("gpu-8") is None
    assert parse_accelerator_type("nonsense") is None


def test_discover_pod_slice(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1w-0,t1w-1,t1w-2,t1w-3")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    assert discover() == "t1w-0:4,t1w-1:4,t1w-2:4,t1w-3:4"


def test_discover_single_host_slice(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    assert discover() == "localhost:8"


def test_discover_absent(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert discover() is None


def test_slot_tpu_env_disjoint_chips():
    """Two workers on one host must see disjoint devices (VERDICT #44)."""
    envs = [slot_tpu_env(i, i, [("localhost", 4)]) for i in range(4)]
    chips = {e["TPU_VISIBLE_CHIPS"] for e in envs}
    assert chips == {"0", "1", "2", "3"}
    ports = {e["TPU_PROCESS_PORT"] for e in envs}
    assert len(ports) == 4
    # every process agrees on the tiling and the address list
    assert {e["TPU_PROCESS_BOUNDS"] for e in envs} == {"2,2,1"}
    assert len({e["TPU_PROCESS_ADDRESSES"] for e in envs}) == 1
    assert all(e["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1" for e in envs)


def test_slot_tpu_env_multi_host_slice_wide():
    """The process tiling must cover the whole slice, not one host — a
    per-host grid would stitch each host into an independent slice."""
    hosts = [("w0", 4), ("w1", 4), ("w2", 4), ("w3", 4)]
    # rank 5 = host w1, local_rank 1, 4 chips/host
    env = slot_tpu_env(5, 1, hosts)
    assert env["TPU_PROCESS_BOUNDS"] == "4,4,1"          # 16 processes
    assert env["CLOUD_TPU_TASK_ID"] == "5"               # global rank
    addrs = env["TPU_PROCESS_ADDRESSES"].split(",")
    assert len(addrs) == 16
    assert addrs[0] == "w0:8476" and addrs[4] == "w1:8476"
    assert env["TPU_PROCESS_PORT"] == "8477"


def test_slot_tpu_env_partial_last_host_consistent():
    """-np that doesn't fill the last host: every rank must still derive
    the identical tiling (6 procs on 2x4-chip hosts → 2,3,1 and 6 addrs)."""
    hosts = [("w0", 4), ("w1", 2)]
    envs = [slot_tpu_env(r, lr, hosts)
            for r, lr in [(0, 0), (3, 3), (4, 0), (5, 1)]]
    assert {e["TPU_PROCESS_BOUNDS"] for e in envs} == {"2,3,1"}
    assert {len(e["TPU_PROCESS_ADDRESSES"].split(",")) for e in envs} == {6}


def test_host_slots_of():
    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import host_slots_of

    slots = get_host_assignments(parse_hosts("a:4,b:4"), 6)
    assert host_slots_of(slots) == [("a", 4), ("b", 2)]


def test_process_bounds_shapes():
    assert tpu_topology._process_bounds(1) == "1,1,1"
    assert tpu_topology._process_bounds(2) == "1,2,1"
    assert tpu_topology._process_bounds(4) == "2,2,1"
    assert tpu_topology._process_bounds(8) == "2,4,1"


def test_hvdrun_exports_chip_binding(tmp_path):
    """hvdrun on a (simulated) TPU VM gives each slot its own chip."""
    script = tmp_path / "show.py"
    script.write_text(textwrap.dedent("""
        import os
        print("CHIP", os.environ["HOROVOD_RANK"],
              os.environ.get("TPU_VISIBLE_CHIPS"), flush=True)
    """))
    env = dict(os.environ, TPU_ACCELERATOR_TYPE="v5litepod-4")
    env.pop("TPU_WORKER_HOSTNAMES", None)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "-H", "localhost:2", sys.executable, str(script)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=60, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CHIP 0 0" in proc.stdout and "CHIP 1 1" in proc.stdout


def test_hvdrun_no_chip_binding_off_tpu(tmp_path):
    script = tmp_path / "show.py"
    script.write_text(
        "import os; print('CHIP', repr(os.environ.get('TPU_VISIBLE_CHIPS')))")
    env = dict(os.environ)
    env.pop("TPU_ACCELERATOR_TYPE", None)
    env.pop("TPU_WORKER_HOSTNAMES", None)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "1",
         sys.executable, str(script)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=60, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CHIP None" in proc.stdout


def test_start_timeout_aborts_unstarted_job(tmp_path):
    """A worker that never calls hvd.init() must fail the job at
    --start-timeout, not hang forever (VERDICT: --start-timeout was parsed
    and never used)."""
    script = tmp_path / "stall.py"
    script.write_text("import time; time.sleep(60)\n")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--start-timeout", "3", sys.executable, str(script)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=45)
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0
    assert "failed to start" in proc.stderr
    assert elapsed < 30, elapsed
