"""Native kernel tests: bit-exact parity with the numpy fallback paths
(reference analog: half.cc conversions and adasum.h fused loops are the
C++ twins of these)."""

import ml_dtypes
import numpy as np
import pytest

from horovod_tpu import _native


pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def native_lib():
    lib = _native.lib()
    if lib is None:
        pytest.skip("native kernels unavailable (no compiler?)")
    return lib


def test_builds_and_probes(native_lib):
    assert native_lib.hvd_native_abi_version() == 1


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_add_inplace_wide(native_lib, dtype):
    rng = np.random.default_rng(0)
    a = rng.standard_normal(1013).astype(dtype)
    b = rng.standard_normal(1013).astype(dtype)
    exp = a + b
    assert _native.add_inplace(a, b)
    np.testing.assert_array_equal(a, exp)


@pytest.mark.parametrize("dtype", [ml_dtypes.bfloat16, np.float16])
def test_add_inplace_narrow_matches_widen_add(native_lib, dtype):
    """Narrow adds must equal numpy's widen-add-narrow (round-to-nearest-
    even both ways), including halfway-rounding cases."""
    rng = np.random.default_rng(1)
    a32 = rng.standard_normal(4096).astype(np.float32)
    b32 = rng.standard_normal(4096).astype(np.float32)
    a = a32.astype(dtype)
    b = b32.astype(dtype)
    exp = (a.astype(np.float32) + b.astype(np.float32)).astype(dtype)
    got = a.copy()
    assert _native.add_inplace(got, b)
    np.testing.assert_array_equal(got.view(np.uint16), exp.view(np.uint16))


@pytest.mark.parametrize("dtype", [ml_dtypes.bfloat16, np.float16,
                                   np.float32, np.float64])
def test_scale_inplace(native_lib, dtype):
    rng = np.random.default_rng(2)
    buf = rng.standard_normal(777).astype(np.float32).astype(dtype)
    exp = (buf.astype(np.float32) * np.float32(0.125)).astype(dtype)
    got = buf.copy()
    assert _native.scale_inplace(got, 0.125)
    if np.dtype(dtype).itemsize == 2:
        np.testing.assert_array_equal(got.view(np.uint16),
                                      exp.view(np.uint16))
    else:
        np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_narrow_special_values(native_lib):
    """inf/nan/zero survive the bit-level conversions."""
    for dtype in (ml_dtypes.bfloat16, np.float16):
        a = np.array([np.inf, -np.inf, 0.0, -0.0, np.nan, 1.0],
                     dtype=dtype)
        b = np.array([1.0, 1.0, 0.0, 0.0, 1.0, np.inf], dtype=dtype)
        got = a.copy()
        assert _native.add_inplace(got, b)
        exp = (a.astype(np.float32) + b.astype(np.float32)).astype(dtype)
        # NaN payloads may differ; compare NaN-ness then values elsewhere
        g32, e32 = got.astype(np.float32), exp.astype(np.float32)
        assert np.array_equal(np.isnan(g32), np.isnan(e32))
        mask = ~np.isnan(e32)
        np.testing.assert_array_equal(g32[mask], e32[mask])


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_dot3(native_lib, dtype):
    rng = np.random.default_rng(3)
    a = rng.standard_normal(511).astype(dtype)
    b = rng.standard_normal(511).astype(dtype)
    out = _native.dot3(a, b)
    assert out is not None
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    np.testing.assert_allclose(
        out, [a64 @ b64, a64 @ a64, b64 @ b64], rtol=1e-12)


def test_combine_inplace(native_lib):
    rng = np.random.default_rng(4)
    a = rng.standard_normal(129).astype(np.float32)
    b = rng.standard_normal(129).astype(np.float32)
    exp = np.float32(0.75) * a + np.float32(-0.25) * b
    got = a.copy()
    assert _native.combine_inplace(got, b, 0.75, -0.25)
    np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_disabled_by_env(monkeypatch):
    """HOROVOD_DISABLE_NATIVE must force the numpy fallback."""
    import importlib

    import horovod_tpu._native as nat

    monkeypatch.setenv("HOROVOD_DISABLE_NATIVE", "1")
    fresh = importlib.reload(nat)
    try:
        assert fresh.lib() is None
        a = np.ones(4, np.float32)
        assert not fresh.add_inplace(a, a)
    finally:
        monkeypatch.delenv("HOROVOD_DISABLE_NATIVE")
        importlib.reload(nat)


def test_non_contiguous_falls_back(native_lib):
    a = np.ones((4, 4), np.float32)[:, 0]
    b = np.ones(4, np.float32)
    assert not _native.add_inplace(a, b)


def test_fp16_subnormal_exactness(native_lib):
    """Subnormal fp16 (|x| < 2^-14) must convert exactly — the initial
    implementation halved them (exponent off by one)."""
    bits = np.array([0x0001, 0x0200, 0x03ff, 0x8001, 0x83ff, 0x0400],
                    dtype=np.uint16)
    a = bits.view(np.float16)
    b = np.zeros_like(a)
    got = a.copy()
    assert _native.add_inplace(got, b)  # x + 0 round-trips exactly
    np.testing.assert_array_equal(got.view(np.uint16), bits)
    # and a subnormal sum that stays subnormal
    x = np.full(8, 2.98023e-08, np.float16)   # smallest subnormal
    y = x.copy()
    assert _native.add_inplace(y, x)
    exp = (x.astype(np.float32) * 2).astype(np.float16)
    np.testing.assert_array_equal(y.view(np.uint16), exp.view(np.uint16))


def test_add_rejects_mismatched_sizes(native_lib):
    a = np.ones(8, np.float32)
    b = np.ones(4, np.float32)
    assert not _native.add_inplace(a, b)
    assert _native.dot3(a, b) is None
    assert not _native.combine_inplace(a, b, 1.0, 1.0)
