"""hvd-mck proto: the elastic-protocol checker's acceptance contract.

Mirror of tests/test_mck.py for the second protocol under the engine.
Five claims, each load-bearing for trusting the elastic control plane:

- **clean and COMPLETE**: every scenario fully explores (never
  truncated) with zero violations — the deployment claim for the epoch
  protocol under message reordering, crashes, and clock jumps.
- **mutants die**: every seeded protocol bug (proto_mutations.py) is
  killed within the configured bounds by one of its expected violation
  classes, with a reproducing schedule.
- **reduction is sound**: the sleep-set footprints (ProtoExecution.
  touches) prune schedules, never verdicts — a reduced run and an
  unreduced run agree.
- **byte-level crashes collapse to frame boundaries**: the journal's
  longest-valid-prefix replay makes a crash at ANY byte offset recover
  to a whole-transaction state, which is what lets the torn sweep check
  frame boundaries and honestly claim every byte.
- **truncation is honest**: hitting the schedule cap reports incomplete
  and fails the CI smoke gate — never silently passes as exhaustive.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from horovod_tpu.tools.mck import main  # noqa: E402
from horovod_tpu.tools.mck.explore import check, explore  # noqa: E402
from horovod_tpu.tools.mck.proto_model import (  # noqa: E402
    _replay,
    proto_execution_factory,
    proto_unit,
)
from horovod_tpu.tools.mck.proto_mutations import PROTO_MUTATIONS  # noqa: E402
from horovod_tpu.tools.mck.proto_scenarios import PROTO_SCENARIOS  # noqa: E402
from horovod_tpu.transport.journal import (  # noqa: E402
    JOURNAL_MAGIC,
    OP_SET,
    encode_group,
    pack_frame,
)


def _explore(name, mutation=None, **kw):
    return explore(PROTO_SCENARIOS[name], "proto", mutation=mutation,
                   execution_factory=proto_execution_factory,
                   unit_fn=proto_unit, **kw)


def _check(name, mutation=None, **kw):
    return check(PROTO_SCENARIOS[name], "proto", mutation=mutation,
                 execution_factory=proto_execution_factory,
                 unit_fn=proto_unit, **kw)


# ---------------------------------------------------------------------------
# the deployment claim: clean AND complete on every scenario
# ---------------------------------------------------------------------------

# The two biggest state spaces (clock-jump scenarios: ~10s each) ride
# the slow lane; ci/lint.sh's `proto --smoke` still explores every
# scenario on every CI run, so tier-1 skipping them loses no coverage.
_SLOW_SCENARIOS = {"lease_expiry", "outage_regrace"}


@pytest.mark.parametrize(
    "name",
    [pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_SCENARIOS
     else n for n in sorted(PROTO_SCENARIOS)])
def test_proto_exhaustive_and_clean(name):
    res = _check(name)
    assert res.complete, (
        f"proto run over {name!r} truncated at {res.schedules} schedules "
        "— an incomplete exploration is not a proof")
    assert res.ok, (
        f"proto violations in {name!r}: "
        + "; ".join(f"{v.name}: {v.detail}" for v in res.violations.values()))


def test_proto_is_deterministic():
    # Replay-based DFS over the protocol generators must be exactly
    # reproducible: same scenario, same schedule count, same max depth.
    a = _explore("driver_crash_recovery")
    b = _explore("driver_crash_recovery")
    assert (a.schedules, a.max_depth) == (b.schedules, b.max_depth)
    assert a.ok and b.ok


# ---------------------------------------------------------------------------
# the reduction's soundness canary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["txn_crash", "stale_race", "np2_demotion"])
def test_sleep_sets_prune_schedules_not_verdicts(name):
    # The per-location footprints (ProtoExecution.touches) are the one
    # place an UNDER-approximation would silently hide interleavings, so
    # diff a reduced run against an unreduced one: identical verdicts,
    # fewer-or-equal schedules.
    reduced = _explore(name)
    full = _explore(name, sleep_sets=False)
    assert sorted(reduced.violations) == sorted(full.violations) == []
    assert reduced.complete and full.complete
    assert reduced.schedules <= full.schedules


@pytest.mark.parametrize("name", ["txn_crash", "stale_race"])
def test_mutants_die_without_sleep_sets_too(name):
    # And the kill verdicts agree as well: a seeded bug found only
    # thanks to pruning (or only without it) would mean the reduction
    # changes semantics.
    muts = [m for m in PROTO_MUTATIONS.values() if m.scenario == name]
    assert muts
    for mut in muts:
        reduced = _explore(name, mutation=mut)
        full = _explore(name, mutation=mut, sleep_sets=False)
        assert set(reduced.violations) & mut.expected
        assert set(full.violations) & mut.expected


# ---------------------------------------------------------------------------
# the mutation-kill suite: the checker's checker
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "name",
    [pytest.param(n, marks=pytest.mark.slow)
     if PROTO_MUTATIONS[n].scenario in _SLOW_SCENARIOS else n
     for n in sorted(PROTO_MUTATIONS)])
def test_proto_mutation_killed(name):
    mut = PROTO_MUTATIONS[name]
    res = _check(mut.scenario, mutation=mut)
    caught = set(res.violations) & mut.expected
    assert caught, (
        f"mutant {name!r} SURVIVED the exhaustive run (expected one of "
        f"{sorted(mut.expected)}, found {sorted(res.violations)}): the "
        "configured bounds no longer catch seeded protocol bugs")
    for cls in caught:
        assert res.violations[cls].schedule, (
            f"kill of {name!r} by {cls} carries no reproducing schedule")


def test_proto_mutation_suite_is_nontrivial():
    # At least the ISSUE's five classic control-plane bugs, each on the
    # side and scenario where it can actually bite.
    assert {"apply_before_journal", "group_split",
            "stale_epoch_check_removed", "blacklist_after_poll",
            "regrace_dropped"} <= set(PROTO_MUTATIONS)


# ---------------------------------------------------------------------------
# the negotiation fan-in degrade model (fanin_model.py)
# ---------------------------------------------------------------------------

def test_fanin_sleep_sets_prune_schedules_not_verdicts():
    # The fan-in footprints prune ~99% of the schedule space, which is
    # exactly when an unsound footprint would hide a bug silently — so
    # diff reduced vs unreduced where the unreduced run still completes
    # (bound 0; crash and clock actions are free, so bound 0 already
    # explores the aggregator crashed and staled at every position).
    reduced = _explore("fanin_degrade", bound=0)
    full = _explore("fanin_degrade", bound=0, sleep_sets=False)
    assert sorted(reduced.violations) == sorted(full.violations) == []
    assert reduced.complete and full.complete
    assert reduced.schedules <= full.schedules
    mut = PROTO_MUTATIONS["fanin_bits_dropped"]
    mreduced = _explore("fanin_degrade", mutation=mut, bound=0)
    mfull = _explore("fanin_degrade", mutation=mut, bound=0,
                     sleep_sets=False)
    assert set(mreduced.violations) & mut.expected
    assert set(mfull.violations) & mut.expected


def test_fanin_degrade_falls_back_direct_with_o_hosts_ingress():
    # One deterministic schedule through the model itself: a clean tree
    # round lands ONE bundle at the coordinator (vs 3 worker frames —
    # the O(hosts)-vs-O(ranks) claim in miniature), then the aggregator
    # is crashed mid-collect and the heartbeat staled: the conviction
    # must veto the host, degrade everyone to direct, and the retry
    # round must re-deliver every announced bit exactly.
    from horovod_tpu.tools.mck.fanin_model import FANIN_DEGRADE, \
        FaninExecution

    ex = FaninExecution(FANIN_DEGRADE)
    script = [
        ("p", "m4"), ("p", "m5"),    # members push to the aggregator
        ("p", "agg"),                # fold_host -> one bundle upward
        ("p", "coord"),              # round 0 completes off 1 frame
        ("p", "agg"),                # relay the agreed mask down
        ("p", "m4"), ("p", "m5"),    # consume cycle-0 replies
        ("p", "m4"),                 # cycle 1: m4 pushes to the agg...
        ("c", "agg"),                # ...which dies holding its frame
        ("k", 0),                    # heartbeat goes stale
        ("p", "m4"),                 # conviction -> abort -> veto
        ("p", "m4"), ("p", "m5"),    # retry DIRECT (full re-announce)
        ("p", "coord"),              # round 1 completes off 2 frames
        ("p", "m4"), ("p", "m5"),    # consume cycle-1 replies
    ]
    for act in script:
        assert act in ex.enabled_actions(), (act, ex.trace)
        ex.step(act)
    assert ex.final_check() is None, ex.final_check()
    assert ex.vetoed and ex.mode == "direct" and ex.fallbacks == 1
    masks = FANIN_DEGRADE.masks
    tree, direct = ex.completions
    # tree round: one bundle covers all three ranks, AND-exact
    assert tree["ingress_frames"] == 1
    assert tree["covered"] == (3, 4, 5)
    assert tree["agreed"] == masks["agg"] & masks["m4"] & masks["m5"]
    # degraded round: per-rank direct frames (the dead aggregator's
    # rank is excused), still AND-exact — nothing consumed by the dead
    # aggregator was lost, because the members re-announced in full
    assert direct["ingress_frames"] == 2
    assert direct["covered"] == (4, 5)
    assert direct["agreed"] == masks["m4"] & masks["m5"]


def test_fanin_listed_with_proto_scenarios(capsys):
    assert main(["proto", "--list"]) == 0
    out = capsys.readouterr().out
    assert "fanin_degrade" in out
    assert "fanin_bits_dropped" in out


# ---------------------------------------------------------------------------
# byte-level crash points collapse to frame boundaries
# ---------------------------------------------------------------------------

def test_byte_level_crash_points_collapse_to_frame_boundaries():
    # The torn sweep checks frame-boundary prefixes but the claim is
    # per BYTE: a crash may truncate the journal anywhere.  The bridge
    # is the longest-valid-prefix replay — prove it on a real blob by
    # replaying every byte prefix and checking each lands exactly on
    # the nearest preceding whole-frame state, never a half-group.
    frames = [
        pack_frame(JOURNAL_MAGIC),
        pack_frame(encode_group([(OP_SET, "driver/epoch", b"1"),
                                 (OP_SET, "lease/h0:0", b"{}")])),
        pack_frame(encode_group([(OP_SET, "driver/epoch", b"2"),
                                 (OP_SET, "metrics/rank-0", b"x" * 7)])),
    ]
    blob = b"".join(frames)
    boundary_states = []
    off = 0
    for frame in frames:
        off += len(frame)
        boundary_states.append(_replay(blob[:off]))
    # Group atomicity: successive boundaries differ by whole
    # transactions (epoch 1 + lease together, then epoch 2 + metrics).
    assert boundary_states[1]["driver/epoch"] == b"1"
    assert "lease/h0:0" in boundary_states[1]
    assert boundary_states[2]["metrics/rank-0"] == b"x" * 7

    for cut in range(len(blob) + 1):
        state = _replay(blob[:cut])
        assert state in [{}] + boundary_states, (
            f"byte cut at {cut} replays to a state that is no "
            f"transaction boundary: {state!r}")
    # And a cut strictly inside the last frame must fall BACK to the
    # previous boundary (longest VALID prefix, not best-effort parse).
    mid_last = len(blob) - len(frames[-1]) + 3
    assert _replay(blob[:mid_last]) == boundary_states[1]


# ---------------------------------------------------------------------------
# truncation honesty + CLI contract
# ---------------------------------------------------------------------------

def test_truncated_run_is_not_a_proof():
    res = _explore("tick_posts", max_schedules=3)
    assert res.truncated and not res.complete
    assert res.schedules <= 3


def test_cli_scenarios_pass_clean(capsys):
    assert main(["proto", "--scenario", "txn_crash",
                 "--scenario", "stale_race", "--smoke", "-q"]) == 0
    out = capsys.readouterr().out
    assert "no violations" in out


def test_cli_inject_finds_the_seeded_bug(capsys):
    # The lint lane's teeth guard: a seeded bug run as a plain check
    # must exit 1 — violations found — specifically, not a crash.
    assert main(["proto", "--inject", "stale_epoch_check_removed",
                 "-q"]) == 1
    out = capsys.readouterr().out
    assert "stale-report-acted" in out


def test_cli_single_mutant_killed(capsys):
    assert main(["proto", "--mutation", "group_split"]) == 0
    out = capsys.readouterr().out
    assert "KILLED by torn-group" in out


def test_cli_smoke_trips_on_truncation(capsys):
    assert main(["proto", "--scenario", "tick_posts", "--smoke",
                 "--max-schedules", "3", "-q"]) == 2


def test_cli_unknown_names(capsys):
    assert main(["proto", "--scenario", "nope"]) == 2
    assert main(["proto", "--mutation", "nope"]) == 2
    assert main(["proto", "--inject", "nope"]) == 2


def test_cli_json_report(tmp_path, capsys):
    path = tmp_path / "mck.proto.json"
    assert main(["proto", "--scenario", "txn_crash", "-q",
                 "--json", str(path)]) == 0
    doc = json.loads(path.read_text())
    assert doc["tool"] == "hvd-mck"
    assert doc["mode"] == "proto"
    assert doc["ok"] and doc["complete"]
    run = doc["runs"][0]
    assert run["scenario"] == "txn_crash"
    assert run["complete"] and run["violations"] == []
