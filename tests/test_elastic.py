"""Elastic subsystem tests.

Unit tier mirrors reference `test/single/test_elastic_driver.py` (mock
discovery, in-process); the integration tier mirrors
`test/integration/elastic_common.py`: a real `hvdrun --host-discovery-script`
job against a mutable hosts file, asserting recovery invariants from worker
logs."""

import copy
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from horovod_tpu.elastic.discovery import (
    FixedHosts,
    HostDiscoveryScript,
    HostManager,
)
from horovod_tpu.elastic.registration import WorkerStateRegistry
from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.runner.hosts import HostInfo

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _MutableDiscovery:
    def __init__(self, hosts):
        self.hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        return dict(self.hosts)


class TestHostManager:
    def test_stable_order_on_growth(self):
        disc = _MutableDiscovery({"a": 2})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        disc.hosts["b"] = 2
        changed, removal = mgr.update_available_hosts()
        assert changed and not removal
        assert [h.hostname for h in mgr.current_hosts] == ["a", "b"]

    def test_removal_flag_and_order(self):
        disc = _MutableDiscovery({"a": 1, "b": 1, "c": 1})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        del disc.hosts["b"]
        changed, removal = mgr.update_available_hosts()
        assert changed and removal
        assert [h.hostname for h in mgr.current_hosts] == ["a", "c"]

    def test_blacklist_excludes_host(self):
        disc = _MutableDiscovery({"a": 1, "b": 1})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        mgr.blacklist("b")
        changed, removal = mgr.update_available_hosts()
        assert changed and removal
        assert [h.hostname for h in mgr.current_hosts] == ["a"]
        # blacklisted host reappearing in discovery stays excluded
        changed, _ = mgr.update_available_hosts()
        assert not changed

    def test_discovery_script(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB\n")
        script.chmod(0o755)
        disc = HostDiscoveryScript(str(script))
        assert disc.find_available_hosts_and_slots() == {"hostA": 2, "hostB": 1}

    def test_blacklist_cooldown_expires_and_host_rejoins(self, monkeypatch):
        """With a cooldown, a blacklisted (e.g. transiently preempted)
        host rejoins the pool after expiry instead of shrinking it
        forever; a failure after rejoining re-blacklists with a fresh
        clock."""
        clock = [1000.0]
        monkeypatch.setattr(HostManager, "_now",
                            staticmethod(lambda: clock[0]))
        disc = _MutableDiscovery({"a": 1, "b": 1})
        mgr = HostManager(disc, blacklist_cooldown=30.0)
        mgr.update_available_hosts()
        mgr.blacklist("b")
        assert mgr.is_blacklisted("b")
        mgr.update_available_hosts()
        assert [h.hostname for h in mgr.current_hosts] == ["a"]
        # still excluded just before expiry
        clock[0] += 29.0
        assert mgr.is_blacklisted("b")
        # past expiry: rejoins the pool
        clock[0] += 2.0
        assert not mgr.is_blacklisted("b")
        changed, removal = mgr.update_available_hosts()
        assert changed and not removal
        assert [h.hostname for h in mgr.current_hosts] == ["a", "b"]
        # re-blacklist restarts the clock
        mgr.blacklist("b")
        clock[0] += 29.0
        assert mgr.is_blacklisted("b")
        clock[0] += 2.0
        assert not mgr.is_blacklisted("b")

    def test_blacklist_default_is_permanent(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_BLACKLIST_COOLDOWN_SECS", raising=False)
        disc = _MutableDiscovery({"a": 1, "b": 1})
        mgr = HostManager(disc)
        mgr.blacklist("b")
        assert mgr._blacklist["b"] == float("inf")
        assert mgr.is_blacklisted("b")

    def test_blacklist_cooldown_env_knob(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_SECS", "45")
        mgr = HostManager(_MutableDiscovery({"a": 1}))
        assert mgr._cooldown == 45.0


def test_worker_state_registry_barrier():
    reg = WorkerStateRegistry(2)
    reg.record_success(0)
    assert not reg.all_accounted()
    reg.record_failure(1)
    assert reg.all_accounted()
    assert reg.failed_ranks() == {1}
    reg.reset(1)
    assert not reg.all_accounted()


def test_object_state_commit_restore():
    state = ObjectState(epoch=0, items=[1, 2])
    state.epoch = 5
    state.items.append(3)
    state.restore()
    assert state.epoch == 0 and state.items == [1, 2]
    state.epoch = 7
    state.save()
    state.epoch = 9
    state.restore()
    assert state.epoch == 7


def test_object_state_sync_adopts_roots_attribute_set(monkeypatch):
    """Live-reshard joiner edge: a joiner whose constructor defaults
    differ from the coordinator's evolved attribute set must adopt the
    ROOT's set — values AND keys — or its next save/restore cycle
    snapshots keys nobody else agrees on."""
    from horovod_tpu.frameworks.jax import functions as jax_fns

    root_payload = {"a": 10, "c": [3, 4]}  # root dropped b, grew c

    def fake_broadcast(values, root_rank=0, name=""):
        assert set(values) == {"a", "b"}  # the joiner offered its own set
        return {k: copy.deepcopy(v) for k, v in root_payload.items()}

    monkeypatch.setattr(jax_fns, "broadcast_object", fake_broadcast)
    joiner = ObjectState(a=1, b=2)
    joiner.sync(root_rank=0)
    assert joiner._known == ["a", "c"]
    assert joiner.a == 10 and joiner.c == [3, 4]
    # The adopted set is committed: a dirty restore comes back to the
    # ROOT's state, and b is no longer part of any snapshot.
    joiner.a = 99
    joiner.c.append(5)
    joiner.restore()
    assert joiner.a == 10 and joiner.c == [3, 4]
    assert "b" not in joiner._saved


def test_object_state_restore_after_failed_mid_sync_broadcast(monkeypatch):
    """A broadcast that dies mid-sync (the reshard it rode aborted, a
    peer vanished) must leave the last committed snapshot intact:
    restore() lands bit-exact on the pre-sync commit, and a later
    successful sync proceeds from there."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.frameworks.jax import functions as jax_fns

    state = ObjectState(batch=7, params=[1.0, 2.0])
    state.commit()

    def dying_broadcast(values, root_rank=0, name=""):
        raise HorovodInternalError("peer gone mid-broadcast")

    monkeypatch.setattr(jax_fns, "broadcast_object", dying_broadcast)
    with pytest.raises(HorovodInternalError):
        state.sync()
    state.restore()
    assert state.batch == 7 and state.params == [1.0, 2.0]
    assert state._known == ["batch", "params"]

    def good_broadcast(values, root_rank=0, name=""):
        return {k: copy.deepcopy(v) for k, v in values.items()}

    monkeypatch.setattr(jax_fns, "broadcast_object", good_broadcast)
    state.sync()
    assert state.batch == 7 and state.params == [1.0, 2.0]


def test_object_state_commit_restore_idempotent_across_epochs():
    """Two epoch transitions' worth of commit/restore churn: repeated
    restores of the same commit are idempotent, and a re-commit of an
    unmodified state changes nothing — the retry loop in elastic.run may
    restore more than once per epoch and must always land on the same
    bits."""
    state = ObjectState(batch=0, acc=[0])
    # Epoch 1: some progress, committed.
    state.batch = 10
    state.acc.append(1)
    state.commit()
    snap1 = (state.batch, list(state.acc))
    state.batch = 11  # uncommitted progress, then two restores
    state.restore()
    first = (state.batch, list(state.acc))
    state.restore()
    assert first == (state.batch, list(state.acc)) == snap1
    # Re-commit without modification: still the same snapshot.
    state.commit()
    state.restore()
    assert (state.batch, list(state.acc)) == snap1
    # Epoch 2: more progress on top of the restored state.
    state.batch = 20
    state.acc.append(2)
    state.commit()
    snap2 = (state.batch, list(state.acc))
    state.batch = 99
    state.acc.clear()
    state.restore()
    state.restore()
    assert (state.batch, list(state.acc)) == snap2
    # deepcopy discipline: the snapshot must not alias live objects.
    state.acc.append(3)
    state.restore()
    assert (state.batch, list(state.acc)) == snap2


_ELASTIC_TRAIN = """
import os, sys, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0)

@hvd.elastic.run
def train(state):
    while state.batch < 90:
        v = np.ones(4, np.float32)
        out = hvd.allreduce(v, op=hvd.Sum, name="grad")
        assert np.allclose(np.asarray(out), hvd.size()), out
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()}",
              flush=True)
        state.batch += 1
        state.commit()
        time.sleep(0.15)

train(state)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


@pytest.mark.parametrize("mode", ["remove_host"])
def test_elastic_host_removal_end_to_end(tmp_path, mode):
    """Two single-slot 'hosts' (localhost + 127.0.0.1); mid-run the hosts
    file drops one — the survivor re-rendezvouses at size 1 and finishes
    (reference `test_hosts_added_and_removed` analog)."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("localhost:1\n127.0.0.1:1\n")
    disc = tmp_path / "discover.sh"
    disc.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_ELASTIC_TRAIN)

    out_path = tmp_path / "stdout.log"
    err_path = tmp_path / "stderr.log"
    with open(out_path, "w") as of, open(err_path, "w") as ef:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", "2", "--min-np", "1",
             "--host-discovery-script", str(disc),
             sys.executable, str(train)],
            cwd=REPO_ROOT, text=True, stdout=of, stderr=ef)
        try:
            # Drop the host only after batches PROVABLY ran at size 2 —
            # worker startup time varies wildly (remote-backend imports),
            # so a fixed sleep races the first rendezvous.
            _wait_for_output(out_path, "size=2", proc, timeout=90)
            hosts_file.write_text("localhost:1\n")  # drop the second host
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            raise AssertionError(
                f"elastic job hung\nstdout:\n{out_path.read_text()}"
                f"\nstderr:\n{err_path.read_text()}")
    out, err = out_path.read_text(), err_path.read_text()
    assert proc.returncode == 0, (out, err)
    assert "ELASTIC_DONE" in out, (out, err)
    assert "size=2" in out, ("never ran at full size", err[-4000:])
    assert "size=1" in out, "never recovered at reduced size"


def _wait_for_output(path, needle: str, proc, timeout: float) -> None:
    """Poll a worker-output file until ``needle`` appears (or the job
    exits / times out)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if needle in path.read_text():
            return
        if proc.poll() is not None:
            raise AssertionError(
                f"job exited before producing {needle!r}:\n"
                + path.read_text())
        time.sleep(0.5)
    raise AssertionError(f"timed out waiting for {needle!r} in output")


_FAILING_TRAIN = """
import os, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0)
marker = os.environ["FAIL_MARKER"]

@hvd.elastic.run
def train(state):
    while state.batch < 60:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="g")
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()}",
              flush=True)
        if state.batch == 8 and hvd.rank() == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            os.kill(os.getpid(), 9)  # simulate sudden worker death
        state.batch += 1
        state.commit()
        time.sleep(0.1)

train(state)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


def test_elastic_single_rank_failure(tmp_path):
    """Rank 1 SIGKILLs itself mid-run: its host is blacklisted, the
    survivor rolls back to the last commit and finishes at size 1
    (reference `test_single_rank_failure`)."""
    disc = tmp_path / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_FAILING_TRAIN)

    env = os.environ.copy()
    env["FAIL_MARKER"] = str(tmp_path / "failed.marker")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "1",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env,
        capture_output=True, timeout=180)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "ELASTIC_DONE" in proc.stdout
    assert "size=2" in proc.stdout and "size=1" in proc.stdout
    # survivor re-ran from its last committed batch, not from zero
    assert proc.stdout.count("BATCH 0 ") <= 2, proc.stdout[-1500:]


_ALL_FAIL_TRAIN = """
import os, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0)

@hvd.elastic.run
def train(state):
    while state.batch < 60:
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="g")
        if state.batch == 4:
            os.kill(os.getpid(), 9)  # every rank dies
        state.batch += 1
        state.commit()
        time.sleep(0.1)

train(state)
hvd.shutdown()
"""


def test_elastic_all_ranks_failure(tmp_path):
    """Every rank SIGKILLs itself: the job must FAIL promptly and cleanly
    (reference `test_all_ranks_failure`, elastic_common.py:199) rather than
    hang waiting for capacity."""
    disc = tmp_path / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_ALL_FAIL_TRAIN)

    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "1",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=120)
    assert proc.returncode != 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    assert "ELASTIC_DONE" not in proc.stdout


_TRANSIENT_TRAIN = """
import os, sys, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd
from horovod_tpu.elastic.constants import TRANSIENT_EXIT_CODE

hvd.init()
state = hvd.elastic.ObjectState(batch=0)
marker = os.environ["FAIL_MARKER"]

@hvd.elastic.run
def train(state):
    while state.batch < 40:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="g")
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()}",
              flush=True)
        if state.batch == 6 and hvd.rank() == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(TRANSIENT_EXIT_CODE)  # transient casualty, host healthy
        state.batch += 1
        state.commit()
        time.sleep(0.1)

train(state)
print("ELASTIC_DONE", hvd.rank(), "size", hvd.size(), flush=True)
hvd.shutdown()
"""


def test_elastic_transient_exit_respawns_without_blacklist(tmp_path):
    """A worker exiting with TRANSIENT_EXIT_CODE is respawned on the same
    host (below the transient blacklist threshold): the job finishes back
    at FULL size, proving the host was not blacklisted."""
    disc = tmp_path / "discover.sh"
    disc.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_TRANSIENT_TRAIN)

    env = os.environ.copy()
    env["FAIL_MARKER"] = str(tmp_path / "t.marker")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "1",
         "--host-discovery-script", str(disc),
         sys.executable, str(train)],
        cwd=REPO_ROOT, text=True, env=env, capture_output=True, timeout=180)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    # both ranks finish, and they finish at size 2 (host came back)
    assert proc.stdout.count("ELASTIC_DONE") == 2, proc.stdout[-1500:]
    assert "ELASTIC_DONE 0 size 2" in proc.stdout


_XLA_ELASTIC_TRAIN = """
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.backend import xla as xla_backend

hvd.init()
state = hvd.elastic.ObjectState(batch=0, dispatches_before_reset=-1)

@hvd.elastic.run
def train(state):
    assert xla_backend.context().ready, "XLA data plane not up"
    while state.batch < 60:
        v = jnp.ones((8,), jnp.float32)
        out = hvd.allreduce(v, op=hvd.Sum, name="grad")
        np.testing.assert_allclose(np.asarray(out), hvd.size())
        n = xla_backend.stats.get("allreduce", 0)
        if state.dispatches_before_reset >= 0 and hvd.size() == 2:
            # post-reset world: the DEVICE plane must be doing the work
            assert n > state.dispatches_before_reset, (
                n, state.dispatches_before_reset)
            print(f"XLA_POST_RESET_DEVICE_PATH n={n}", flush=True)
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()} "
              f"xla_dispatches={n}", flush=True)
        state.batch += 1
        state.commit()
        time.sleep(0.15)

def on_reset():
    # remember the dispatch count at reset; post-reset batches must grow it
    state.dispatches_before_reset = xla_backend.stats.get("allreduce", 0)

state.register_reset_callbacks([on_reset])
train(state)
print("XLA_ELASTIC_DONE", hvd.rank(), "size", hvd.size(), flush=True)
hvd.shutdown()
"""


def test_elastic_xla_data_plane_survives_host_change(tmp_path):
    """VERDICT r2 #5: with HOROVOD_DATA_PLANE=xla, a host removal must
    re-establish jax.distributed + the device mesh for the NEW world —
    stats counters prove post-reset collectives ride the device plane."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("localhost:1\n127.0.0.1:1\n127.0.0.2:1\n")
    disc = tmp_path / "discover.sh"
    disc.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    disc.chmod(0o755)
    train = tmp_path / "train.py"
    train.write_text(_XLA_ELASTIC_TRAIN)

    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    out_path = tmp_path / "stdout.log"
    err_path = tmp_path / "stderr.log"
    with open(out_path, "w") as of, open(err_path, "w") as ef:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", "3", "--min-np", "1", "--data-plane", "xla",
             "--host-discovery-script", str(disc),
             sys.executable, str(train)],
            cwd=REPO_ROOT, text=True, env=env, stdout=of, stderr=ef)
        try:
            _wait_for_output(out_path, "size=3", proc, timeout=120)
            hosts_file.write_text("localhost:1\n127.0.0.1:1\n")
            proc.wait(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            raise AssertionError(
                f"xla elastic job hung\nstdout:\n{out_path.read_text()}"
                f"\nstderr:\n{err_path.read_text()}")
    out, err = out_path.read_text(), err_path.read_text()
    assert proc.returncode == 0, (out[-3000:], err[-3000:])
    assert "XLA_ELASTIC_DONE" in out, (out[-3000:], err[-3000:])
    assert "size=3" in out, "never ran at full size"
    assert "XLA_POST_RESET_DEVICE_PATH" in out, \
        "post-reset batches did not prove the device plane"
