"""Spark integration tests against the in-repo fake SparkContext
(real subprocess tasks; see ``fake_spark.py``).  Mirrors the reference's
local-mode ``test_spark.py`` strategy minus the pyspark dependency."""

import pytest

from .fake_spark import FakeDataFrame, FakeSparkContext


def _train_fn(mult):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum)
    result = float(np.asarray(out)[0]) * mult + hvd.rank()
    hvd.shutdown()
    return result


def test_spark_run_end_to_end():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(10.0,), num_proc=2,
                            sc=FakeSparkContext(),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    # allreduce sum = 3.0 on both ranks; +rank makes results rank-ordered
    assert results == [30.0, 31.0], results


def test_spark_run_defaults_to_cluster_parallelism():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(1.0,),
                            sc=FakeSparkContext(default_parallelism=2),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    assert results == [3.0, 4.0], results


def test_spark_task_failure_surfaces():
    import horovod_tpu.spark as hvd_spark

    def boom():
        raise ValueError("task exploded")

    with pytest.raises(RuntimeError, match="task exploded"):
        hvd_spark.run(boom, num_proc=1, sc=FakeSparkContext(),
                      start_timeout=30)


def test_keras_estimator_fit_transform(tmp_path):
    keras = pytest.importorskip("keras")
    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.keras import KerasEstimator, KerasModel

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")

    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    store = LocalStore(str(tmp_path))
    est = KerasEstimator(
        model=model, optimizer=keras.optimizers.Adam(0.01),
        loss="sparse_categorical_crossentropy",
        batch_size=16, epochs=2, num_proc=2, store=store,
        sc=FakeSparkContext())
    fitted = est.fit((x, y))
    preds = fitted.predict(x[:8])
    assert preds.shape == (8, 2)
    assert store.exists("keras_checkpoint.npz")
    # round-trip through the store
    fitted.save(store, "model.pkl")
    loaded = KerasModel.load(store, "model.pkl")
    assert np.allclose(loaded.predict(x[:8]), preds)


def test_torch_estimator_fit_transform(tmp_path):
    torch = pytest.importorskip("torch")
    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.torch import TorchEstimator, TorchModel

    rng = np.random.RandomState(1)
    x = rng.randn(64, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")

    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
    store = LocalStore(str(tmp_path))
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda p: torch.optim.SGD(p, lr=0.05),
        loss=torch.nn.functional.cross_entropy,
        batch_size=16, epochs=2, num_proc=2, store=store,
        sc=FakeSparkContext())
    fitted = est.fit((x, y))
    preds = fitted.predict(x[:8])
    assert preds.shape == (8, 2)
    assert store.exists("torch_checkpoint.pt")
    fitted.save(store, "model.pkl")
    loaded = TorchModel.load(store, "model.pkl")
    assert np.allclose(loaded.predict(x[:8]), preds)


def test_spark_run_rejects_oversubscription():
    import horovod_tpu.spark as hvd_spark

    with pytest.raises(ValueError, match="exceeds"):
        hvd_spark.run(lambda: None, num_proc=8,
                      sc=FakeSparkContext(default_parallelism=2))


def test_spark_estimator_uneven_dataset():
    """65 rows over 2 ranks: shards pad to equal step counts so the
    per-step allreduces stay paired (would deadlock otherwise)."""
    torch = pytest.importorskip("torch")
    import numpy as np

    from horovod_tpu.spark.torch import TorchEstimator

    rng = np.random.RandomState(2)
    x = rng.randn(65, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")
    model = torch.nn.Linear(4, 2)
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda p: torch.optim.SGD(p, lr=0.05),
        loss=torch.nn.functional.cross_entropy,
        batch_size=16, epochs=1, num_proc=2, sc=FakeSparkContext())
    fitted = est.fit((x, y))
    assert fitted.predict(x[:4]).shape == (4, 2)


def test_shard_equalizes_lengths():
    import numpy as np

    from horovod_tpu.spark.common import shard

    x = np.arange(65)
    y = np.arange(65) * 2
    s0x, s0y = shard(x, y, 0, 2)
    s1x, s1y = shard(x, y, 1, 2)
    assert len(s0x) == len(s1x) == 33
    assert np.array_equal(s1x[-1:], s1x[:1])  # wrap-around pad
    assert np.array_equal(s0y, s0x * 2) and np.array_equal(s1y, s1x * 2)


def _elastic_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = float(np.asarray(hvd.allreduce(np.ones(2), op=hvd.Sum,
                                         name="se"))[0])
    hvd.shutdown()
    return out


def test_spark_run_elastic_end_to_end():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run_elastic(
        _elastic_fn, num_proc=2, min_np=1, sc=FakeSparkContext(),
        extra_env={"JAX_PLATFORMS": "cpu"}, start_timeout=60)
    assert results == [2.0, 2.0], results


def test_prepare_dataset_partitionwise(tmp_path):
    """Partitions materialize into per-part npz shards written BY TASKS;
    the driver sees only metadata; validation rows split out."""
    import numpy as np

    from horovod_tpu.spark.common import LocalStore, prepare_dataset, read_shards
    from tests.fake_spark import FakeDataFrame

    rows = [{"features": [float(i), float(i) * 2], "label": float(i % 2)}
            for i in range(40)]
    df = FakeDataFrame(rows, num_partitions=4)
    store = LocalStore(str(tmp_path))

    manifest = prepare_dataset(df, store, ["features"], ["label"],
                               validation=0.25, seed=3)
    assert manifest["train_rows"] + manifest["val_rows"] == 40
    assert manifest["val_rows"] > 0
    assert len(manifest["train"]) <= 4
    for p in manifest["train"]:
        assert store.exists(p["path"])
    assert store.exists("data/manifest.json")

    # worker-side: two ranks read disjoint shard FILES, equalized lengths
    a = read_shards(store, manifest, 0, 2)
    b = read_shards(store, manifest, 1, 2)
    assert len(a[0]) == len(b[0]) == -(-manifest["train_rows"] // 2)
    va = read_shards(store, manifest, 0, 2, split="val")
    assert len(va[0]) == -(-manifest["val_rows"] // 2)


def test_keras_estimator_store_data_plane(tmp_path):
    """VERDICT r2 #4 acceptance: estimator fit() where the dataset is
    produced partition-wise — no whole-dataset collect() on the driver,
    nothing dataset-sized pickled into tasks; per-epoch metrics logged
    through the Store."""
    keras = pytest.importorskip("keras")
    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.keras import KerasEstimator
    from tests.fake_spark import FakeDataFrame

    rng = np.random.RandomState(0)
    rows = [{"features": rng.randn(4).astype("float32").tolist(),
             "label": int(i % 2)} for i in range(64)]
    df = FakeDataFrame(rows, num_partitions=4)
    # guard: the Store path must never call collect()/select() on the df
    df.collect = df.select = None  # would TypeError if touched

    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    store = LocalStore(str(tmp_path))
    est = KerasEstimator(
        model=model, optimizer=keras.optimizers.Adam(0.01),
        loss="sparse_categorical_crossentropy",
        batch_size=16, epochs=2, num_proc=2, store=store,
        validation=0.2, sc=FakeSparkContext())
    fitted = est.fit(df)
    assert fitted.predict(rng.randn(8, 4).astype("float32")).shape == (8, 2)
    # epoch metric logs written through the store, with val_loss
    assert store.exists("logs/epoch-0000.json")
    assert store.exists("logs/epoch-0001.json")
    import json
    logs = json.loads(store.load_bytes("logs/epoch-0001.json"))
    assert "loss" in logs and "val_loss" in logs, logs
    # training history carries validation metrics per epoch
    assert "val_loss" in fitted.history


def test_torch_estimator_store_data_plane(tmp_path):
    torch = pytest.importorskip("torch")
    import json

    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.torch import TorchEstimator
    from tests.fake_spark import FakeDataFrame

    rng = np.random.RandomState(1)
    rows = [{"features": rng.randn(4).astype("float32").tolist(),
             "label": float(rng.rand() > 0.5)} for i in range(48)]
    df = FakeDataFrame(rows, num_partitions=3)
    df.collect = df.select = None

    model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                                torch.nn.Linear(8, 1))
    store = LocalStore(str(tmp_path))
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda p: torch.optim.SGD(p, lr=0.05),
        loss=lambda out, y: torch.nn.functional.mse_loss(
            out.squeeze(-1), y.float()),
        batch_size=16, epochs=2, num_proc=2, store=store,
        validation=0.25, sc=FakeSparkContext())
    fitted = est.fit(df)
    assert fitted.predict(rng.randn(5, 4).astype("float32")).shape[0] == 5
    logs = json.loads(store.load_bytes("logs/epoch-0001.json"))
    assert "loss" in logs and "val_loss" in logs, logs


def test_read_shards_skewed_and_scarce(tmp_path):
    """Row-balanced shard reading: skewed shard sizes drop no rows, and a
    split with fewer shard files than ranks still feeds every rank."""
    import io

    import numpy as np

    from horovod_tpu.spark.common import LocalStore, read_shards

    store = LocalStore(str(tmp_path))
    sizes = [100, 10]  # heavily skewed
    off = 0
    parts = []
    for i, n in enumerate(sizes):
        buf = io.BytesIO()
        np.savez(buf, x=np.arange(off, off + n, dtype=np.float32)[:, None],
                 y=np.zeros(n, np.float32))
        store.save_bytes(f"d/part-{i}.npz", buf.getvalue())
        parts.append({"path": f"d/part-{i}.npz", "rows": n})
        off += n
    manifest = {"train": parts, "train_rows": 110}

    a = read_shards(store, manifest, 0, 2)
    b = read_shards(store, manifest, 1, 2)
    assert len(a[0]) == len(b[0]) == 55
    seen = set(a[0].ravel().astype(int)) | set(b[0].ravel().astype(int))
    assert seen == set(range(110)), "rows were dropped"

    # one shard file, 4 ranks: every rank still gets ceil(10/4)=3 rows
    m2 = {"train": parts[1:], "train_rows": 10}
    lens = {r: len(read_shards(store, m2, r, 4)[0]) for r in range(4)}
    assert set(lens.values()) == {3}, lens


@pytest.mark.smoke
class TestInlineCollectGuardrail:
    """Store-less fit guardrail (reference always stages through a Store,
    spark/common/store.py:32-153): collecting a distributed DataFrame on
    the driver warns loudly and refuses above a row cap."""

    @staticmethod
    def _capture_warnings():
        """The horovod_tpu logger does not propagate to the global root
        (logging_util sets propagate=False), so caplog cannot see it;
        attach a list handler directly."""
        import logging

        records = []

        class _H(logging.Handler):
            def emit(self, rec):
                records.append(rec.getMessage())

        handler = _H(level=logging.WARNING)
        logging.getLogger("horovod_tpu.spark").addHandler(handler)
        return records, handler

    def test_driver_local_inputs_pass_silently(self):
        import logging

        from horovod_tpu.spark.common import guard_inline_collect

        records, handler = self._capture_warnings()
        try:
            guard_inline_collect(([1, 2], [3, 4]))       # arrays
        finally:
            logging.getLogger("horovod_tpu.spark").removeHandler(handler)
        assert not records

    def test_spark_df_warns_below_cap(self):
        import logging

        from horovod_tpu.spark.common import guard_inline_collect

        records, handler = self._capture_warnings()
        try:
            df = FakeDataFrame([{"x": i} for i in range(10)])
            guard_inline_collect(df)
        finally:
            logging.getLogger("horovod_tpu.spark").removeHandler(handler)
        assert any("collect the full DataFrame" in m and "store=" in m
                   for m in records), records

    def test_spark_df_refuses_above_cap(self, monkeypatch):
        from horovod_tpu.spark.common import guard_inline_collect

        monkeypatch.setenv("HOROVOD_SPARK_INLINE_MAX_ROWS", "5")
        df = FakeDataFrame([{"x": i} for i in range(6)])
        with pytest.raises(ValueError, match="store-less fit"):
            guard_inline_collect(df)

    def test_cap_disabled_by_zero(self, monkeypatch):
        from horovod_tpu.spark.common import guard_inline_collect

        monkeypatch.setenv("HOROVOD_SPARK_INLINE_MAX_ROWS", "0")
        df = FakeDataFrame([{"x": i} for i in range(10_000)])
        guard_inline_collect(df)   # warns but does not raise

    def test_keras_fit_guarded(self, monkeypatch):
        """The estimator's store-less fit path actually calls the guard."""
        import horovod_tpu.spark.keras as hk

        monkeypatch.setenv("HOROVOD_SPARK_INLINE_MAX_ROWS", "3")
        est = hk.KerasEstimator.__new__(hk.KerasEstimator)
        est.store = None
        est.sc = FakeSparkContext()
        est.feature_cols, est.label_cols = ["x"], ["y"]
        est.num_proc = None
        df = FakeDataFrame([{"x": float(i), "y": 0.0} for i in range(10)])
        with pytest.raises(ValueError, match="store-less fit"):
            hk.KerasEstimator.fit(est, df)
