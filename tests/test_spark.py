"""Spark integration tests against the in-repo fake SparkContext
(real subprocess tasks; see ``fake_spark.py``).  Mirrors the reference's
local-mode ``test_spark.py`` strategy minus the pyspark dependency."""

import pytest

from .fake_spark import FakeSparkContext


def _train_fn(mult):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum)
    result = float(np.asarray(out)[0]) * mult + hvd.rank()
    hvd.shutdown()
    return result


def test_spark_run_end_to_end():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(10.0,), num_proc=2,
                            sc=FakeSparkContext(),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    # allreduce sum = 3.0 on both ranks; +rank makes results rank-ordered
    assert results == [30.0, 31.0], results


def test_spark_run_defaults_to_cluster_parallelism():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(1.0,),
                            sc=FakeSparkContext(default_parallelism=2),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    assert results == [3.0, 4.0], results


def test_spark_task_failure_surfaces():
    import horovod_tpu.spark as hvd_spark

    def boom():
        raise ValueError("task exploded")

    with pytest.raises(RuntimeError, match="task exploded"):
        hvd_spark.run(boom, num_proc=1, sc=FakeSparkContext(),
                      start_timeout=30)
