"""Spark integration tests against the in-repo fake SparkContext
(real subprocess tasks; see ``fake_spark.py``).  Mirrors the reference's
local-mode ``test_spark.py`` strategy minus the pyspark dependency."""

import pytest

from .fake_spark import FakeSparkContext


def _train_fn(mult):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum)
    result = float(np.asarray(out)[0]) * mult + hvd.rank()
    hvd.shutdown()
    return result


def test_spark_run_end_to_end():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(10.0,), num_proc=2,
                            sc=FakeSparkContext(),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    # allreduce sum = 3.0 on both ranks; +rank makes results rank-ordered
    assert results == [30.0, 31.0], results


def test_spark_run_defaults_to_cluster_parallelism():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_train_fn, args=(1.0,),
                            sc=FakeSparkContext(default_parallelism=2),
                            extra_env={"JAX_PLATFORMS": "cpu"})
    assert results == [3.0, 4.0], results


def test_spark_task_failure_surfaces():
    import horovod_tpu.spark as hvd_spark

    def boom():
        raise ValueError("task exploded")

    with pytest.raises(RuntimeError, match="task exploded"):
        hvd_spark.run(boom, num_proc=1, sc=FakeSparkContext(),
                      start_timeout=30)


def test_keras_estimator_fit_transform(tmp_path):
    keras = pytest.importorskip("keras")
    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.keras import KerasEstimator, KerasModel

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")

    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    store = LocalStore(str(tmp_path))
    est = KerasEstimator(
        model=model, optimizer=keras.optimizers.Adam(0.01),
        loss="sparse_categorical_crossentropy",
        batch_size=16, epochs=2, num_proc=2, store=store,
        sc=FakeSparkContext())
    fitted = est.fit((x, y))
    preds = fitted.predict(x[:8])
    assert preds.shape == (8, 2)
    assert store.exists("keras_checkpoint.npz")
    # round-trip through the store
    fitted.save(store, "model.pkl")
    loaded = KerasModel.load(store, "model.pkl")
    assert np.allclose(loaded.predict(x[:8]), preds)


def test_torch_estimator_fit_transform(tmp_path):
    torch = pytest.importorskip("torch")
    import numpy as np

    from horovod_tpu.spark.common import LocalStore
    from horovod_tpu.spark.torch import TorchEstimator, TorchModel

    rng = np.random.RandomState(1)
    x = rng.randn(64, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")

    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
    store = LocalStore(str(tmp_path))
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda p: torch.optim.SGD(p, lr=0.05),
        loss=torch.nn.functional.cross_entropy,
        batch_size=16, epochs=2, num_proc=2, store=store,
        sc=FakeSparkContext())
    fitted = est.fit((x, y))
    preds = fitted.predict(x[:8])
    assert preds.shape == (8, 2)
    assert store.exists("torch_checkpoint.pt")
    fitted.save(store, "model.pkl")
    loaded = TorchModel.load(store, "model.pkl")
    assert np.allclose(loaded.predict(x[:8]), preds)


def test_spark_run_rejects_oversubscription():
    import horovod_tpu.spark as hvd_spark

    with pytest.raises(ValueError, match="exceeds"):
        hvd_spark.run(lambda: None, num_proc=8,
                      sc=FakeSparkContext(default_parallelism=2))


def test_spark_estimator_uneven_dataset():
    """65 rows over 2 ranks: shards pad to equal step counts so the
    per-step allreduces stay paired (would deadlock otherwise)."""
    torch = pytest.importorskip("torch")
    import numpy as np

    from horovod_tpu.spark.torch import TorchEstimator

    rng = np.random.RandomState(2)
    x = rng.randn(65, 4).astype("float32")
    y = (x.sum(axis=1) > 0).astype("int64")
    model = torch.nn.Linear(4, 2)
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda p: torch.optim.SGD(p, lr=0.05),
        loss=torch.nn.functional.cross_entropy,
        batch_size=16, epochs=1, num_proc=2, sc=FakeSparkContext())
    fitted = est.fit((x, y))
    assert fitted.predict(x[:4]).shape == (4, 2)


def test_shard_equalizes_lengths():
    import numpy as np

    from horovod_tpu.spark.common import shard

    x = np.arange(65)
    y = np.arange(65) * 2
    s0x, s0y = shard(x, y, 0, 2)
    s1x, s1y = shard(x, y, 1, 2)
    assert len(s0x) == len(s1x) == 33
    assert np.array_equal(s1x[-1:], s1x[:1])  # wrap-around pad
    assert np.array_equal(s0y, s0x * 2) and np.array_equal(s1y, s1x * 2)


def _elastic_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = float(np.asarray(hvd.allreduce(np.ones(2), op=hvd.Sum,
                                         name="se"))[0])
    hvd.shutdown()
    return out


def test_spark_run_elastic_end_to_end():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run_elastic(
        _elastic_fn, num_proc=2, min_np=1, sc=FakeSparkContext(),
        extra_env={"JAX_PLATFORMS": "cpu"}, start_timeout=60)
    assert results == [2.0, 2.0], results
