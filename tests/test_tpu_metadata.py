"""TPU-preemption discovery: metadata polling → elastic scale-down.

Reference analog: the pluggable discovery family
(``/root/reference/horovod/runner/elastic/discovery.py:130-163``) tested by
``test/single/test_elastic_driver.py`` with mock discovery scripts.  Here
the mock is a fake GCE metadata server (per-host preempted /
maintenance-event keys), driving:

- unit: state classification (ok / preempted / terminating / unreachable
  grace) in :class:`TpuMetadataDiscovery`;
- in-process: a preemption notice drives a scale-down epoch end-to-end
  through the real :class:`ElasticDriver` (new slot table published,
  removed identity gets rank −1);
- subprocess: ``hvdrun --host-discovery tpu-metadata`` runs a real 2-proc
  elastic job that survives a mid-run preemption at size 1.
"""

from __future__ import annotations

import http.server
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.elastic.tpu_metadata import TpuMetadataDiscovery
from horovod_tpu.runner.hosts import HostInfo

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeMetadataServer:
    """Per-host GCE instance metadata: GET /{host}/computeMetadata/v1/
    instance/{key}.  Hosts marked down drop the connection without an
    HTTP response (a real down host gives no HTTP answer at all; an HTTP
    error status now classifies as relay-down/host-alive, not
    unreachable)."""

    def __init__(self):
        self.states = {}          # host -> {"preempted": .., "maintenance-event": ..}
        self.down = set()         # no HTTP answer at all (host gone)
        self.broken = set()       # relay alive but erroring (HTTP 502)
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                parts = self.path.strip("/").split("/")
                # {host}/computeMetadata/v1/instance/{key}
                if len(parts) != 4 + 1 or parts[1] != "computeMetadata":
                    self.send_error(404)
                    return
                host, key = parts[0], parts[-1]
                if host in outer.broken:
                    self.send_error(502, "metadata fetch failed")
                    return
                if host in outer.down or host not in outer.states:
                    # Simulate true unreachability: no HTTP response at
                    # all (close the TCP connection under the client).
                    self.close_connection = True
                    self.connection.close()
                    return
                body = outer.states[host].get(key, "NONE").encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # noqa: A003
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), _Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    @property
    def url_template(self) -> str:
        return (f"http://127.0.0.1:{self.port}/{{host}}"
                "/computeMetadata/v1/instance")

    def set_ok(self, host):
        self.states[host] = {"preempted": "FALSE",
                             "maintenance-event": "NONE"}

    def preempt(self, host):
        self.states[host]["preempted"] = "TRUE"

    def maintenance(self, host, event):
        self.states[host]["maintenance-event"] = event

    def stop(self):
        self._server.shutdown()


@pytest.fixture()
def meta():
    server = FakeMetadataServer()
    yield server
    server.stop()


def _discovery(meta, hosts=("a", "b"), **kw):
    for h in hosts:
        meta.set_ok(h)
    return TpuMetadataDiscovery([HostInfo(h, 2) for h in hosts],
                                url_template=meta.url_template, **kw)


@pytest.mark.smoke
def test_all_healthy_hosts_listed(meta):
    disc = _discovery(meta)
    assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}


@pytest.mark.smoke
def test_preempted_host_dropped_immediately(meta):
    disc = _discovery(meta)
    meta.preempt("b")
    assert disc.find_available_hosts_and_slots() == {"a": 2}


@pytest.mark.smoke
def test_terminal_maintenance_drops_but_migrate_does_not(meta):
    disc = _discovery(meta)
    meta.maintenance("a", "MIGRATE_ON_HOST_MAINTENANCE")
    meta.maintenance("b", "TERMINATE_ON_HOST_MAINTENANCE")
    assert disc.find_available_hosts_and_slots() == {"a": 2}


@pytest.mark.smoke
def test_unreachable_grace_then_removed(meta):
    """Kept for exactly `unreachable_grace` consecutive failed polls,
    dropped on the next one."""
    disc = _discovery(meta, unreachable_grace=2)
    meta.down.add("b")
    assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}
    assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}
    assert disc.find_available_hosts_and_slots() == {"a": 2}
    # recovery clears the strike counter
    meta.down.discard("b")
    assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}


@pytest.mark.smoke
def test_relay_down_connection_refused_never_evicts():
    """A crashed relay answers with a TCP RST (connection refused): the
    host is alive, only its monitoring plane died — it must stay in the
    membership indefinitely, not be evicted after the unreachable grace
    (a monitoring-plane failure shrinking the job was the ADVICE r5
    finding)."""
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()  # nothing listens: connects are refused
    disc = TpuMetadataDiscovery(
        [HostInfo("a", 2)],
        url_template=("http://127.0.0.1:%d/{host}/computeMetadata/v1/"
                      "instance" % dead_port),
        unreachable_grace=1, timeout=1.0)
    # Far past the unreachable grace (1): still listed every poll.
    for _ in range(5):
        assert disc.find_available_hosts_and_slots() == {"a": 2}


@pytest.mark.smoke
def test_relay_http_error_keeps_host(meta):
    """A relay answering HTTP 5xx (its upstream metadata fetch failing)
    is a LIVE server on the host — host stays in the membership past any
    grace, like connection-refused."""
    disc = _discovery(meta, unreachable_grace=1)
    meta.broken.add("b")
    for _ in range(4):
        assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}
    meta.broken.discard("b")
    assert disc.find_available_hosts_and_slots() == {"a": 2, "b": 2}


def test_refused_detection_unwraps_urlerror():
    """URLError carries the socket error in .reason, not __cause__; the
    classifier must find ConnectionRefusedError through either chain and
    stay False for timeouts/no-route."""
    import urllib.error

    is_refused = TpuMetadataDiscovery._is_refused
    assert is_refused(ConnectionRefusedError(111, "refused"))
    assert is_refused(
        urllib.error.URLError(ConnectionRefusedError(111, "refused")))
    assert not is_refused(urllib.error.URLError(TimeoutError()))
    assert not is_refused(OSError("no route to host"))
    assert not is_refused(
        urllib.error.HTTPError("u", 503, "gone", None, None))


@pytest.mark.smoke
def test_url_template_requires_host_placeholder():
    with pytest.raises(ValueError, match="{host}"):
        TpuMetadataDiscovery([HostInfo("a", 1)],
                             url_template="http://fixed:1/md")


@pytest.mark.smoke
def test_relay_proxies_only_metadata_paths(meta):
    """The worker-side relay forwards /computeMetadata/ GETs to its local
    metadata server and refuses everything else."""
    import urllib.error
    import urllib.request

    from horovod_tpu.elastic.tpu_metadata import serve_metadata_relay

    meta.set_ok("self")
    relay = serve_metadata_relay(
        port=0, metadata_base=f"http://127.0.0.1:{meta.port}/self",
        bind="127.0.0.1", block=False)
    try:
        rport = relay.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{rport}/computeMetadata/v1/instance/preempted",
            timeout=5).read()
        assert body == b"FALSE"
        # Anything beyond the two health keys is refused — the metadata
        # tree also serves service-account tokens.
        for path in ("/etc/passwd",
                     "/computeMetadata/v1/instance/service-accounts/"
                     "default/token",
                     "/computeMetadata/v1/instance/?recursive=true"):
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}{path}", timeout=5)
    finally:
        relay.shutdown()


def test_preemption_drives_scale_down_epoch_through_driver(meta):
    """End-to-end through the real ElasticDriver: a preemption notice on
    one host advances the membership epoch, republishes the slot table
    with the survivor at size 1, and hands the removed identity rank −1."""
    from horovod_tpu.elastic.discovery import HostManager
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.rendezvous import RendezvousServer

    disc = _discovery(meta, hosts=("hostA", "hostB"))
    server = RendezvousServer(bind_addr="127.0.0.1")
    server.start()
    spawned = []
    driver = ElasticDriver(server, HostManager(disc), min_np=1, timeout=30)
    try:
        driver.start(lambda slot, epoch: spawned.append((slot, epoch)))
        assert {s.hostname for s, _ in spawned} == {"hostA", "hostB"}
        assert len(driver.current_slots) == 4  # 2 hosts x 2 slots
        assert driver.epoch == 0

        meta.preempt("hostB")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and driver.epoch == 0:
            time.sleep(0.2)
        assert driver.epoch >= 1, "preemption never advanced the epoch"
        slots = driver.current_slots
        assert {s.hostname for s in slots} == {"hostA"}
        assert all(s.size == 2 for s in slots)

        removed = json.loads(
            server.get("rank_and_size", "hostB:0").decode())
        assert removed["rank"] == -1, removed
        survivor = json.loads(
            server.get("rank_and_size", "hostA:0").decode())
        assert survivor["size"] == 2 and survivor["rank"] >= 0
    finally:
        driver.stop()
        server.stop()


_ELASTIC_TRAIN = """
import os, time
import numpy as np
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0)

@hvd.elastic.run
def train(state):
    while state.batch < 90:
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="g")
        assert np.allclose(np.asarray(out), hvd.size()), out
        print(f"BATCH {state.batch} rank={hvd.rank()} size={hvd.size()}",
              flush=True)
        state.batch += 1
        state.commit()
        time.sleep(0.15)

train(state)
print("ELASTIC_DONE", hvd.rank(), flush=True)
hvd.shutdown()
"""


def test_hvdrun_tpu_metadata_preemption_end_to_end(meta, tmp_path):
    """`hvdrun --host-discovery tpu-metadata`: a 2-host elastic job sees
    one host preempted mid-run (via the fake metadata server) and
    finishes at size 1 — the BASELINE config-#5 flow with metadata
    notices instead of a discovery script."""
    for h in ("localhost", "127.0.0.1"):
        meta.set_ok(h)
    train = tmp_path / "train.py"
    train.write_text(_ELASTIC_TRAIN)
    out_path = tmp_path / "stdout.log"
    err_path = tmp_path / "stderr.log"
    with open(out_path, "w") as of, open(err_path, "w") as ef:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", "2", "--min-np", "1",
             "-H", "localhost:1,127.0.0.1:1",
             "--host-discovery", "tpu-metadata",
             "--tpu-metadata-url", meta.url_template,
             sys.executable, str(train)],
            cwd=REPO_ROOT, text=True, stdout=of, stderr=ef)
        try:
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                if "size=2" in out_path.read_text():
                    break
                if proc.poll() is not None:
                    raise AssertionError(
                        "job exited early:\n" + out_path.read_text()
                        + err_path.read_text())
                time.sleep(0.5)
            else:
                raise AssertionError("never ran at size 2:\n"
                                     + err_path.read_text())
            meta.preempt("127.0.0.1")
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            raise AssertionError(
                f"elastic job hung\nstdout:\n{out_path.read_text()}"
                f"\nstderr:\n{err_path.read_text()}")
    out, err = out_path.read_text(), err_path.read_text()
    assert proc.returncode == 0, (out, err)
    assert "ELASTIC_DONE" in out, (out, err)
    assert "size=1" in out, "never recovered at reduced size"
