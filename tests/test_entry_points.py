"""Driver entry points must survive a wedged accelerator backend.

Round 4 lost both scoreboard artifacts to a hung TPU: ``jax.devices()``
blocked forever inside ``dryrun_multichip`` (rc=124) and raised UNAVAILABLE
inside ``bench.py`` (rc=1, no JSON).  These tests pin the defenses:

- ``bench._probe_accelerator`` bounds backend init in a subprocess and
  reports structured outcomes (timeout vs error) instead of propagating.
- ``bench.py`` degrades to the CPU mini-bench with ``"error":
  "tpu_unavailable"`` when the probe fails — still rc=0, still ONE JSON line.
- ``__graft_entry__._ensure_devices`` pins the platform to CPU *before* the
  first backend lookup, so a backend that hangs unless explicitly pinned to
  CPU (exactly how the wedged axon tunnel behaved) cannot stall the dryrun.

The wedge is simulated with a ``sitecustomize`` shim (imported automatically
by any child python) that makes ``jax.devices()`` sleep forever unless the
live jax config says "cpu" — the same observable behavior as the round-4
infra failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402

_WEDGE_SITE = textwrap.dedent(
    """
    # Fake wedged accelerator: jax.devices()/backends() hang unless the
    # platform is explicitly pinned to cpu — mirrors the round-4 axon
    # tunnel wedge (jax.devices() >120s, no error).
    import os
    if os.environ.get("HVD_FAKE_WEDGE") == "1":
        import time
        import jax

        _orig_devices = jax.devices

        def _wedged_devices(*a, **k):
            if "cpu" in str(jax.config.jax_platforms or ""):
                return _orig_devices(*a, **k)
            time.sleep(3600)

        jax.devices = _wedged_devices
        import jax._src.xla_bridge as _xb

        _orig_backends = _xb.backends

        def _wedged_backends(*a, **k):
            if "cpu" in str(jax.config.jax_platforms or ""):
                return _orig_backends(*a, **k)
            time.sleep(3600)

        _xb.backends = _wedged_backends
    """
)


@pytest.fixture()
def wedged_env(tmp_path):
    """Env dict whose child pythons see a hanging non-CPU backend."""
    (tmp_path / "sitecustomize.py").write_text(_WEDGE_SITE)
    env = os.environ.copy()
    env["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO_ROOT}"
    env["HVD_FAKE_WEDGE"] = "1"
    env.pop("JAX_PLATFORMS", None)  # the exact round-4 driver condition
    return env


@pytest.mark.smoke
def test_probe_timeout_is_bounded_and_structured():
    res = bench._probe_accelerator(
        timeout_s=1.0, retries=2, retry_delay_s=0.1,
        probe_src="import time; time.sleep(60)")
    assert res["ok"] is False
    assert [a["outcome"] for a in res["attempts"]] == ["timeout", "timeout"]


@pytest.mark.smoke
def test_probe_error_captures_stderr_tail():
    res = bench._probe_accelerator(
        timeout_s=30.0, retries=1, retry_delay_s=0.0,
        probe_src="raise RuntimeError('UNAVAILABLE: TPU backend wedged')")
    assert res["ok"] is False
    (attempt,) = res["attempts"]
    assert attempt["outcome"] == "error"
    assert "UNAVAILABLE" in attempt["stderr_tail"]


@pytest.mark.smoke
def test_probe_success_reports_platform():
    res = bench._probe_accelerator(
        timeout_s=30.0, retries=3, retry_delay_s=0.0,
        probe_src="print('HVD_PROBE_OK fakeplat 4')")
    assert res == {"ok": True, "platform": "fakeplat", "n_devices": 4,
                   "attempts": []}


@pytest.mark.smoke
def test_probe_retries_then_succeeds(tmp_path):
    # Child python startup alone costs ~10s here (the axon sitecustomize
    # imports jax), so the timeout must comfortably cover startup while
    # still cutting off the first attempt's sleep.
    flag = tmp_path / "second_try"
    src = (
        "import os, sys, time\n"
        f"p = {str(flag)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close(); time.sleep(300)\n"
        "print('HVD_PROBE_OK cpu 1')\n"
    )
    res = bench._probe_accelerator(timeout_s=30.0, retries=3,
                                   retry_delay_s=0.1, probe_src=src)
    assert res["ok"] is True
    assert [a["outcome"] for a in res["attempts"]] == ["timeout"]


def test_ensure_devices_survives_wedged_backend(wedged_env):
    """_ensure_devices must pin CPU before any backend lookup: with the
    wedge active and no JAX_PLATFORMS pin from outside, an unpinned
    jax.devices() would sleep an hour."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; d = g._ensure_devices(8); "
         "print('GOT', len(d), d[0].platform)"],
        capture_output=True, text=True, timeout=240, env=wedged_env,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr
    assert "GOT 8 cpu" in proc.stdout


def test_bench_degrades_to_structured_error_on_wedge(wedged_env):
    """bench.py under a wedged accelerator: probe times out (bounded),
    CPU fallback still produces the one JSON line, rc=0, error field set."""
    wedged_env.update({
        "HVD_BENCH_PROBE_TIMEOUT_S": "20",
        "HVD_BENCH_PROBE_RETRIES": "2",
        "HVD_BENCH_BATCH": "2",
        "HVD_BENCH_IMAGE": "32",
        "HVD_BENCH_WARMUP": "1",
        "HVD_BENCH_ITERS": "2",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, timeout=600, env=wedged_env,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["error"] == "tpu_unavailable", rec
    assert rec["probe"]["ok"] is False, rec
    assert rec["metric"] == "resnet50_synthetic_images_per_sec_per_chip"
    assert rec["value"] > 0  # CPU mini-bench actually ran


@pytest.mark.smoke
def test_bench_guard_emits_json_on_crash(tmp_path, monkeypatch):
    """Any in-process bench failure still prints one parseable JSON line
    with rc=0 (the round-4 rc=1 mode is unreachable)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import os; os.environ['HVD_BENCH_WATCHDOG_S'] = '5'\n"
         "import bench\n"
         "bench.main = lambda: (_ for _ in ()).throw(RuntimeError('boom'))\n"
         "bench._run_guarded()"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT}, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["error"] == "bench_failed"
    assert "boom" in rec["exception"]


@pytest.mark.smoke
def test_bench_watchdog_converts_hang_to_json():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import os; os.environ['HVD_BENCH_WATCHDOG_S'] = '2'\n"
         "import time, bench\n"
         "bench.main = lambda: time.sleep(60)\n"
         "bench._run_guarded()"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT}, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["error"] == "tpu_hang"
