"""SPMD parallel layer tests on the 8-device virtual CPU mesh.

Mirrors the reference's numerics-test style (`test/parallel/test_torch.py`):
closed-form expectations, rank-dependent inputs so wrong-rank bugs change
results, dtype-dependent tolerances.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import (
    MeshSpec,
    build_mesh,
    collectives,
    data_parallel_mesh,
    mesh_shape_for,
    moe_dispatch_combine,
    pipeline_apply,
    ring_attention,
    ulysses_attention,
)
from horovod_tpu.parallel.pipeline import stack_stage_params
from horovod_tpu.parallel.sharding import shard_map_fn


pytestmark = pytest.mark.smoke


def test_mesh_shape_resolution():
    assert mesh_shape_for(MeshSpec(data=-1, model=2), 8) == (
        ("data", 4), ("pipe", 1), ("expert", 1), ("seq", 1), ("model", 2))
    with pytest.raises(ValueError):
        mesh_shape_for(MeshSpec(data=3, model=3), 8)
    with pytest.raises(ValueError):
        mesh_shape_for(MeshSpec(data=-1, model=3), 8)


def test_build_mesh_axes():
    mesh = build_mesh(MeshSpec(data=-1, model=2))
    assert mesh.devices.shape == (4, 1, 1, 1, 2)
    assert mesh.axis_names == ("data", "pipe", "expert", "seq", "model")


def _smap(fn, mesh, in_specs, out_specs):
    return shard_map_fn(fn, mesh, in_specs=in_specs, out_specs=out_specs)


def test_collectives_allreduce_allgather_broadcast():
    mesh = data_parallel_mesh()
    n = 8
    x = jnp.arange(n * 3, dtype=jnp.float32).reshape(n, 3)

    out = _smap(lambda a: collectives.allreduce(a, "data"), mesh,
                P("data", None), P("data", None))(x)
    expect = np.tile(np.asarray(x).sum(0, keepdims=True), (n, 1))
    np.testing.assert_allclose(np.asarray(out)[0], expect[0] / n * n)

    avg = _smap(lambda a: collectives.allreduce(a, "data", op="average"),
                mesh, P("data", None), P("data", None))(x)
    np.testing.assert_allclose(np.asarray(avg)[0], np.asarray(x).mean(0),
                               rtol=1e-6)

    gathered = _smap(lambda a: collectives.allgather(a, "data"), mesh,
                     P("data", None), P(None, None))(x)
    np.testing.assert_array_equal(np.asarray(gathered), np.asarray(x))

    bcast = _smap(lambda a: collectives.broadcast(a, "data", root=3), mesh,
                  P("data", None), P("data", None))(x)
    np.testing.assert_array_equal(np.asarray(bcast)[5], np.asarray(x)[3])


def test_collectives_reduce_scatter_and_ring():
    mesh = data_parallel_mesh()
    n = 8
    x = jnp.ones((n, n * 2), jnp.float32) * jnp.arange(1, n + 1,
                                                       dtype=jnp.float32)[:, None]

    rs = _smap(lambda a: collectives.reduce_scatter(a[0], "data"), mesh,
               P("data", None), P("data"))(x)
    # each rank ends with its 2-wide shard of the columnwise sum (=36)
    np.testing.assert_allclose(np.asarray(rs), np.full((n * 2,), 36.0))

    shifted = _smap(lambda a: collectives.ppermute_ring(a, "data", 1), mesh,
                    P("data", None), P("data", None))(x)
    np.testing.assert_array_equal(np.asarray(shifted)[1], np.asarray(x)[0])
    np.testing.assert_array_equal(np.asarray(shifted)[0], np.asarray(x)[7])


def test_hierarchical_allreduce_matches_flat():
    mesh = build_mesh(MeshSpec(data=2, model=4))
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    def hier(a):
        return collectives.hierarchical_allreduce(a, "model", "data")

    out = _smap(hier, mesh, P(("data", "model"), None),
                P(("data", "model"), None))(x)
    expect = np.asarray(x).sum(0)
    np.testing.assert_allclose(np.asarray(out)[0], expect)


def _reference_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = np.arange(sq)[:, None] >= np.arange(sk)[None, :]
        s = np.where(mask[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = build_mesh(MeshSpec(data=1, seq=8))
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 32, 4, 8
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))

    spec = P("data", "seq", None, None)
    fn = _smap(functools.partial(ring_attention, axis_name="seq",
                                 causal=causal),
               mesh, (spec, spec, spec), spec)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expect = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, expect, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    mesh = build_mesh(MeshSpec(data=1, seq=4), devices=jax.devices()[:4])
    rng = np.random.RandomState(1)
    b, s, h, d = 2, 16, 8, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))

    spec = P("data", "seq", None, None)
    fn = _smap(functools.partial(ulysses_attention, axis_name="seq",
                                 causal=causal),
               mesh, (spec, spec, spec), spec)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expect = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, expect, atol=2e-5)


def test_pipeline_matches_sequential():
    n_stages, n_micro, mb, dim = 4, 8, 2, 6
    mesh = build_mesh(MeshSpec(data=1, pipe=n_stages),
                      devices=jax.devices()[:n_stages])
    rng = np.random.RandomState(2)
    ws = [rng.randn(dim, dim).astype(np.float32) * 0.3 for _ in range(n_stages)]
    stacked = stack_stage_params([{"w": jnp.asarray(w)} for w in ws])
    x = rng.randn(n_micro, mb, dim).astype(np.float32)

    def stage(params, h):
        return jnp.tanh(h @ params["w"])

    def body(params, mbs):
        params = jax.tree_util.tree_map(lambda l: l[0], params)
        return pipeline_apply(stage, params, mbs, axis_name="pipe")

    fn = _smap(body, mesh, (P("pipe"), P(None)), P(None))
    out = np.asarray(fn(stacked, jnp.asarray(x)))

    h = x.copy()
    for w in ws:
        h = np.tanh(h @ w)
    np.testing.assert_allclose(out, h, atol=1e-5)


def test_moe_routes_and_combines():
    n = 8
    mesh = build_mesh(MeshSpec(data=1, expert=n))
    t, d = 16, 4
    rng = np.random.RandomState(3)
    x = rng.randn(t, d).astype(np.float32)
    # Route token i deterministically to expert i % n with prob ~1.
    logits = np.full((t, n), -20.0, np.float32)
    logits[np.arange(t), np.arange(t) % n] = 20.0

    def body(xs, ls):
        return moe_dispatch_combine(
            xs, ls, expert_fn=lambda h: h * 2.0, axis_name="expert",
            capacity=4)

    fn = _smap(body, mesh, (P(None, None), P(None, None)), P(None, None))
    out = np.asarray(fn(jnp.asarray(x), jnp.asarray(logits)))
    # gate prob is ~1, expert doubles: expect 2x (within softmax epsilon)
    np.testing.assert_allclose(out, 2 * x, rtol=1e-4, atol=1e-5)
