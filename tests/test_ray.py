"""Ray integration tests against the in-repo fake ray (real subprocess
actors; see ``fake_ray.py``).  Mirrors the reference's ``test_ray.py``
strategy of a local mini-cluster, minus the ray dependency."""

import sys

import numpy as np
import pytest

from . import fake_ray


@pytest.fixture
def ray_env(monkeypatch):
    monkeypatch.setitem(sys.modules, "ray", fake_ray)
    fake_ray.NODES = []
    yield fake_ray


def _train_fn(scale):
    # Runs inside a spawned actor process: force CPU before first device
    # use (the axon sitecustomize pins JAX_PLATFORMS at import).
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(3) * (hvd.rank() + 1), op=hvd.Sum)
    result = float(np.asarray(out)[0]) * scale
    hvd.shutdown()
    return result


def test_ray_executor_end_to_end(ray_env):
    from horovod_tpu.ray import RayExecutor, RaySettings

    ex = RayExecutor(RaySettings(timeout_s=120, placement_timeout_s=120),
                     num_workers=2)
    ex.start(extra_env_vars={"JAX_PLATFORMS": "cpu"})
    assert len(ex.slots) == 2
    assert [s.rank for s in ex.slots] == [0, 1]
    results = ex.run(_train_fn, args=(10.0,))
    assert results == [30.0, 30.0], results
    single = ex.execute_single(lambda: "solo")
    assert single == "solo"
    ex.shutdown()


class _Exe:
    def __init__(self, base):
        self.base = base

    def value(self):
        return self.base * 2


def test_ray_executor_executable_cls(ray_env):
    from horovod_tpu.ray import RayExecutor, RaySettings

    ex = RayExecutor(RaySettings(timeout_s=60), num_workers=1)
    ex.start(executable_cls=_Exe, executable_args=[21])
    out = ex.execute(lambda exe: exe.value())
    assert out == [42]
    ex.shutdown()


def test_ray_host_discovery(ray_env):
    from horovod_tpu.ray import RayHostDiscovery

    fake_ray.NODES = [
        {"Alive": True, "NodeManagerHostname": "n1",
         "Resources": {"CPU": 8.0}},
        {"Alive": True, "NodeManagerHostname": "n2",
         "Resources": {"CPU": 4.0, "TPU": 4.0}},
        {"Alive": False, "NodeManagerHostname": "dead",
         "Resources": {"CPU": 16.0}},
    ]
    d = RayHostDiscovery(cpus_per_slot=2)
    assert d.find_available_hosts_and_slots() == {"n1": 4, "n2": 2}
    dt = RayHostDiscovery(use_tpu=True)
    assert dt.find_available_hosts_and_slots() == {"n2": 4}


def test_ray_requires_worker_spec(ray_env):
    from horovod_tpu.ray import RayExecutor

    with pytest.raises(ValueError):
        RayExecutor(num_hosts=2)  # num_slots missing


def _elastic_fn():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(2), op=hvd.Sum, name="er")
    result = float(np.asarray(out)[0])
    hvd.shutdown()
    return result


def test_elastic_ray_executor_fixed_hosts(ray_env):
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.ray import ElasticRayExecutor, RaySettings
    from horovod_tpu.runner.hosts import HostInfo

    ex = ElasticRayExecutor(
        RaySettings(timeout_s=120,
                    extra_env_vars={"JAX_PLATFORMS": "cpu"}),
        min_np=2, discovery=FixedHosts([HostInfo("localhost", 2)]))
    ex.start()
    results = ex.run(_elastic_fn)
    assert results == [2.0, 2.0], results
    ex.shutdown()
