"""Multi-process test harness.

Reference analog: the parallel test tier runs every test body under a real
2+-process launcher (``.buildkite/gen-pipeline.sh:96-114`` —
``mpirun -np 2 pytest ...``).  We invert it: the test process plays launcher
(rendezvous server + env + subprocess spawn), each worker runs a script body
against the real runtime, and the test asserts on worker stdout/exit codes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# port reservation (de-flake: the bind(0)-close-reuse idiom races the OS
# ephemeral allocator — another process can grab the port in the window
# between close and the worker's bind).  Two defenses, layered:
#
# 1. **Pid-partitioned range**: the 20000-32000 below-ephemeral band is
#    split into disjoint per-process slices (pid % N picks the slice), so
#    two concurrent pytest processes walk non-overlapping counters instead
#    of colliding via the old pid*137%9000 seeding.
# 2. **Held reservations**: the probe socket stays BOUND until handoff —
#    from reservation to the moment workers are spawned, no other process
#    can bind the port at all.  `release_reservations()` closes them
#    immediately before the spawn; the residual window is
#    spawn→worker-bind only, inside a slice no other test process
#    allocates from.  The probe deliberately does NOT set SO_REUSEADDR:
#    the option is per-socket (it would not transfer to the consumer), and
#    with it the probe could bind a TIME_WAIT port that the consumer then
#    cannot.  Bound-never-connected sockets leave no TIME_WAIT behind, so
#    holding and releasing costs nothing.

_PORT_BAND_LO, _PORT_BAND_HI = 20000, 32000
_SLICES = 24
_SLICE_LEN = (_PORT_BAND_HI - _PORT_BAND_LO) // _SLICES  # 500 ports each

_port_counter: Optional[int] = None
_held_reservations: Dict[int, socket.socket] = {}


def _slice_bounds() -> tuple:
    lo = _PORT_BAND_LO + (os.getpid() % _SLICES) * _SLICE_LEN
    return lo, lo + _SLICE_LEN


def reserve_port() -> int:
    """Reserve a port from this process's slice, HOLDING the bound socket
    open until :func:`release_reservations` (called by run_distributed at
    spawn time, and safe to call directly)."""
    global _port_counter
    lo, hi = _slice_bounds()
    if _port_counter is None:
        _port_counter = lo
    for _ in range(_SLICE_LEN):
        _port_counter += 1
        if _port_counter >= hi:
            _port_counter = lo + 1
        if _port_counter in _held_reservations:
            continue
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", _port_counter))
        except OSError:
            s.close()
            continue
        _held_reservations[_port_counter] = s
        return _port_counter
    raise RuntimeError("no free port in this process's reserved slice")


def release_reservations() -> None:
    """Close every held reservation socket — the handoff point, called
    right before worker processes are spawned so the consumer can bind."""
    while _held_reservations:
        _, s = _held_reservations.popitem()
        try:
            s.close()
        except OSError:
            pass


def scaled_mesh_startup_timeout() -> str:
    """Load-scaled TCP-mesh bring-up budget for worker envs (the product
    default is 60 s, core/state.py); one definition so the policy cannot
    drift between launch helpers."""
    return str(int(60 * _timeout_scale()))


def _log_retry(reason: str) -> None:
    """Record a retry-gate engagement (VERDICT r4 #4: de-flake runs must
    prove ZERO engagements — this is the audit trail)."""
    path = os.environ.get("HVD_TEST_RETRY_LOG")
    if not path:
        return
    test = os.environ.get("PYTEST_CURRENT_TEST", "?")
    with open(path, "a") as f:
        f.write(f"{time.strftime('%H:%M:%S')} {test} :: {reason[:200]}\n")

PREAMBLE = """
import os, sys
# JAX_PLATFORMS=cpu in the env is NOT enough on this machine: the axon
# sitecustomize overrides the platform via jax.config at import time, so
# workers must override it back or they contend for the one real TPU chip.
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import numpy as np
import horovod_tpu as hvd
hvd.init()
rank, size = hvd.rank(), hvd.size()
"""

EPILOGUE = """
hvd.shutdown()
print("WORKER_OK", rank)
"""


def _timeout_scale() -> float:
    """Timeout multiplier for loaded hosts.

    Round-3 full runs saw 7 timing flakes on a contended 2-core box
    (VERDICT r3 weak #1): fixed 120 s budgets assume an idle machine.
    Scale every timeout by the current load-per-core (capped), or by the
    explicit ``HVD_TEST_TIMEOUT_SCALE`` override."""
    env = os.environ.get("HVD_TEST_TIMEOUT_SCALE")
    floor = float(env) if env else 1.0
    try:
        load = os.getloadavg()[0]
        cores = os.cpu_count() or 1
    except OSError:
        return floor
    # Divide by cores-1: on a small box one core's worth of load (the
    # test runner + harness itself) is the steady state, and a 2-proc
    # jax worker pair needs real headroom beyond it.  The env value is a
    # FLOOR under the load-reactive scale (ADVICE r4): containerized CI
    # sees the HOST loadavg (~0) and needs the fixed floor, while a
    # genuinely loaded bare host can still scale past it, up to 6x.
    return max(floor, min(6.0, load / max(1, cores - 1)))


#: Failure signatures that indicate host-load flakiness (worker starved of
#: CPU → peer death / handshake timeout), not a product bug.  Only these
#: trigger the automatic retries.
FLAKY_SIGNATURES = (
    "timed out after",
    "peer closed connection",
    "Connection reset by peer",
    "recv from rank",
    "background loop died",
    "could not connect to rank",
    "rendezvous wait timed out",
    "tcp mesh accept failed",
    # Bring-up half-meshes on a saturated box: a starved acceptor whose
    # join deadline lapses without an error reports this instead of
    # "accept failed" (same root cause, different raceside).
    "tcp mesh incomplete",
    # Transport progress-deadline trips (transport/tcp.py): with the
    # generous production default these only fire when the box starved a
    # worker outright.  Deliberately NOT matching broader failure-plane
    # text (PeerGoneError/CoordinatedAbortError wrappers): those carry the
    # underlying reason verbatim, so genuine infra causes still match the
    # specific signatures above, while a product bug in the abort path
    # itself stays loud instead of being retried into a pass.
    "no recv progress",
    "no send progress",
)
_FLAKY_SIGNATURES = FLAKY_SIGNATURES  # back-compat alias


class WorkerFailure(AssertionError):
    """Worker-job failure carrying each failing rank's combined output so
    the retry gate can judge EVERY rank, not just the first."""

    def __init__(self, message: str, sections: List[str]):
        super().__init__(message)
        self.sections = sections


def infra_retryable(failure: BaseException) -> bool:
    """True when a failure is pure infrastructure flakiness.

    For a :class:`WorkerFailure`, EVERY failing rank's output must match
    an infra signature — a deterministic product crash on one rank
    surfaces on its *siblings* as peer-death text, so judging only the
    first failing rank would retry real bugs."""
    if isinstance(failure, WorkerFailure):
        return all(any(sig in s for sig in FLAKY_SIGNATURES)
                   for s in failure.sections) and bool(failure.sections)
    return any(sig in str(failure) for sig in FLAKY_SIGNATURES)


def retry_backoff(attempt: int) -> None:
    """Shared backoff between infra retries (let the loaded box drain)."""
    import time as _time

    _time.sleep(2.0 * attempt)


def run_distributed(n: int, body: str, timeout: float = 120,
                    extra_env: Optional[Dict[str, str]] = None,
                    expect_failure: bool = False,
                    local_size: Optional[int] = None,
                    retries: int = 2) -> List[str]:
    """Run `body` on n worker processes; returns per-rank stdout.

    ``local_size`` simulates a host-major multi-host topology (n must
    divide evenly): rank r gets local_rank r%local_size, cross_rank
    r//local_size — how hierarchical-allreduce paths are tested without
    real multi-host.

    Timeouts are load-scaled (see ``_timeout_scale``); a failure is
    retried only when :func:`infra_retryable` judges every failing rank's
    output to be infrastructure text — product asserts go red
    immediately."""
    attempt = 0
    while True:
        try:
            return _run_distributed_once(
                n, body, timeout * _timeout_scale(), extra_env,
                expect_failure, local_size)
        except AssertionError as e:
            attempt += 1
            if attempt > retries or not infra_retryable(e):
                raise
            _log_retry(f"run_distributed attempt {attempt}: "
                       + str(e).splitlines()[0])
            retry_backoff(attempt)


def _run_distributed_once(n: int, body: str, timeout: float,
                          extra_env: Optional[Dict[str, str]],
                          expect_failure: bool,
                          local_size: Optional[int]) -> List[str]:
    from horovod_tpu.runner.rendezvous import RendezvousServer

    # Handoff point for reserved ports (e.g. the jax coordinator port in
    # extra_env): close the held sockets so the workers can bind them.
    release_reservations()
    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    script = PREAMBLE + body + ("" if expect_failure else EPILOGUE)
    ls = local_size or n
    assert n % ls == 0, "local_size must divide n"
    procs = []
    try:
        for r in range(n):
            env = os.environ.copy()
            env.update({
                "HOROVOD_RANK": str(r),
                "HOROVOD_SIZE": str(n),
                "HOROVOD_LOCAL_RANK": str(r % ls),
                "HOROVOD_LOCAL_SIZE": str(ls),
                "HOROVOD_CROSS_RANK": str(r // ls),
                "HOROVOD_CROSS_SIZE": str(n // ls),
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
                "JAX_PLATFORMS": "cpu",
            })
            # Mesh bring-up shares the load-scaled budget: run-1 audit of
            # the retry log showed every engagement was a bring-up
            # failure racing the product's fixed 60 s while neighbors'
            # 8-proc jobs drained.
            env.setdefault("HOROVOD_MESH_STARTUP_TIMEOUT",
                           scaled_mesh_startup_timeout())
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, "-c", script],
                env=env, cwd=REPO_ROOT, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        outs, errs, codes = [], [], []
        timed_out_rank = None
        for r, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                # Kill the whole job but KEEP collecting: a sibling that
                # crashed with a product error must contribute its section
                # to the retry gate — timeout text alone would always look
                # like infra flakiness and retry real bugs.
                if timed_out_rank is None:
                    timed_out_rank = r
                for q in procs:
                    q.kill()
                out, err = p.communicate()
            outs.append(out)
            errs.append(err)
            codes.append(p.returncode)
        if timed_out_rank is not None:
            sections = []
            for r, (code, out, err) in enumerate(zip(codes, outs, errs)):
                if r == timed_out_rank:
                    head = f"worker timed out after {timeout:.0f}s"
                elif code == 0 and f"WORKER_OK {r}" in out:
                    continue
                elif code and code < 0:
                    # our own post-timeout kill — infra by construction
                    head = (f"rank {r} killed after sibling timed out "
                            f"after {timeout:.0f}s")
                else:
                    head = f"rank {r} failed (exit {code}) before timeout"
                sections.append(
                    f"{head}\nstdout:\n{out}\nstderr:\n{err}")
            raise WorkerFailure("\n=== next failing rank ===\n"
                                .join(sections), sections)
        if not expect_failure:
            failing = [
                f"rank {r} failed (exit {code})\nstdout:\n{out}\nstderr:\n{err}"
                for r, (code, out, err) in enumerate(zip(codes, outs, errs))
                if code != 0 or f"WORKER_OK {r}" not in out
            ]
            if failing:
                raise WorkerFailure("\n=== next failing rank ===\n"
                                    .join(failing), failing)
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
