"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against `--xla_force_host_platform_device_count=8` CPU devices, mirroring
how the driver dry-runs the multi-chip path.  Must run before jax is
imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
