"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against `--xla_force_host_platform_device_count=8` CPU devices, mirroring
how the driver dry-runs the multi-chip path.

Two wrinkles: the outer environment may pin ``JAX_PLATFORMS`` to the real
TPU platform, and installed pytest plugins import jax before this conftest
runs (so jax has already latched the env value into its config).  Hence we
hard-set the env *and* update the live jax config.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Containerized CI reports the HOST's loadavg (≈0 even when this cgroup's
# cores are saturated), so the load-reactive timeout scale in
# tests/helpers.py never engages there.  Default to a 3x floor — the
# load-reactive scale can still exceed it on a genuinely loaded bare
# host (helpers._timeout_scale takes max(floor, load_scale)).  A timeout
# only binds when something is already slow, so healthy runs pay nothing
# and starved multi-process workers get real headroom.
os.environ.setdefault("HVD_TEST_TIMEOUT_SCALE", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # Audit trail for the infra-retry gate (helpers._log_retry): a de-flake
    # claim needs "zero engagements" to be checkable per run.
    import tempfile
    import time as _time

    os.environ.setdefault(
        "HVD_TEST_RETRY_LOG",
        os.path.join(tempfile.gettempdir(),
                     f"hvd_retries_{_time.strftime('%Y%m%d_%H%M%S')}"
                     f"_{os.getpid()}.log"))
    # "engagements this run" must mean THIS run even when the operator
    # pins the log path across runs: start from an empty file.
    open(os.environ["HVD_TEST_RETRY_LOG"], "w").close()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    path = os.environ.get("HVD_TEST_RETRY_LOG")
    lines = []
    if path and os.path.exists(path):
        with open(path) as f:
            lines = f.read().splitlines()
    terminalreporter.write_line(
        f"retry-gate engagements this run: {len(lines)}"
        + (f"  (log: {path})" if lines else ""))
    for ln in lines:
        terminalreporter.write_line("  " + ln)
