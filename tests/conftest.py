"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against `--xla_force_host_platform_device_count=8` CPU devices, mirroring
how the driver dry-runs the multi-chip path.

Two wrinkles: the outer environment may pin ``JAX_PLATFORMS`` to the real
TPU platform, and installed pytest plugins import jax before this conftest
runs (so jax has already latched the env value into its config).  Hence we
hard-set the env *and* update the live jax config.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Containerized CI reports the HOST's loadavg (≈0 even when this cgroup's
# cores are saturated), so the load-reactive timeout scale in
# tests/helpers.py never engages there.  Default to a 3x floor — the
# load-reactive scale can still exceed it on a genuinely loaded bare
# host (helpers._timeout_scale takes max(floor, load_scale)).  A timeout
# only binds when something is already slow, so healthy runs pay nothing
# and starved multi-process workers get real headroom.
os.environ.setdefault("HVD_TEST_TIMEOUT_SCALE", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")

# Lockdep (horovod_tpu/common/lockdep.py): when HOROVOD_LOCK_DEBUG is
# enabled, instrument THIS pytest process too (worker subprocesses
# self-install via the horovod_tpu import hook), so every in-process
# suite feeds the lock-order graph.  The exit-time report prints cycles;
# pytest_terminal_summary below surfaces the verdict per run.


def _lock_debug_enabled() -> bool:
    # Same truthiness as env.get_bool, without importing the package for
    # the (common) disabled case: "0"/"false"/"no"/"off"/"" are OFF.
    val = os.environ.get("HOROVOD_LOCK_DEBUG", "")
    return val.lower() not in ("", "0", "false", "no", "off")


if _lock_debug_enabled():
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from horovod_tpu.common import lockdep as _lockdep

    _lockdep.install()


def pytest_configure(config):
    # Audit trail for the infra-retry gate (helpers._log_retry): a de-flake
    # claim needs "zero engagements" to be checkable per run.
    import tempfile
    import time as _time

    os.environ.setdefault(
        "HVD_TEST_RETRY_LOG",
        os.path.join(tempfile.gettempdir(),
                     f"hvd_retries_{_time.strftime('%Y%m%d_%H%M%S')}"
                     f"_{os.getpid()}.log"))
    # "engagements this run" must mean THIS run even when the operator
    # pins the log path across runs: start from an empty file.
    open(os.environ["HVD_TEST_RETRY_LOG"], "w").close()


def pytest_collection_modifyitems(config, items):
    """Run chaos-marked tests LAST (stable sort: everything else keeps its
    order).  The chaos lane is wall-clock-heavy multiprocess jobs; signal
    from the fast functional tiers must never queue behind it, and
    ``ci/chaos.sh`` runs the lane standalone anyway."""
    items.sort(key=lambda it: it.get_closest_marker("chaos") is not None)


class TestWatchdogTimeout(Exception):
    """Raised in the test when its @pytest.mark.timeout bound expires."""


import pytest  # noqa: E402


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    """Per-test wall-clock guard for @pytest.mark.timeout(N).

    The chaos suite's whole point is the NO-HANG property: a regression
    that hangs a worker must fail that one test, not wedge the suite until
    the outer CI timeout kills everything.  SIGALRM interrupts the test in
    the main thread (subprocess waits included); bounds are load-scaled
    like every other suite timeout.  No-ops where SIGALRM is unavailable
    or pytest-timeout is installed (which then owns the marker)."""
    import signal
    import threading

    marker = item.get_closest_marker("timeout")
    if (marker is None or not marker.args
            or not hasattr(signal, "SIGALRM")
            or item.config.pluginmanager.hasplugin("timeout")
            or threading.current_thread() is not threading.main_thread()):
        return (yield)
    from .helpers import _timeout_scale

    seconds = max(1, int(marker.args[0] * _timeout_scale()))

    def _expired(signum, frame):
        raise TestWatchdogTimeout(
            f"test exceeded its {seconds}s watchdog bound "
            f"(@pytest.mark.timeout({marker.args[0]}), load-scaled)")

    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if _lock_debug_enabled():
        from horovod_tpu.common import lockdep

        cycles = lockdep.find_cycles()
        terminalreporter.write_line(
            f"lockdep: {len(lockdep.edges())} lock-order edge(s), "
            f"{len(cycles)} inversion cycle(s), "
            f"{len(lockdep.slow_waits())} held-lock blocking wait(s)")
        for cyc in cycles:
            terminalreporter.write_line(
                "lockdep INVERSION CYCLE: " + " -> ".join(cyc + cyc[:1]))
    path = os.environ.get("HVD_TEST_RETRY_LOG")
    lines = []
    if path and os.path.exists(path):
        with open(path) as f:
            lines = f.read().splitlines()
    terminalreporter.write_line(
        f"retry-gate engagements this run: {len(lines)}"
        + (f"  (log: {path})" if lines else ""))
    for ln in lines:
        terminalreporter.write_line("  " + ln)
