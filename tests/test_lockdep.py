"""lockdep: the runtime lock-order validator must be demonstrably live.

The headline test constructs a real two-thread A->B / B->A inversion and
asserts the detector reports exactly that cycle — proving that a chaos or
multiprocess run under ``HOROVOD_LOCK_DEBUG=1`` reporting zero cycles
means *validated*, not *not measured*.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from horovod_tpu.common import lockdep  # noqa: E402


@pytest.fixture()
def lockdep_session():
    """Install around the test with a tight slow-wait threshold.

    When the suite already runs under HOROVOD_LOCK_DEBUG=1 (conftest
    installed lockdep session-wide), the validator must stay installed and
    the session's accumulated graph must survive this file: snapshot the
    state, run the test against a clean slate, then put everything back.
    """
    was_installed = lockdep.is_installed()
    prev_slow = lockdep.slow_secs()
    snap = lockdep.snapshot()
    lockdep.reset()
    lockdep.install(slow_secs=0.15)
    try:
        yield lockdep
    finally:
        if not was_installed:
            lockdep.uninstall()
        lockdep.set_slow_secs(prev_slow)
        lockdep.restore(snap)


def _run_thread(fn, name):
    t = threading.Thread(target=fn, name=name)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


def test_locks_are_instrumented(lockdep_session):
    lk = threading.Lock()
    assert isinstance(lk, lockdep._Instrumented)
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_inversion_cycle_reported(lockdep_session):
    """A->B in one thread, B->A in another: no deadlock occurs (the
    threads run sequentially), but the ORDER disagreement alone must be
    reported — that is the whole lockdep idea."""
    a = threading.Lock()
    b = threading.Lock()

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    _run_thread(order_ab, "lockdep-ab")
    _run_thread(order_ba, "lockdep-ba")

    cycles = lockdep.find_cycles()
    assert len(cycles) == 1
    assert len(cycles[0]) == 2
    # Both sites live in this module.
    assert all(site.startswith("tests.test_lockdep:") for site in cycles[0])
    with pytest.raises(RuntimeError, match="inversion"):
        lockdep.check()
    assert lockdep.report(file=open(os.devnull, "w")) is False


def test_consistent_order_is_clean(lockdep_session):
    a = threading.Lock()
    b = threading.Lock()

    def nested():
        with a:
            with b:
                pass

    _run_thread(nested, "lockdep-c1")
    _run_thread(nested, "lockdep-c2")
    assert lockdep.find_cycles() == []
    assert lockdep.edges()  # the A->B edge itself was recorded
    lockdep.check()  # must not raise
    assert lockdep.report(file=open(os.devnull, "w")) is True


def test_held_lock_blocking_wait_recorded(lockdep_session):
    a = threading.Lock()
    c = threading.Lock()
    entered = threading.Event()

    def holder():
        with a:
            entered.set()
            time.sleep(0.4)

    t = threading.Thread(target=holder, name="lockdep-holder")
    t.start()
    assert entered.wait(timeout=5)
    with c:
        with a:  # blocks ~0.4s while holding c
            pass
    t.join(timeout=5)

    waits = lockdep.slow_waits()
    assert waits, "expected a held-lock blocking wait to be recorded"
    assert any(w["waited_secs"] >= 0.15 and w["held"] for w in waits)


def test_rlock_reentrancy_no_self_cycle(lockdep_session):
    r = threading.RLock()

    def reenter():
        with r:
            with r:
                pass

    _run_thread(reenter, "lockdep-reenter")
    assert lockdep.find_cycles() == []


def test_condition_on_instrumented_lock(lockdep_session):
    cv = threading.Condition()
    with cv:
        cv.wait(timeout=0.01)
    with cv:
        cv.notify_all()
    assert lockdep.find_cycles() == []


def test_stdlib_locks_stay_raw(lockdep_session):
    # queue.Queue allocates its mutex inside queue.py — must NOT be
    # instrumented (hot stdlib paths keep C-speed locks).
    q = queue.Queue()
    assert not isinstance(q.mutex, lockdep._Instrumented)


def test_handoff_release_prunes_stale_entry(lockdep_session):
    """A Lock acquired by one thread and released by another (handoff
    signal) must not leave a stale held entry fabricating ordering edges
    on the acquiring thread — and the unmatched release is reported."""
    handoff = threading.Lock()
    a = threading.Lock()
    b = threading.Lock()

    # No Event signalling here: an instrumented lock op while handoff is
    # held would record a REAL (and test-irrelevant) ordering edge, so
    # both sides poll the raw lock state instead.
    def releaser():
        deadline = time.time() + 5
        while not handoff.locked() and time.time() < deadline:
            time.sleep(0.01)
        handoff.release()  # ... another thread releases

    t = threading.Thread(target=releaser, name="lockdep-releaser")
    t.start()
    handoff.acquire()  # ... the main thread acquired
    deadline = time.time() + 5
    while handoff.locked() and time.time() < deadline:
        time.sleep(0.01)
    assert not handoff.locked(), "foreign release never happened"
    t.join(timeout=5)
    assert not t.is_alive()

    # Post-handoff, main takes a then b; without pruning, the stale
    # handoff entry would fabricate handoff->a and handoff->b edges.
    with a:
        with b:
            pass

    # Exactly the a->b edge; a stale handoff entry would add
    # handoff->a and handoff->b (3 edges over 3 sites).
    assert len(lockdep.edges()) == 1
    sites = {site for edge in lockdep.edges() for site in edge}
    assert len(sites) == 2
    assert lockdep.find_cycles() == []

    import io
    buf = io.StringIO()
    assert lockdep.report(file=buf) is True  # unmatched release != cycle
    assert "UNMATCHED RELEASE" in buf.getvalue()


def test_handoff_credit_keyed_to_acquiring_thread(lockdep_session):
    """The prune credit belongs to the thread whose stack holds the stale
    entry.  A third thread's later legitimate acquire/release of the same
    lock must NOT consume it (or be misreported as unmatched)."""
    handoff = threading.Lock()

    def releaser():
        deadline = time.time() + 5
        while not handoff.locked() and time.time() < deadline:
            time.sleep(0.01)
        handoff.release()

    def legit_user():
        # fully matched acquire/release on a third thread
        with handoff:
            pass

    t = threading.Thread(target=releaser, name="lockdep-releaser2")
    t.start()
    handoff.acquire()  # main acquires; stale entry lives on main's stack
    deadline = time.time() + 5
    while handoff.locked() and time.time() < deadline:
        time.sleep(0.01)
    assert not handoff.locked(), "foreign release never happened"
    t.join(timeout=5)
    _run_thread(legit_user, "lockdep-legit")

    # Exactly one unmatched release recorded — the handoff, not legit's
    # (the buggy instance-global credit consumed legit's own fresh entry
    # and misreported its matched release as a second unmatched one).
    import io
    buf = io.StringIO()
    lockdep.report(file=buf)
    assert buf.getvalue().count("UNMATCHED RELEASE") == 1
    assert "lockdep-releaser2" in buf.getvalue()

    # Main's stale entry is still pruned by main's next lock op: a later
    # nested pair records only its own edge.
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    assert len(lockdep.edges()) == 1
    assert lockdep.find_cycles() == []


def test_uninstall_restores_raw_factories():
    if lockdep.is_installed():
        pytest.skip("ambient HOROVOD_LOCK_DEBUG session owns the install")
    snap = lockdep.snapshot()
    lockdep.install()
    lockdep.uninstall()
    lk = threading.Lock()
    assert not isinstance(lk, lockdep._Instrumented)
    lockdep.restore(snap)


def test_requested_reads_env_knob(monkeypatch):
    from horovod_tpu.common import env as env_mod

    monkeypatch.delenv(env_mod.HOROVOD_LOCK_DEBUG, raising=False)
    assert not lockdep.requested()
    monkeypatch.setenv(env_mod.HOROVOD_LOCK_DEBUG, "1")
    assert lockdep.requested()
    monkeypatch.setenv(env_mod.HOROVOD_LOCK_DEBUG, "0")
    assert not lockdep.requested()
