"""Wire-format roundtrip tests (reference analog: message serialization used
throughout `test/parallel/*`; here tested directly)."""

import numpy as np
import pytest

from horovod_tpu.core.messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
)


pytestmark = pytest.mark.smoke


def test_request_roundtrip():
    req = Request(
        request_rank=3,
        request_type=RequestType.ALLREDUCE,
        tensor_name="layer0/kernel.grad",
        tensor_type=DataType.BFLOAT16,
        tensor_shape=[128, 784],
        root_rank=-1,
        device=0,
        prescale_factor=0.5,
        postscale_factor=0.25,
    )
    rl = RequestList(requests=[req, Request(tensor_name="b")], shutdown=False)
    out = RequestList.from_bytes(rl.to_bytes())
    assert out.shutdown is False
    assert len(out.requests) == 2
    got = out.requests[0]
    assert got == req
    assert out.requests[1].tensor_name == "b"


def test_request_nbytes():
    req = Request(tensor_type=DataType.FLOAT32, tensor_shape=[4, 8])
    assert req.num_elements == 32
    assert req.nbytes == 128


def test_response_roundtrip():
    resp = Response(
        response_type=ResponseType.ALLGATHER,
        tensor_names=["x", "y"],
        tensor_type=DataType.FLOAT64,
        tensor_sizes=[5, 9],
        devices=[0, 1],
        prescale_factor=2.0,
        postscale_factor=0.125,
        last_joined_rank=1,
    )
    rl = ResponseList(responses=[resp], shutdown=True)
    out = ResponseList.from_bytes(rl.to_bytes())
    assert out.shutdown is True
    assert out.responses[0] == resp


def test_error_response_roundtrip():
    resp = Response(response_type=ResponseType.ERROR,
                    tensor_names=["bad"],
                    error_message="shape mismatch: rank 0 [2] vs rank 1 [3]")
    out = ResponseList.from_bytes(ResponseList(responses=[resp]).to_bytes())
    assert out.responses[0].response_type == ResponseType.ERROR
    assert "mismatch" in out.responses[0].error_message


def test_bad_magic_rejected():
    with pytest.raises(ValueError):
        RequestList.from_bytes(b"\x00\x00\x00\x00\x00\x00\x00\x00")


def test_abort_frame_roundtrip():
    from horovod_tpu.core.messages import AbortFrame, is_abort_frame

    frame = AbortFrame(epoch=3, origin_rank=2,
                       reason="stall shutdown: tensor g, missing ranks [1]")
    data = frame.to_bytes()
    assert is_abort_frame(data)
    assert not is_abort_frame(RequestList().to_bytes())
    out = AbortFrame.from_bytes(data)
    assert (out.epoch, out.origin_rank) == (3, 2)
    assert "missing ranks [1]" in out.reason
    with pytest.raises(ValueError):
        AbortFrame.from_bytes(RequestList().to_bytes())


def test_abort_reason_bounded_at_construction():
    """A giant traceback in the reason must not bloat the control frame:
    ≤ 512 UTF-8 bytes from construction on, truncation marked, and the
    bounded frame still round-trips."""
    from horovod_tpu.core.messages import (
        MAX_ABORT_REASON_BYTES,
        AbortFrame,
    )

    frame = AbortFrame(epoch=1, origin_rank=0, reason="x" * 10_000)
    assert len(frame.reason.encode("utf-8")) <= MAX_ABORT_REASON_BYTES
    assert frame.reason.endswith("…[truncated]")
    out = AbortFrame.from_bytes(frame.to_bytes())
    assert out.reason == frame.reason
    # multi-byte characters at the cut never split into mojibake
    multi = AbortFrame(reason="é" * 600)
    assert len(multi.reason.encode("utf-8")) <= MAX_ABORT_REASON_BYTES
    multi.reason.encode("utf-8").decode("utf-8")  # still valid UTF-8
    # short reasons pass through untouched
    assert AbortFrame(reason="peer died").reason == "peer died"


def test_bad_magic_reports_got_expected_and_hexdump():
    wire = ResponseList().to_bytes()
    with pytest.raises(ValueError) as exc:
        # a MaskFrame parser fed a ResponseList frame
        from horovod_tpu.core.messages import MaskFrame

        MaskFrame.from_bytes(wire)
    msg = str(exc.value)
    assert "got 0x48564454" in msg          # WIRE_MAGIC it found
    assert "expected 0x4B53414D" in msg     # MASK_MAGIC it wanted
    assert wire[:16].hex(" ") in msg        # the head hexdump


# ---------------------------------------------------------------------------
# single-byte-flip / truncation fuzz: the two-layer integrity contract
# ---------------------------------------------------------------------------

def _exemplar_frames():
    """One realistic instance of EVERY frame type that crosses the wire."""
    from horovod_tpu.core.messages import AbortFrame, MaskFrame

    req = Request(
        request_rank=3, request_type=RequestType.ALLGATHER,
        tensor_name="layer0/kernel.grad", tensor_type=DataType.BFLOAT16,
        tensor_shape=[128, 784], root_rank=1, device=0, group_id=2,
        prescale_factor=0.5, postscale_factor=0.25, splits=[1, 2, 3])
    resp = Response(
        response_type=ResponseType.ALLREDUCE, tensor_names=["a", "b"],
        tensor_type=DataType.FLOAT32, tensor_sizes=[5, 9],
        error_message="err", devices=[0, 1], prescale_factor=2.0,
        postscale_factor=0.125, last_joined_rank=1)
    return [
        ("RequestList", RequestList,
         RequestList(requests=[req, Request(tensor_name="b")],
                     shutdown=True, cache_hits=[1, 5],
                     cache_mask=b"\x2a\x01")),
        ("ResponseList", ResponseList,
         ResponseList(responses=[resp], shutdown=False,
                      cache_assignments=[(7, req)], evicted_bits=[2],
                      tuned_params=(64 << 20, 1.5))),
        ("MaskFrame", MaskFrame, MaskFrame(mask=b"\xff\x10", shutdown=True)),
        ("AbortFrame", AbortFrame,
         AbortFrame(epoch=4, origin_rank=1, reason="peer rank 2 is gone")),
    ]


def test_every_frame_type_roundtrips():
    for name, cls, frame in _exemplar_frames():
        assert cls.from_bytes(frame.to_bytes()) == frame, name


def test_single_byte_flip_never_silently_misparses():
    """The integrity contract, exhaustively: for EVERY byte position and
    a spread of XOR masks, a flipped frame either (a) raises a TYPED
    parse error — never a raw struct.error — or (b) parses into a
    DIFFERENT value, which the wire CRC catches (crc32 of the flipped
    bytes always differs for a single-byte flip).  A flip that parsed
    back EQUAL to the original would be a silent misparse past both
    layers — the bug class this plane exists to kill."""
    import struct as struct_mod
    import zlib

    from horovod_tpu.common.exceptions import TruncatedFrameError

    for name, cls, frame in _exemplar_frames():
        wire = frame.to_bytes()
        base_crc = zlib.crc32(wire)
        for pos in range(len(wire)):
            for mask in (0x01, 0x80, 0xFF):
                flipped = wire[:pos] + bytes([wire[pos] ^ mask]) \
                    + wire[pos + 1:]
                try:
                    out = cls.from_bytes(flipped)
                except (TruncatedFrameError, ValueError, OverflowError):
                    continue  # typed parse-layer rejection
                except struct_mod.error:  # pragma: no cover
                    pytest.fail(f"{name}: raw struct.error leaked at "
                                f"byte {pos} mask 0x{mask:02X}")
                assert out != frame or zlib.crc32(flipped) != base_crc, \
                    f"{name}: silent misparse at byte {pos} mask {mask:#x}"
                # CRC32 detects every single-byte flip, so layer 2 always
                # catches what the parser accepted:
                assert zlib.crc32(flipped) != base_crc


def test_truncated_prefix_always_typed_error():
    """Every strict prefix of every frame fails TYPED (truncation is what
    an interrupted sender or an injected truncate fault produces)."""
    from horovod_tpu.common.exceptions import TruncatedFrameError

    for name, cls, frame in _exemplar_frames():
        wire = frame.to_bytes()
        for cut in range(len(wire)):
            with pytest.raises((TruncatedFrameError, ValueError)):
                cls.from_bytes(wire[:cut])


@pytest.mark.parametrize("np_dtype", [
    np.uint8, np.int8, np.int32, np.int64, np.float16, np.float32,
    np.float64, np.bool_,
])
def test_dtype_mapping_roundtrip(np_dtype):
    dt = DataType.from_numpy(np_dtype)
    assert dt.to_numpy() == np.dtype(np_dtype)
    assert dt.itemsize == np.dtype(np_dtype).itemsize


def test_bfloat16_mapping():
    import ml_dtypes

    dt = DataType.from_numpy(ml_dtypes.bfloat16)
    assert dt == DataType.BFLOAT16
    assert dt.itemsize == 2


def test_topology_from_env(monkeypatch):
    from horovod_tpu.common import topology

    monkeypatch.setenv("HOROVOD_RANK", "3")
    monkeypatch.setenv("HOROVOD_SIZE", "8")
    monkeypatch.setenv("HOROVOD_LOCAL_RANK", "1")
    monkeypatch.setenv("HOROVOD_LOCAL_SIZE", "4")
    monkeypatch.setenv("HOROVOD_CROSS_RANK", "0")
    monkeypatch.setenv("HOROVOD_CROSS_SIZE", "2")
    topo = topology.from_env()
    assert topo.rank == 3 and topo.size == 8
    assert topo.local_rank == 1 and topo.local_size == 4
    assert topo.cross_size == 2
    assert topo.is_homogeneous


def test_topology_defaults(monkeypatch):
    from horovod_tpu.common import topology

    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE"):
        monkeypatch.delenv(k, raising=False)
    topo = topology.from_env()
    assert topo.rank == 0 and topo.size == 1
    assert topo.is_coordinator


def test_topology_validation():
    from horovod_tpu.common.topology import ProcessTopology

    with pytest.raises(ValueError):
        ProcessTopology(rank=2, size=2)
    with pytest.raises(ValueError):
        ProcessTopology(rank=0, size=4, local_size=2, cross_size=1)
