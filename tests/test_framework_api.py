"""Framework-level API tests: DistributedOptimizer, object broadcast,
compression — run under real worker subprocesses (the parallel test tier,
reference `test/parallel/test_torch.py` style)."""

import numpy as np

from .helpers import run_distributed


def test_broadcast_parameters_and_object():
    out = run_distributed(2, """
from horovod_tpu.frameworks.jax.functions import (
    broadcast_parameters, broadcast_object, allgather_object)

params = {"w": np.full((3,), float(rank)), "b": np.array([rank + 1.0])}
synced = broadcast_parameters(params, root_rank=1)
assert np.allclose(synced["w"], 1.0), synced
assert np.allclose(synced["b"], 2.0), synced

obj = broadcast_object({"lr": 0.5, "rank": rank} if rank == 0 else None,
                       root_rank=0)
assert obj == {"lr": 0.5, "rank": 0}, obj

gathered = allgather_object(("r", rank))
assert gathered == [("r", 0), ("r", 1)], gathered
print("FUNCS_OK", rank)
""")
    for r, o in enumerate(out):
        assert f"FUNCS_OK {r}" in o


def test_distributed_optimizer_sgd():
    out = run_distributed(2, """
import optax
from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer

tx = DistributedOptimizer(optax.sgd(0.1))
params = {"w": np.ones(4, np.float32)}
state = tx.init(params)
# rank-dependent grads: average = (0+2)/2 = 1.0 -> update = -0.1
grads = {"w": np.full(4, 2.0 * rank, np.float32)}
updates, state = tx.update(grads, state, params)
assert np.allclose(np.asarray(updates["w"]), -0.1), updates
print("OPT_OK", rank)
""")
    for r, o in enumerate(out):
        assert f"OPT_OK {r}" in o


def test_distributed_optimizer_backward_passes_per_step():
    out = run_distributed(2, """
import optax
from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer

tx = DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
params = {"w": np.zeros(2, np.float32)}
state = tx.init(params)
g1 = {"w": np.full(2, 1.0 + rank, np.float32)}
u1, state = tx.update(g1, state, params)
assert np.allclose(np.asarray(u1["w"]), 0.0), u1  # off step: zero update
u2, state = tx.update(g1, state, params)
# accumulated avg per rank = (1+rank); cross-rank avg = 1.5; lr=1 -> -1.5
assert np.allclose(np.asarray(u2["w"]), -1.5), u2
print("ACCUM_OK", rank)
""")
    for r, o in enumerate(out):
        assert f"ACCUM_OK {r}" in o


def test_compression_fp16_roundtrip():
    out = run_distributed(2, """
from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer
from horovod_tpu.frameworks.jax.compression import Compression
import optax

comp, ctx = Compression.fp16.compress(np.ones(3, np.float32))
assert comp.dtype == np.float16
back = Compression.fp16.decompress(comp, ctx)
assert back.dtype == np.float32

tx = DistributedOptimizer(optax.sgd(0.1), compression=Compression.fp16)
params = {"w": np.ones(4, np.float32)}
state = tx.init(params)
grads = {"w": np.full(4, float(rank), np.float32)}
updates, state = tx.update(grads, state, params)
assert np.allclose(np.asarray(updates["w"]), -0.05), updates
print("COMP_OK", rank)
""")
    for r, o in enumerate(out):
        assert f"COMP_OK {r}" in o


def test_distributed_value_and_grad():
    out = run_distributed(2, """
import jax.numpy as jnp
from horovod_tpu.frameworks.jax.optimizer import distributed_value_and_grad

def loss(w):
    return (w ** 2).sum() * (rank + 1)

vg = distributed_value_and_grad(loss)
val, grad = vg(jnp.ones(3))
# grads: rank0 2w, rank1 4w -> avg 3w = 3
assert np.allclose(np.asarray(grad), 3.0), grad
print("VG_OK", rank)
""")
    for r, o in enumerate(out):
        assert f"VG_OK {r}" in o


def test_checkpoint_save_restore_broadcast(tmp_path):
    """Rank 0 writes orbax, every rank restores the identical tree even
    though only rank 0 reads storage (reference rank-0-checkpoint +
    broadcast fan-out idiom, SURVEY §5.4)."""
    out = run_distributed(2, f"""
import jax.numpy as jnp
import horovod_tpu.frameworks.jax.checkpoint as ckpt

path = {str(tmp_path)!r} + "/state"
state = {{"w": jnp.arange(4, dtype=jnp.float32) * (rank + 1),
          "step": jnp.asarray(7)}}
# only rank 0's state is durable; all ranks call save
ckpt.save(path, state)
assert ckpt.exists(path)
restored = ckpt.restore(path)
# every rank gets RANK 0's tree
assert np.allclose(np.asarray(restored["w"]), np.arange(4)), restored
assert int(restored["step"]) == 7
assert not ckpt.exists(path + ".missing")
print("CKPT_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"CKPT_OK {r}" in o


def test_checkpoint_rotating_self_healing_np2(tmp_path):
    """The integrity-plane checkpoint contract, distributed: a missing
    checkpoint raises typed CheckpointNotFoundError on EVERY rank (no
    exists()+restore() TOCTOU), rotation prunes to ``keep``, and a
    corrupted newest snapshot falls back to the previous valid one on
    every rank."""
    out = run_distributed(2, f"""
import os
import horovod_tpu.frameworks.jax.checkpoint as ckpt
from horovod_tpu.common.exceptions import CheckpointNotFoundError

base = {str(tmp_path)!r} + "/run"

# 1. nothing there yet: typed not-found on every rank, not a hang/TOCTOU
try:
    ckpt.restore_latest(base)
    print("MISSED_NOT_FOUND", rank, flush=True)
except CheckpointNotFoundError:
    print("NOT_FOUND_OK", rank, flush=True)
try:
    ckpt.restore(base + ".direct")
except CheckpointNotFoundError:
    print("RESTORE_NOT_FOUND_OK", rank, flush=True)

# 2. three rotating saves with keep=2: oldest pruned
like = {{"w": np.zeros(4, np.float32), "step": np.asarray(0)}}
for step in (1, 2, 3):
    ckpt.save_rotating(
        base, {{"w": np.full(4, float(step), np.float32),
               "step": np.asarray(step)}}, keep=2, step=step)
if rank == 0:
    snaps = ckpt._list_snapshots(os.path.abspath(base))
    assert [s for s, _ in snaps] == [3, 2], snaps

# 3. corrupt the NEWEST on disk (rank 0 only touches storage); every
#    rank still restores the previous valid snapshot
if rank == 0:
    snap = ckpt._list_snapshots(os.path.abspath(base))[0][1]
    victim = None
    for dp, _, fn in os.walk(snap):
        for f in fn:
            p = os.path.join(dp, f)
            if victim is None or os.path.getsize(p) > os.path.getsize(victim):
                victim = p
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(raw))
state = ckpt.restore_latest(base, like=like)
assert int(state["step"]) == 2, state
assert np.allclose(np.asarray(state["w"]), 2.0), state
print("HEALED_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        for mark in ("NOT_FOUND_OK", "RESTORE_NOT_FOUND_OK", "HEALED_OK"):
            assert f"{mark} {r}" in o, (mark, r, o)
        assert "MISSED_NOT_FOUND" not in o
