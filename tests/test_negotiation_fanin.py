"""Tree negotiation fan-in (core/negotiation_fanin.py): fold semantics,
role/plan derivation, heartbeat conviction, veto bookkeeping, and a live
np=4 two-loopback-host run counter-asserting the O(ranks) -> O(hosts)
coordinator-ingress drop with bit-identical results against the star.

The degrade protocol's crash/reorder interleavings are model-checked in
tests/test_mck_proto.py (hvd-mck's fanin_degrade scenario); the
aggregator-death chaos test (abort -> reshard -> bit-identical
convergence) lives with the other elastic proofs in
tests/test_fault_injection.py.
"""

import os
import time

import numpy as np
import pytest

from horovod_tpu.common import env as env_mod
from horovod_tpu.common.exceptions import (
    AggregatorStaleError,
    HorovodInternalError,
)
from horovod_tpu.common.topology import ProcessTopology
from horovod_tpu.core.messages import (
    HostMaskFrame,
    MaskFrame,
    is_host_mask_frame,
)
from horovod_tpu.core.negotiation_fanin import (
    AggregatorHeartbeat,
    FaninPlan,
    active_vetoes,
    build_plan,
    fold_host,
    resolve_mode,
)
from horovod_tpu.elastic.fanin import HEARTBEAT_STALE_PERIODS

from .helpers import run_distributed


def _mask(bits: int, shutdown: bool = False) -> bytes:
    size = max(1, (bits.bit_length() + 7) // 8)
    return MaskFrame(mask=bits.to_bytes(size, "little"),
                     shutdown=shutdown).to_bytes()


def _topo(rank, size, ls):
    return ProcessTopology(rank=rank, size=size, local_rank=rank % ls,
                           local_size=ls, cross_rank=rank // ls,
                           cross_size=size // ls)


class TestFoldHost:
    def test_masks_collapse_to_one_host_frame(self):
        entries = fold_host([(4, _mask(0b0111)), (5, _mask(0b1011)),
                             (6, _mask(0b1110))])
        assert len(entries) == 1
        rank, payload = entries[0]
        assert rank == 4 and is_host_mask_frame(payload)
        frame = HostMaskFrame.from_bytes(payload)
        assert frame.covered == [4, 5, 6]
        assert frame.mask_int == 0b0111 & 0b1011 & 0b1110
        assert frame.shutdown is False

    def test_shutdown_is_or_of_covered_flags(self):
        entries = fold_host([(2, _mask(0b11)), (3, _mask(0b11,
                                                         shutdown=True))])
        assert HostMaskFrame.from_bytes(entries[0][1]).shutdown is True

    def test_non_mask_payloads_pass_unfolded(self):
        full = b"not-a-mask-frame"
        entries = fold_host([(2, _mask(0b10)), (3, full), (4, _mask(0b11))])
        assert entries == sorted(entries)
        assert (3, full) in entries
        frames = [e for e in entries if is_host_mask_frame(e[1])]
        assert len(frames) == 1
        assert HostMaskFrame.from_bytes(frames[0][1]).covered == [2, 4]

    def test_wide_masks_survive_per_host_bit_offsets(self):
        """Cache bits are a global big-int bitvector: a host whose ranks
        announce bits far past the first byte must fold without
        truncation (the little-endian width follows the AND's
        bit_length, not any fixed frame size)."""
        hi = (1 << 300) | (1 << 9) | 1
        lo = (1 << 300) | (1 << 9) | (1 << 2)
        entries = fold_host([(8, _mask(hi)), (9, _mask(lo))])
        frame = HostMaskFrame.from_bytes(entries[0][1])
        assert frame.mask_int == hi & lo == (1 << 300) | (1 << 9)
        # round-trips through the wire encoding untruncated
        assert HostMaskFrame.from_bytes(frame.to_bytes()).mask_int \
            == frame.mask_int

    def test_fold_is_pure_and_order_insensitive(self):
        """The mck model leans on the fold being a pure per-cycle
        function; the live bundle leans on member arrival order being
        invisible (the AND is commutative, covered is sorted)."""
        a = [(4, _mask(0b0110)), (5, _mask(0b0011))]
        assert fold_host(a) == fold_host(a) == fold_host(list(reversed(a)))

    def test_empty_input_folds_to_nothing(self):
        assert fold_host([]) == []


class TestResolveModeAndPlan:
    def test_auto_on_for_blocked_multihost(self, monkeypatch):
        monkeypatch.delenv(env_mod.HOROVOD_NEGOTIATION_FANIN, raising=False)
        assert resolve_mode(_topo(0, 4, 2)) == "on"

    @pytest.mark.parametrize("size,ls", [(2, 1), (4, 4), (4, 1), (8, 8)])
    def test_auto_off_when_tree_cannot_pay(self, monkeypatch, size, ls):
        """Single-rank hosts have nothing to fold and single-host jobs
        have no cross link to save: auto stays off (the bypass the
        ISSUE's satellite names)."""
        monkeypatch.delenv(env_mod.HOROVOD_NEGOTIATION_FANIN, raising=False)
        assert resolve_mode(_topo(1, size, ls)) == "off"

    def test_forced_off_and_bad_values(self, monkeypatch):
        monkeypatch.setenv(env_mod.HOROVOD_NEGOTIATION_FANIN, "0")
        assert resolve_mode(_topo(0, 4, 2)) == "off"
        monkeypatch.setenv(env_mod.HOROVOD_NEGOTIATION_FANIN, "banana")
        with pytest.raises(ValueError):
            resolve_mode(_topo(0, 4, 2))

    def test_forced_on_bad_layout_is_loud(self, monkeypatch):
        monkeypatch.setenv(env_mod.HOROVOD_NEGOTIATION_FANIN, "1")
        with pytest.raises(HorovodInternalError):
            resolve_mode(_topo(0, 4, 4))       # single host

    def test_roles_at_2x3(self):
        """np=6, local_size=2, three hosts: host 0 is direct (its
        would-be aggregator IS the coordinator), hosts 1-2 tree."""
        plans = {r: build_plan(_topo(r, 6, 2)) for r in range(6)}
        assert plans[0].role == "coordinator"
        assert plans[0].coordinator_senders == (1, 2, 4)
        assert plans[0].bundle_senders == frozenset({2, 4})
        assert plans[1].role == "direct"
        assert plans[2].role == "aggregator"
        assert plans[2].member_ranks == (3,)
        assert plans[3].role == "member"
        assert plans[3].aggregator_rank == 2
        assert plans[4].role == "aggregator" and plans[5].role == "member"

    def test_vetoed_host_degrades_to_direct(self):
        """A vetoed host's ranks all run direct and the coordinator
        expects them individually — exactly the star wire shape for that
        host, nothing silenced."""
        plans = {r: build_plan(_topo(r, 6, 2), vetoed_hosts=[1])
                 for r in range(6)}
        assert plans[2].role == "direct" and plans[3].role == "direct"
        assert plans[0].coordinator_senders == (1, 2, 3, 4)
        assert plans[0].bundle_senders == frozenset({4})
        assert plans[4].role == "aggregator"        # host 2 still trees

    def test_unblocked_layout_refused(self):
        bad = ProcessTopology(rank=1, size=4, local_rank=0, local_size=2,
                              cross_rank=1, cross_size=2)
        with pytest.raises(HorovodInternalError):
            build_plan(bad)


class TestAggregatorHeartbeat:
    def _hb(self, tmp_path, is_aggregator, period=1.0):
        return AggregatorHeartbeat(str(tmp_path / "hb"), period,
                                   aggregator_rank=2, cross_rank=1,
                                   is_aggregator=is_aggregator)

    def _mock_clock(self, monkeypatch, start=1000.0):
        """Drive both the heartbeat's wall clock AND the file mtimes it
        stats from one fake clock (os.utime(None) would otherwise stamp
        REAL time and every age computation would go negative)."""
        now = [start]
        real_utime = os.utime
        monkeypatch.setattr(time, "time", lambda: now[0])
        monkeypatch.setattr(
            os, "utime", lambda p, t=None: real_utime(p, (now[0], now[0])))
        return now

    def test_absent_file_fresh_during_arming_grace(self, tmp_path,
                                                   monkeypatch):
        now = self._mock_clock(monkeypatch)
        hb = self._hb(tmp_path, is_aggregator=False)
        hb.check()                                  # armed just now: fresh
        now[0] += HEARTBEAT_STALE_PERIODS - 0.1
        hb.check()                                  # still inside grace
        now[0] += 0.6                               # past grace + rate limit
        with pytest.raises(AggregatorStaleError) as ei:
            hb.check()
        assert ei.value.aggregator_rank == 2

    def test_touch_keeps_member_fresh_until_window(self, tmp_path,
                                                   monkeypatch):
        now = self._mock_clock(monkeypatch)
        agg = self._hb(tmp_path, is_aggregator=True)
        member = self._hb(tmp_path, is_aggregator=False)
        for _ in range(5):
            now[0] += 1.0
            agg.touch()
            member.check()                          # fresh every period
        # the aggregator wedges: stops touching; ~1.5 periods later the
        # member convicts (HEARTBEAT_STALE_PERIODS shared with
        # elastic/fanin.py so both planes degrade on the same clock)
        now[0] += HEARTBEAT_STALE_PERIODS + 0.1
        with pytest.raises(AggregatorStaleError):
            member.check()

    def test_checks_are_rate_limited(self, tmp_path, monkeypatch):
        now = self._mock_clock(monkeypatch)
        self._hb(tmp_path, is_aggregator=True)      # stamps the file once
        member = self._hb(tmp_path, is_aggregator=False)
        now[0] += HEARTBEAT_STALE_PERIODS + 1.0     # stale by now...
        member._last_check = now[0] - 0.1           # ...but just checked
        member.check()                              # rate limit: no stat
        now[0] += 0.5
        with pytest.raises(AggregatorStaleError):
            member.check()


class TestVetoBookkeeping:
    def test_active_vetoes_window_and_malformed(self, monkeypatch):
        monkeypatch.setenv(env_mod.HOROVOD_NEGOTIATION_FANIN_VETO_EPOCHS,
                           "2")
        records = {
            "host-a": {"epoch": 9},                 # 1 epoch old: active
            "host-b": {"epoch": 8},                 # 2 epochs old: expired
            "host-c": {"epoch": 10},                # this epoch: active
            "host-d": {"epoch": "not-an-int"},      # malformed: ignored
            "host-e": {},                           # malformed: ignored
        }
        assert active_vetoes(records, epoch=10) == ["host-a", "host-c"]


# ---------------------------------------------------------------------------
# live np=4 (2 simulated hosts x 2 ranks): the counter-asserted
# O(ranks) -> O(hosts) ingress drop, with star-vs-tree bit-identity
# ---------------------------------------------------------------------------

_NP4_BODY = """
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.core.state import global_state

hvd.init()
for i in range(6):
    out = hvd.allreduce(np.full(4, float(hvd.rank() + i), np.float32),
                        op=hvd.Sum, name=f"t{i}")
    print("SUM", i, hvd.rank(), np.asarray(out).tobytes().hex(), flush=True)
c = global_state().controller
plan = c.fanin_plan
print("ROLE", hvd.rank(), plan.role if plan else "none", flush=True)
print("COUNTS", hvd.rank(), c.ingress_frame_count,
      c.fanin_tree_frame_count, c.fanin_direct_frame_count,
      c.fanin_fallback_count, flush=True)
hvd.shutdown()
"""


@pytest.mark.timeout(300)
def test_np4_tree_ingress_o_hosts_bit_identical_to_star():
    """Two loopback hosts x two ranks.  Under the tree the coordinator
    ingests 2 frames per busy cycle (host 0's direct member + host 1's
    bundle) instead of the star's 3 — the counter assertion, not
    wall-clock — and every rank's allreduce bytes are identical between
    the two modes (the fold only touches frames whose meaning is "AND
    me", so the agreed masks and therefore the math cannot move)."""
    runs = {}
    for mode in ("auto", "0"):
        outs = run_distributed(
            4, _NP4_BODY, timeout=180, local_size=2,
            extra_env={"HOROVOD_NEGOTIATION_FANIN": mode})
        parsed = {"sums": {}, "roles": {}, "counts": {}}
        for out in outs:
            for line in out.splitlines():
                parts = line.split()
                if parts[:1] == ["SUM"]:
                    parsed["sums"][(int(parts[1]), int(parts[2]))] = parts[3]
                elif parts[:1] == ["ROLE"]:
                    parsed["roles"][int(parts[1])] = parts[2]
                elif parts[:1] == ["COUNTS"]:
                    parsed["counts"][int(parts[1])] = [int(x)
                                                       for x in parts[2:]]
        runs[mode] = parsed

    tree, star = runs["auto"], runs["0"]
    assert tree["roles"] == {0: "coordinator", 1: "direct",
                             2: "aggregator", 3: "member"}
    assert star["roles"] == {r: "none" for r in range(4)}
    # bit-identity: every (tensor, rank) sum matches across modes
    assert tree["sums"] == star["sums"]
    assert len(tree["sums"]) == 24
    # ingress drop, counter-asserted: same workload, same busy-cycle
    # structure (the lockstep mesh is deterministic for a fixed
    # per-rank program), so frames shrink by exactly senders-per-cycle
    # 3 -> 2.  No fallbacks fired.
    star_ingress = star["counts"][0][0]
    tree_ingress = tree["counts"][0][0]
    assert star_ingress > 0 and star_ingress % 3 == 0
    assert tree_ingress * 3 == star_ingress * 2, (tree_ingress,
                                                  star_ingress)
    assert all(c[3] == 0 for c in tree["counts"].values())
    # the tree actually carried frames on both tree roles, and host 0's
    # non-coordinator rank rode the counted direct path
    assert tree["counts"][2][1] > 0 and tree["counts"][3][1] > 0
    assert tree["counts"][1][2] > 0
