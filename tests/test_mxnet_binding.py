"""MXNet binding vs an async dependency engine (fake_mxnet).

Reference analog: ``mxnet/mpi_ops.cc:182-191`` serializes collectives with
NDArray compute via engine read/write var deps, covered upstream by
``test/parallel/test_mxnet.py``.  Our bridge relies on the NDArray sync
points instead (``asnumpy`` waits for pending writes; ``tensor[:] =``
enqueues a write); these tests run it against ``tests/fake_mxnet.py``'s
genuinely-asynchronous engine so an eager-execution assumption would read
stale buffers and fail.
"""

from __future__ import annotations

import sys

import numpy as np
import pytest

from . import fake_mxnet


@pytest.fixture(scope="module")
def _runtime():
    # One init/shutdown for the module: the eager runtime is a process
    # singleton and cycling it per-test leaves the next init a no-op
    # against a drained background loop.  The fake is installed
    # UNCONDITIONALLY (these tests assert fake types — running against a
    # previously-imported real mxnet would be a different suite) and the
    # prior sys.modules entry is restored afterwards.
    prior = sys.modules.get("mxnet")
    sys.modules["mxnet"] = fake_mxnet
    import horovod_tpu.mxnet as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()
    if prior is not None:
        sys.modules["mxnet"] = prior
    else:
        sys.modules.pop("mxnet", None)


@pytest.fixture()
def hvd_mx(_runtime):
    return _runtime


@pytest.mark.smoke
def test_allreduce_roundtrip(hvd_mx):
    x = fake_mxnet.nd.array([1.0, 2.0, 3.0])
    out = hvd_mx.allreduce(x, name="mx.rt")
    assert isinstance(out, fake_mxnet.NDArray)
    assert np.allclose(out.asnumpy(), [1.0, 2.0, 3.0])  # size 1: identity


@pytest.mark.smoke
def test_engine_ordering_interleaved_mutation(hvd_mx):
    """Mutate the same NDArray before and after in-place collectives: the
    collective must observe every mutation enqueued before it, and later
    mutations must land after it.  x_{k+1} = 2*x_k + 1 from x_0 = 1 gives
    x_n = 2^(n+1) - 1; any ordering violation (collective reading the
    pre-doubled buffer, or the +1 racing the write-back) breaks the
    closed form."""
    x = fake_mxnet.nd.ones((1024,))
    for _ in range(8):
        x *= 2.0                                   # pending engine write
        hvd_mx.allreduce_(x, name="mx.ord")        # must see the doubling
        x += 1.0                                   # must follow write-back
    assert np.allclose(x.asnumpy(), 2.0 ** 9 - 1.0), x.asnumpy()[:4]


@pytest.mark.smoke
def test_engine_ordering_broadcast_inplace(hvd_mx):
    x = fake_mxnet.nd.array(np.arange(16, dtype=np.float32))
    x *= 3.0
    hvd_mx.broadcast_(x, root_rank=0, name="mx.bc")
    x += 2.0
    assert np.allclose(x.asnumpy(), np.arange(16) * 3.0 + 2.0)


@pytest.mark.smoke
def test_out_of_place_does_not_mutate_input(hvd_mx):
    x = fake_mxnet.nd.array([5.0, 5.0])
    y = hvd_mx.allreduce(x, name="mx.oop")
    x += 1.0
    assert np.allclose(y.asnumpy(), [5.0, 5.0])
    assert np.allclose(x.asnumpy(), [6.0, 6.0])


@pytest.mark.smoke
def test_broadcast_parameters(hvd_mx):
    params = {"w": fake_mxnet.nd.ones((3,)), "b": fake_mxnet.nd.zeros((2,))}
    hvd_mx.broadcast_parameters(params, root_rank=0)
    assert np.allclose(params["w"].asnumpy(), 1.0)
    assert np.allclose(params["b"].asnumpy(), 0.0)
