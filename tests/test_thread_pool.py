"""ThreadPool (reference thread_pool.h role) + multi-thread finalizer."""

import threading
import time

from horovod_tpu.core.thread_pool import ThreadPool

from .helpers import run_distributed


def test_pool_executes_and_drains():
    pool = ThreadPool(3, name="t")
    done = []
    lock = threading.Lock()
    for i in range(20):
        def task(i=i):
            with lock:
                done.append(i)
        pool.execute(task)
    pool.shutdown(timeout=10)
    assert sorted(done) == list(range(20))


def test_pool_concurrency():
    pool = ThreadPool(4, name="c")
    gate = threading.Barrier(4, timeout=10)
    hits = []

    def task():
        gate.wait()  # only passes if 4 workers run simultaneously
        hits.append(1)

    for _ in range(4):
        pool.execute(task)
    pool.shutdown(timeout=15)
    assert len(hits) == 4


def test_pool_rejects_after_shutdown():
    import pytest

    pool = ThreadPool(1)
    pool.shutdown(timeout=5)
    with pytest.raises(RuntimeError):
        pool.execute(lambda: None)


def test_multi_finalizer_threads_end_to_end():
    """The XLA eager plane with a >1 finalizer pool completes async
    collectives correctly (HOROVOD_NUM_NCCL_STREAMS analog)."""
    out = run_distributed(1, """
import jax.numpy as jnp
import horovod_tpu.frameworks.jax.ops as ops

hs = [ops.allreduce_async(jnp.ones(64) * i, op=hvd.Sum, name=f"t{i}")
      for i in range(6)]
for i, h in enumerate(hs):
    o = ops.synchronize(h)
    assert float(o[0]) == float(i), (i, o[0])
print("POOLFIN_OK", rank, flush=True)
""", timeout=240, extra_env={"HOROVOD_NUM_FINALIZER_THREADS": "3"})
    assert "POOLFIN_OK 0" in out[0]
