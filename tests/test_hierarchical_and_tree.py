"""Hierarchical allreduce, tree broadcast, and narrow-wire low-precision
reduction (reference ``nccl_operations.cc:194-405`` two-level pattern,
``gloo::broadcast`` tree, ``half.cc`` narrow-wire fp16 sum)."""

import numpy as np
import pytest

from horovod_tpu.backend.cpu_ring import HierarchicalAllreduce
from horovod_tpu.common.topology import ProcessTopology

from .helpers import run_distributed


def _topo(rank, size, lr, ls, cr, cs):
    return ProcessTopology(rank=rank, size=size, local_rank=lr,
                           local_size=ls, cross_rank=cr, cross_size=cs)


def test_hierarchical_applicable():
    # 2 hosts x 2 slots, host-major: applicable
    assert HierarchicalAllreduce.applicable(_topo(3, 4, 1, 2, 1, 2))
    # single host: flat ring is the right tool
    assert not HierarchicalAllreduce.applicable(_topo(1, 4, 1, 4, 0, 1))
    # one slot per host: nothing to split locally
    assert not HierarchicalAllreduce.applicable(_topo(1, 4, 0, 1, 1, 4))


def test_hierarchical_applicable_env_off(monkeypatch):
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "0")
    assert not HierarchicalAllreduce.applicable(_topo(3, 4, 1, 2, 1, 2))


def test_hierarchical_allreduce_2x2():
    """4 ranks as 2 hosts x 2 slots: the two-level path must give exact
    sums (fp32) and rank-dependent values catch chunk-routing bugs."""
    out = run_distributed(4, """
x = np.arange(23, dtype=np.float32) * (rank + 1) + rank
o = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="h"))
exp = sum(np.arange(23, dtype=np.float32) * (r + 1) + r for r in range(4))
assert np.allclose(o, exp), (o[:4], exp[:4])
# a second, larger tensor re-uses the path (uneven chunk bounds)
y = np.ones(101, np.float32) * (rank + 1)
o2 = np.asarray(hvd.allreduce(y, op=hvd.Average, name="h2"))
assert np.allclose(o2, 2.5), o2[:4]
print("HIER_OK", rank, flush=True)
""", timeout=240, local_size=2)
    for r, o in enumerate(out):
        assert f"HIER_OK {r}" in o


@pytest.mark.parametrize("n", [3, 5])
def test_tree_broadcast_non_pow2(n):
    """Binomial tree must cover every rank for non-power-of-two sizes and
    non-zero roots."""
    out = run_distributed(n, f"""
root = {n - 1}
val = np.arange(7, dtype=np.float64) * 3.5 if rank == root else np.zeros(7)
o = np.asarray(hvd.broadcast(val, root_rank=root, name="tb"))
assert np.allclose(o, np.arange(7) * 3.5), o
print("TREE_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TREE_OK {r}" in o


def test_bf16_narrow_wire_allreduce():
    """bf16 stays bf16 on the wire; sums of small integers are exact in
    bf16 so the result must round-trip exactly."""
    out = run_distributed(2, """
import ml_dtypes
x = np.arange(16, dtype=ml_dtypes.bfloat16)
o = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="b"))
assert o.dtype == ml_dtypes.bfloat16, o.dtype
assert np.allclose(o.astype(np.float32), np.arange(16) * 2.0), o
print("BF16_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"BF16_OK {r}" in o

# ---------------------------------------------------------------------------
# Controller negotiation fan-out: binomial tree vs star
# (HOROVOD_CONTROLLER_TOPOLOGY; reference role: MPI gather/bcast are
# tree-structured internally, mpi_controller.cc:108-162)


@pytest.mark.smoke
def test_binomial_tree_shape():
    from horovod_tpu.core.controller import tree_children, tree_parent

    for size in (2, 3, 4, 5, 7, 8, 13, 64, 256):
        seen = {0}
        for rank in range(1, size):
            parent = tree_parent(rank)
            assert 0 <= parent < rank  # acyclic, rooted at 0
            assert rank in tree_children(parent, size), (rank, parent)
            seen.add(rank)
        # children lists are disjoint and cover every non-root rank
        all_children = [c for r in range(size)
                        for c in tree_children(r, size)]
        assert sorted(all_children) == sorted(seen - {0})
        # depth is O(log P): number of up-hops from any rank
        for rank in range(size):
            hops, r = 0, rank
            while r:
                r = tree_parent(r)
                hops += 1
            assert hops <= size.bit_length(), (size, rank, hops)


@pytest.mark.smoke
def test_gather_bundle_roundtrip():
    from horovod_tpu.core.controller import _decode_bundle, _encode_bundle

    entries = [(3, b"abc"), (1, b""), (7, bytes(range(256)))]
    assert _decode_bundle(_encode_bundle(entries)) == entries
    assert _decode_bundle(_encode_bundle([])) == []


@pytest.mark.parametrize("n", [4, 5])
def test_tree_controller_collectives_end_to_end(n):
    """Full eager collectives with the tree fan-out, at a power-of-2 and a
    ragged size: allreduce + broadcast + the cache fast path (steady-state
    cycles ride the mask round through relayed bundles)."""
    out = run_distributed(n, """
v = np.full(8, float(rank + 1), np.float32)
for step in range(12):   # enough cycles to enter the cache fast path
    s = hvd.allreduce(v, op=hvd.Sum, name="tree.sum")
    assert np.allclose(np.asarray(s), sum(range(1, size + 1))), s
b = hvd.broadcast(np.full(4, float(rank), np.float32), root_rank=2,
                  name="tree.bcast")
assert np.allclose(np.asarray(b), 2.0), b
print("TREE_OK", rank, flush=True)
""", timeout=240, extra_env={"HOROVOD_CONTROLLER_TOPOLOGY": "tree"})
    for r, o in enumerate(out):
        assert f"TREE_OK {r}" in o
