"""Hierarchical allreduce, tree broadcast, and narrow-wire low-precision
reduction (reference ``nccl_operations.cc:194-405`` two-level pattern,
``gloo::broadcast`` tree, ``half.cc`` narrow-wire fp16 sum)."""

import numpy as np
import pytest

from horovod_tpu.backend.cpu_ring import HierarchicalAllreduce
from horovod_tpu.common.topology import ProcessTopology

from .helpers import run_distributed


def _topo(rank, size, lr, ls, cr, cs):
    return ProcessTopology(rank=rank, size=size, local_rank=lr,
                           local_size=ls, cross_rank=cr, cross_size=cs)


def test_hierarchical_applicable():
    # 2 hosts x 2 slots, host-major: applicable
    assert HierarchicalAllreduce.applicable(_topo(3, 4, 1, 2, 1, 2))
    # single host: flat ring is the right tool
    assert not HierarchicalAllreduce.applicable(_topo(1, 4, 1, 4, 0, 1))
    # one slot per host: nothing to split locally
    assert not HierarchicalAllreduce.applicable(_topo(1, 4, 0, 1, 1, 4))


def test_hierarchical_applicable_env_off(monkeypatch):
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "0")
    assert not HierarchicalAllreduce.applicable(_topo(3, 4, 1, 2, 1, 2))


def test_hierarchical_allreduce_2x2():
    """4 ranks as 2 hosts x 2 slots: the two-level path must give exact
    sums (fp32) and rank-dependent values catch chunk-routing bugs."""
    out = run_distributed(4, """
x = np.arange(23, dtype=np.float32) * (rank + 1) + rank
o = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="h"))
exp = sum(np.arange(23, dtype=np.float32) * (r + 1) + r for r in range(4))
assert np.allclose(o, exp), (o[:4], exp[:4])
# a second, larger tensor re-uses the path (uneven chunk bounds)
y = np.ones(101, np.float32) * (rank + 1)
o2 = np.asarray(hvd.allreduce(y, op=hvd.Average, name="h2"))
assert np.allclose(o2, 2.5), o2[:4]
print("HIER_OK", rank, flush=True)
""", timeout=240, local_size=2)
    for r, o in enumerate(out):
        assert f"HIER_OK {r}" in o


@pytest.mark.parametrize("n", [3, 5])
def test_tree_broadcast_non_pow2(n):
    """Binomial tree must cover every rank for non-power-of-two sizes and
    non-zero roots."""
    out = run_distributed(n, f"""
root = {n - 1}
val = np.arange(7, dtype=np.float64) * 3.5 if rank == root else np.zeros(7)
o = np.asarray(hvd.broadcast(val, root_rank=root, name="tb"))
assert np.allclose(o, np.arange(7) * 3.5), o
print("TREE_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"TREE_OK {r}" in o


def test_bf16_narrow_wire_allreduce():
    """bf16 stays bf16 on the wire; sums of small integers are exact in
    bf16 so the result must round-trip exactly."""
    out = run_distributed(2, """
import ml_dtypes
x = np.arange(16, dtype=ml_dtypes.bfloat16)
o = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="b"))
assert o.dtype == ml_dtypes.bfloat16, o.dtype
assert np.allclose(o.astype(np.float32), np.arange(16) * 2.0), o
print("BF16_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"BF16_OK {r}" in o
