"""Observability plane: metrics registry + /metrics scrape, cross-rank
merged timeline, flight-recorder post-mortems (docs/observability.md).

Fast unit tiers first (registry semantics, Prometheus rendering, flight
ring, trace alignment, stall-inspector surfacing, runtime timeline
toggles); the np=2 end-to-end proofs — a live ``GET /metrics`` scrape
with cross-rank latency histograms, and a merged two-rank trace where
both ranks' lanes share a cycle id — are chaos-marked so they sort after
the fast tiers (tier-1 budget rule: heavy multiprocess jobs run late).
"""

from __future__ import annotations

import json
import time
from collections import Counter

import numpy as np
import pytest

from horovod_tpu.core import flight_recorder, metrics

from .helpers import run_distributed


@pytest.fixture(autouse=True)
def _clean_registry():
    """Registry/ring state must not leak between tests."""
    metrics.registry.reset()
    flight_recorder.recorder.clear()
    yield
    metrics.configure(None)
    metrics.registry.reset()
    flight_recorder.recorder.clear()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestRegistry:
    def test_counter_accumulates(self):
        metrics.inc("faults_injected_total")
        metrics.inc("faults_injected_total", 2)
        assert metrics.registry.get_counter("faults_injected_total") == 3

    def test_gauge_overwrites(self):
        metrics.set_gauge("tensor_queue_depth", 5)
        metrics.set_gauge("tensor_queue_depth", 2)
        assert metrics.registry.get_gauge("tensor_queue_depth") == 2

    def test_labels_partition_series(self):
        metrics.inc("rendezvous_store_ops_total", op="get")
        metrics.inc("rendezvous_store_ops_total", op="get")
        metrics.inc("rendezvous_store_ops_total", op="set")
        assert metrics.registry.get_counter(
            "rendezvous_store_ops_total", op="get") == 2
        assert metrics.registry.get_counter(
            "rendezvous_store_ops_total", op="set") == 1

    def test_histogram_buckets_and_sum(self):
        for v in (1e-5, 1e-5, 0.5, 1e9):  # last lands in overflow
            metrics.observe("controller_cycle_seconds", v)
        snap = metrics.registry.snapshot()
        h = snap["histograms"]["controller_cycle_seconds"]
        assert h["count"] == 4
        assert h["sum"] == pytest.approx(1e9 + 0.5 + 2e-5)
        assert len(h["counts"]) == len(metrics.BUCKET_BOUNDS) + 1
        assert sum(h["counts"]) == 4
        assert h["counts"][-1] == 1  # the +Inf overflow observation

    def test_disabled_is_a_noop(self):
        metrics.configure(False)
        try:
            metrics.inc("faults_injected_total")
            metrics.observe("controller_cycle_seconds", 1.0)
            metrics.set_gauge("tensor_queue_depth", 9)
        finally:
            metrics.configure(True)
        snap = metrics.registry.snapshot()
        assert "faults_injected_total" not in snap["counters"]
        assert "tensor_queue_depth" not in snap["gauges"]
        assert "controller_cycle_seconds" not in snap["histograms"]

    def test_flat_roundtrip(self):
        flat = metrics.flat("x_total", op="GET", rank="3")
        assert flat == 'x_total{op="GET",rank="3"}'
        base, labels = metrics.parse_flat(flat)
        assert base == "x_total" and labels == {"op": "GET", "rank": "3"}
        assert metrics.parse_flat("plain") == ("plain", {})

    def test_flat_rejects_quotes_in_values(self):
        with pytest.raises(ValueError):
            metrics.flat("x", op='a"b')

    def test_size_bucket_label(self):
        assert metrics.size_bucket_label(1) == "2^0"
        assert metrics.size_bucket_label(1024) == "2^10"
        assert metrics.size_bucket_label(1025) == "2^11"
        assert metrics.size_bucket_label(4 << 20) == "2^22"

    def test_views_fold_into_snapshot_and_replace(self):
        metrics.registry.register_view(
            "t", lambda: {"counters": {"phase_ops_total": 7}})
        assert metrics.registry.snapshot()["counters"][
            "phase_ops_total"] == 7
        metrics.registry.register_view(
            "t", lambda: {"counters": {"phase_ops_total": 9}})
        assert metrics.registry.snapshot()["counters"][
            "phase_ops_total"] == 9

    def test_broken_view_does_not_break_snapshot(self):
        def bad():
            raise RuntimeError("boom")

        metrics.registry.register_view("bad", bad)
        metrics.inc("faults_injected_total")
        assert metrics.registry.snapshot()["counters"][
            "faults_injected_total"] == 1

    def test_wire_and_phase_stats_are_registered_views(self):
        from horovod_tpu.core.timeline import phase_stats, wire_stats

        wire_stats.add("bytes_on_wire", 128)
        phase_stats.add("negotiate", 0.25)
        snap = metrics.registry.snapshot()
        assert snap["counters"]["wire_bytes_on_wire_total"] >= 128
        key = metrics.flat("phase_seconds_total", phase="negotiate")
        assert snap["counters"][key] >= 0.25

    def test_catalog_covers_every_stat_literal(self):
        # The names the codebase feeds to phase_stats/wire_stats.add —
        # HVD007's contract, restated where a registry edit breaks it.
        for name in ("negotiate", "fuse", "collective", "unfuse", "wait",
                     "bytes_on_wire", "heap_copies"):
            assert name in metrics.CATALOG


# ---------------------------------------------------------------------------
# Prometheus rendering / cross-rank merge
# ---------------------------------------------------------------------------


def _snap(rank, counters=None, gauges=None, histograms=None):
    return {"version": 1, "rank": rank, "ts_unix_ns": 0,
            "bucket_bounds": list(metrics.BUCKET_BOUNDS),
            "counters": counters or {}, "gauges": gauges or {},
            "histograms": histograms or {}}


@pytest.mark.smoke
class TestPrometheusRender:
    def test_counters_sum_across_ranks(self):
        text = metrics.render_prometheus({
            0: _snap(0, counters={"aborts_total": 2}),
            1: _snap(1, counters={"aborts_total": 3})})
        assert "hvd_aborts_total 5" in text
        assert "# TYPE hvd_aborts_total counter" in text

    def test_gauges_labeled_by_rank(self):
        text = metrics.render_prometheus({
            0: _snap(0, gauges={"tensor_queue_depth": 1}),
            1: _snap(1, gauges={"tensor_queue_depth": 4})})
        assert 'hvd_tensor_queue_depth{rank="0"} 1' in text
        assert 'hvd_tensor_queue_depth{rank="1"} 4' in text

    def test_histograms_merge_cumulatively(self):
        counts = [0] * (len(metrics.BUCKET_BOUNDS) + 1)
        counts[0] = 1
        h0 = {"collective_latency_seconds": {
            "counts": list(counts), "sum": 0.5, "count": 1}}
        counts2 = list(counts)
        counts2[-1] = 2  # overflow bucket on rank 1
        h1 = {"collective_latency_seconds": {
            "counts": counts2, "sum": 1.5, "count": 3}}
        text = metrics.render_prometheus({0: _snap(0, histograms=h0),
                                          1: _snap(1, histograms=h1)})
        assert 'hvd_collective_latency_seconds_bucket{le="+Inf"} 4' in text
        assert "hvd_collective_latency_seconds_sum 2" in text
        assert "hvd_collective_latency_seconds_count 4" in text
        # cumulative: every bucket line's value is non-decreasing
        vals = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith("hvd_collective_latency_seconds_bucket")]
        assert vals == sorted(vals)

    def test_malformed_snapshot_is_skipped(self):
        text = metrics.render_prometheus({
            0: _snap(0, counters={"aborts_total": 1}), 1: "garbage"})
        assert "hvd_aborts_total 1" in text


@pytest.mark.smoke
def test_scrape_serves_only_newest_epoch():
    """Elastic staleness gate: a departed rank's last snapshot (stamped
    with the old epoch) must drop out of the scrape once survivors push
    under the new epoch."""
    import urllib.request

    from horovod_tpu.runner.rendezvous import RendezvousServer

    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        old = _snap(3, gauges={"tensor_queue_depth": 9})
        old["epoch"] = 0
        new = _snap(0, gauges={"tensor_queue_depth": 1})
        new["epoch"] = 1
        server.set(metrics.METRICS_SCOPE, "rank-3",
                   json.dumps(old).encode())
        server.set(metrics.METRICS_SCOPE, "rank-0",
                   json.dumps(new).encode())
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert 'hvd_tensor_queue_depth{rank="0"} 1' in text
        assert 'rank="3"' not in text, text
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestFlightRecorder:
    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_EVENTS", "8")
        rec = flight_recorder.FlightRecorder()
        for i in range(50):
            rec.record("frame", n=i)
        events = rec.events()
        assert len(events) == 8
        assert [e["n"] for e in events] == list(range(42, 50))

    def test_dump_is_parseable_and_complete(self, tmp_path):
        flight_recorder.record("cycle", n=3)
        flight_recorder.record("fault", site="tcp.send")
        metrics.inc("faults_injected_total")
        path = flight_recorder.recorder.dump(
            "unit test", path=str(tmp_path / "dump.json"))
        doc = json.loads((tmp_path / "dump.json").read_text())
        assert path == str(tmp_path / "dump.json")
        assert doc["format"] == flight_recorder.DUMP_FORMAT
        assert doc["reason"] == "unit test"
        assert {e["kind"] for e in doc["events"]} == {"cycle", "fault"}
        assert doc["metrics"]["counters"]["faults_injected_total"] == 1
        for e in doc["events"]:
            assert "t_mono" in e and "t_wall" in e and "thread" in e

    def test_dump_dir_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_DIR", str(tmp_path))
        monkeypatch.setenv("HOROVOD_RANK", "7")
        flight_recorder.record("cycle", n=1)
        path = flight_recorder.recorder.dump("dir knob")
        assert path == str(tmp_path / "hvd_flight_recorder.rank7.json")
        assert json.loads(open(path).read())["rank"] == 7

    def test_disabled_records_and_dumps_nothing(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "0")
        rec = flight_recorder.FlightRecorder()
        rec.record("frame")
        assert rec.events() == []
        assert rec.dump("off", path=str(tmp_path / "no.json")) is None
        assert not (tmp_path / "no.json").exists()

    def test_dump_never_raises_on_bad_path(self):
        assert flight_recorder.recorder.dump(
            "bad", path="/nonexistent-dir-xyz/d.json") is None


# ---------------------------------------------------------------------------
# stall inspector -> metrics surfacing
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestStallMetrics:
    def _controller(self, warn=0.01, shut=0.0):
        from horovod_tpu.common.topology import ProcessTopology
        from horovod_tpu.core.controller import Controller

        topo = ProcessTopology(rank=0, size=2, local_rank=0, local_size=2)
        c = Controller(topo, mesh=None, stall_warning_secs=warn,
                       stall_shutdown_secs=shut)
        c._last_stall_check = 0.0  # force the next check to run
        return c

    def _stall_tensor(self, c, name="stuck", age=10.0):
        from horovod_tpu.core.controller import _TableEntry

        entry = _TableEntry()
        entry.ranks.add(0)
        entry.first_seen = time.monotonic() - age
        c._message_table[name] = entry

    def test_stalled_gauge_counts_overdue_tensors(self):
        c = self._controller(warn=0.01)
        self._stall_tensor(c, "stuck", age=10.0)
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 1
        # recovery: the next check with an empty table zeroes the gauge
        c._message_table.clear()
        c._last_stall_check = 0.0
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 0

    def test_fresh_tensor_not_counted(self):
        c = self._controller(warn=60.0)
        self._stall_tensor(c, "young", age=0.001)
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 0

    def test_stall_shutdown_increments_counter(self):
        from horovod_tpu.common.exceptions import HorovodInternalError

        c = self._controller(warn=0.0, shut=0.01)
        self._stall_tensor(c, "doomed", age=10.0)
        with pytest.raises(HorovodInternalError, match="stall shutdown"):
            c._check_stalls()
        assert metrics.registry.get_counter("stall_shutdowns_total") == 1


# ---------------------------------------------------------------------------
# trace merge
# ---------------------------------------------------------------------------


def _trace(rank, wall_base_ns, server_offset_ns, events):
    head = [
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"rank {rank}"}},
        {"name": "clock_sync", "ph": "M", "pid": rank,
         "args": {"wall_base_ns": wall_base_ns,
                  "server_offset_ns": server_offset_ns, "rank": rank}},
    ]
    return head + events


@pytest.mark.smoke
class TestTraceMerge:
    def test_clock_alignment_subtracts_skew(self):
        from horovod_tpu.tools import trace_merge

        # Rank 1's wall clock runs 5 ms ahead of rank 0's, and its
        # server-offset estimate says exactly that: after alignment, two
        # spans that happened at the same server time coincide.
        t0 = _trace(0, 1_000_000_000, 0,
                    [{"name": "A", "ph": "B", "pid": 0, "tid": 1, "ts": 100}])
        t1 = _trace(1, 1_000_000_000 + 5_000_000, 5_000_000,
                    [{"name": "A", "ph": "B", "pid": 1, "tid": 1, "ts": 100}])
        merged = trace_merge.merge([json.loads(json.dumps(t)) for t in (t0, t1)])
        ts = [e["ts"] for e in merged if e.get("ph") == "B"]
        assert ts[0] == pytest.approx(ts[1])

    def test_missing_clock_sync_falls_back_to_concat(self):
        from horovod_tpu.tools import trace_merge

        warnings = []
        t0 = _trace(0, 1_000, 0,
                    [{"name": "A", "ph": "B", "pid": 0, "tid": 1, "ts": 7}])
        t1 = [{"name": "A", "ph": "B", "pid": 1, "tid": 1, "ts": 9}]
        merged = trace_merge.merge([t0, t1], warn=warnings.append)
        assert warnings and "WITHOUT" in warnings[0]
        assert sorted(e["ts"] for e in merged if "ts" in e) == [7, 9]

    def test_truncated_trace_is_repaired(self, tmp_path):
        from horovod_tpu.tools import trace_merge

        p = tmp_path / "trunc.json"
        p.write_text('[\n{"name": "A", "ph": "B", "pid": 0, "ts": 1},\n'
                     '{"name": "B", "ph": "E", "pid": 0, "ts":')  # cut mid-record
        events = trace_merge.load_trace(str(p))
        assert [e["name"] for e in events] == ["A"]

    def test_cli_writes_merged_file(self, tmp_path):
        from horovod_tpu.tools import trace_merge

        for r in range(2):
            (tmp_path / f"t{r}.json").write_text(json.dumps(
                _trace(r, 1_000_000, 0,
                       [{"name": "X", "ph": "B", "pid": r, "tid": 1,
                         "ts": 5, "args": {"cycle": 3}}])))
        out = tmp_path / "merged.json"
        rc = trace_merge.main([str(tmp_path / "t0.json"),
                               str(tmp_path / "t1.json"), "-o", str(out)])
        assert rc == 0
        merged = json.loads(out.read_text())
        assert {e.get("pid") for e in merged if e.get("ph") == "B"} == {0, 1}


# ---------------------------------------------------------------------------
# runtime timeline toggles (satellite: the core/timeline.py docstring's
# promise, with balanced B/E per lane)
# ---------------------------------------------------------------------------


def test_start_stop_timeline_balanced_lanes(tmp_path):
    import os

    from horovod_tpu.core import state as state_mod

    state_mod.reset_global_state()
    os.environ.pop("HOROVOD_SIZE", None)
    import horovod_tpu.frameworks.jax.basics as basics
    import horovod_tpu.frameworks.jax.ops as ops

    basics.init()
    try:
        tl = tmp_path / "toggle.json"
        basics.start_timeline(str(tl), mark_cycles=True)
        for i in range(3):
            ops.allreduce(np.ones(8, np.float32), name=f"tg{i}")
        basics.stop_timeline()
        events = json.loads(tl.read_text())  # completed file parses
        assert state_mod.global_state().timeline is None
        # every lane's B (begin) events are balanced by E (end) events
        per_lane = Counter()
        for e in events:
            if e.get("ph") in ("B", "E"):
                per_lane[(e.get("pid"), e.get("tid"), e["ph"])] += 1
        lanes = {(p, t) for (p, t, _ph) in per_lane}
        assert lanes, "no span events recorded"
        for p, t in lanes:
            assert per_lane[(p, t, "B")] == per_lane[(p, t, "E")], \
                (p, t, per_lane)
        # spans are cycle-tagged and the clock_sync anchor is present
        assert any(e.get("args", {}).get("cycle") for e in events
                   if e.get("ph") == "B")
        assert any(e.get("name") == "clock_sync" for e in events)
        # a second start after stop works (toggle, not one-shot)
        tl2 = tmp_path / "toggle2.json"
        basics.start_timeline(str(tl2))
        ops.allreduce(np.ones(8, np.float32), name="tg_again")
        basics.stop_timeline()
        assert any(e.get("ph") == "B" for e in json.loads(tl2.read_text()))
    finally:
        state_mod.global_state().shutdown()
        state_mod.reset_global_state()


# ---------------------------------------------------------------------------
# np=2 end-to-end proofs (chaos-marked: multiprocess jobs sort last)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.timeout(240)
def test_metrics_scrape_e2e_np2():
    """Acceptance proof (a): a live np=2 job's ``GET /metrics`` serves
    Prometheus text with cross-rank collective latency histograms and
    per-rank gauges."""
    body = """
import time, urllib.request
for i in range(6):
    hvd.allreduce(np.ones(1024, np.float32), name=f"m{i % 2}")
hvd.barrier()
time.sleep(1.2)
hvd.barrier()
if rank == 0:
    addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
    port = os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]
    deadline = time.time() + 30
    text = ""
    while time.time() < deadline:
        text = urllib.request.urlopen(
            f"http://{addr}:{port}/metrics", timeout=5).read().decode()
        if ('hvd_collective_latency_seconds_bucket' in text
                and 'rank="1"' in text):
            break
        time.sleep(0.3)
    assert 'hvd_collective_latency_seconds_bucket' in text, text[:3000]
    assert 'op="ALLREDUCE"' in text, text[:3000]
    assert 'dtype="FLOAT32"' in text, text[:3000]
    assert 'rank="0"' in text and 'rank="1"' in text, text[:3000]
    assert 'hvd_wire_bytes_on_wire_total' in text, text[:3000]
    assert '# TYPE hvd_collective_latency_seconds histogram' in text
    print("SCRAPE_OK", flush=True)
"""
    outs = run_distributed(
        2, body, timeout=180,
        extra_env={"HOROVOD_METRICS_PUSH_SECS": "0.2"})
    assert "SCRAPE_OK" in outs[0], outs[0]


@pytest.mark.chaos
@pytest.mark.timeout(240)
def test_trace_merge_e2e_np2(tmp_path):
    """Acceptance proof (b): per-rank traces from a real np=2 job merge
    into one Chrome trace where both ranks' lanes for the same collective
    share a negotiation cycle id."""
    from horovod_tpu.tools import trace_merge

    tl = tmp_path / "tl.json"
    run_distributed(2, """
for i in range(4):
    hvd.allreduce(np.ones(64, np.float32), name="tm0")
""", timeout=180, extra_env={"HOROVOD_TIMELINE": str(tl)})
    merged_path = tmp_path / "merged.json"
    rc = trace_merge.main([str(tl), f"{tl}.rank1", "-o", str(merged_path)])
    assert rc == 0
    events = json.loads(merged_path.read_text())
    lane_names = {
        (e["pid"], e["tid"]): e["args"]["name"] for e in events
        if e.get("name") == "thread_name" and e.get("ph") == "M"}
    cycles = {0: [], 1: []}
    for e in events:
        if e.get("ph") == "B" and e.get("name") == "ALLREDUCE" \
                and lane_names.get((e["pid"], e["tid"])) == "tm0":
            cycles[e["pid"]].append(e["args"]["cycle"])
    assert cycles[0], "rank 0 recorded no ALLREDUCE spans"
    assert cycles[1], "rank 1 recorded no ALLREDUCE spans"
    assert sorted(cycles[0]) == sorted(cycles[1]), \
        "ranks disagree on the cycle ids of the same collectives"
