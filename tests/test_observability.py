"""Observability plane: metrics registry + /metrics scrape, cross-rank
merged timeline, flight-recorder post-mortems (docs/observability.md).

Fast unit tiers first (registry semantics, Prometheus rendering, flight
ring, trace alignment, stall-inspector surfacing, runtime timeline
toggles); the np=2 end-to-end proofs — a live ``GET /metrics`` scrape
with cross-rank latency histograms, and a merged two-rank trace where
both ranks' lanes share a cycle id — are chaos-marked so they sort after
the fast tiers (tier-1 budget rule: heavy multiprocess jobs run late).
"""

from __future__ import annotations

import json
import time
from collections import Counter

import numpy as np
import pytest

from horovod_tpu.core import flight_recorder, metrics

from .helpers import run_distributed


@pytest.fixture(autouse=True)
def _clean_registry():
    """Registry/ring state must not leak between tests."""
    metrics.registry.reset()
    flight_recorder.recorder.clear()
    yield
    metrics.configure(None)
    metrics.registry.reset()
    flight_recorder.recorder.clear()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestRegistry:
    def test_counter_accumulates(self):
        metrics.inc("faults_injected_total")
        metrics.inc("faults_injected_total", 2)
        assert metrics.registry.get_counter("faults_injected_total") == 3

    def test_gauge_overwrites(self):
        metrics.set_gauge("tensor_queue_depth", 5)
        metrics.set_gauge("tensor_queue_depth", 2)
        assert metrics.registry.get_gauge("tensor_queue_depth") == 2

    def test_labels_partition_series(self):
        metrics.inc("rendezvous_store_ops_total", op="get")
        metrics.inc("rendezvous_store_ops_total", op="get")
        metrics.inc("rendezvous_store_ops_total", op="set")
        assert metrics.registry.get_counter(
            "rendezvous_store_ops_total", op="get") == 2
        assert metrics.registry.get_counter(
            "rendezvous_store_ops_total", op="set") == 1

    def test_histogram_buckets_and_sum(self):
        for v in (1e-5, 1e-5, 0.5, 1e9):  # last lands in overflow
            metrics.observe("controller_cycle_seconds", v)
        snap = metrics.registry.snapshot()
        h = snap["histograms"]["controller_cycle_seconds"]
        assert h["count"] == 4
        assert h["sum"] == pytest.approx(1e9 + 0.5 + 2e-5)
        assert len(h["counts"]) == len(metrics.BUCKET_BOUNDS) + 1
        assert sum(h["counts"]) == 4
        assert h["counts"][-1] == 1  # the +Inf overflow observation

    def test_disabled_is_a_noop(self):
        metrics.configure(False)
        try:
            metrics.inc("faults_injected_total")
            metrics.observe("controller_cycle_seconds", 1.0)
            metrics.set_gauge("tensor_queue_depth", 9)
        finally:
            metrics.configure(True)
        snap = metrics.registry.snapshot()
        assert "faults_injected_total" not in snap["counters"]
        assert "tensor_queue_depth" not in snap["gauges"]
        assert "controller_cycle_seconds" not in snap["histograms"]

    def test_flat_roundtrip(self):
        flat = metrics.flat("x_total", op="GET", rank="3")
        assert flat == 'x_total{op="GET",rank="3"}'
        base, labels = metrics.parse_flat(flat)
        assert base == "x_total" and labels == {"op": "GET", "rank": "3"}
        assert metrics.parse_flat("plain") == ("plain", {})

    def test_flat_rejects_quotes_in_values(self):
        with pytest.raises(ValueError):
            metrics.flat("x", op='a"b')

    def test_size_bucket_label(self):
        assert metrics.size_bucket_label(1) == "2^0"
        assert metrics.size_bucket_label(1024) == "2^10"
        assert metrics.size_bucket_label(1025) == "2^11"
        assert metrics.size_bucket_label(4 << 20) == "2^22"

    def test_views_fold_into_snapshot_and_replace(self):
        metrics.registry.register_view(
            "t", lambda: {"counters": {"phase_ops_total": 7}})
        assert metrics.registry.snapshot()["counters"][
            "phase_ops_total"] == 7
        metrics.registry.register_view(
            "t", lambda: {"counters": {"phase_ops_total": 9}})
        assert metrics.registry.snapshot()["counters"][
            "phase_ops_total"] == 9

    def test_broken_view_does_not_break_snapshot(self):
        def bad():
            raise RuntimeError("boom")

        metrics.registry.register_view("bad", bad)
        metrics.inc("faults_injected_total")
        assert metrics.registry.snapshot()["counters"][
            "faults_injected_total"] == 1

    def test_wire_and_phase_stats_are_registered_views(self):
        from horovod_tpu.core.timeline import phase_stats, wire_stats

        wire_stats.add("bytes_on_wire", 128)
        phase_stats.add("negotiate", 0.25)
        snap = metrics.registry.snapshot()
        assert snap["counters"]["wire_bytes_on_wire_total"] >= 128
        key = metrics.flat("phase_seconds_total", phase="negotiate")
        assert snap["counters"][key] >= 0.25

    def test_catalog_covers_every_stat_literal(self):
        # The names the codebase feeds to phase_stats/wire_stats.add —
        # HVD007's contract, restated where a registry edit breaks it.
        for name in ("negotiate", "fuse", "collective", "unfuse", "wait",
                     "bytes_on_wire", "heap_copies"):
            assert name in metrics.CATALOG


# ---------------------------------------------------------------------------
# Prometheus rendering / cross-rank merge
# ---------------------------------------------------------------------------


def _snap(rank, counters=None, gauges=None, histograms=None):
    return {"version": 1, "rank": rank, "ts_unix_ns": 0,
            "bucket_bounds": list(metrics.BUCKET_BOUNDS),
            "counters": counters or {}, "gauges": gauges or {},
            "histograms": histograms or {}}


@pytest.mark.smoke
class TestPrometheusRender:
    def test_counters_sum_across_ranks(self):
        text = metrics.render_prometheus({
            0: _snap(0, counters={"aborts_total": 2}),
            1: _snap(1, counters={"aborts_total": 3})})
        assert "hvd_aborts_total 5" in text
        assert "# TYPE hvd_aborts_total counter" in text

    def test_gauges_labeled_by_rank(self):
        text = metrics.render_prometheus({
            0: _snap(0, gauges={"tensor_queue_depth": 1}),
            1: _snap(1, gauges={"tensor_queue_depth": 4})})
        assert 'hvd_tensor_queue_depth{rank="0"} 1' in text
        assert 'hvd_tensor_queue_depth{rank="1"} 4' in text

    def test_histograms_merge_cumulatively(self):
        counts = [0] * (len(metrics.BUCKET_BOUNDS) + 1)
        counts[0] = 1
        h0 = {"collective_latency_seconds": {
            "counts": list(counts), "sum": 0.5, "count": 1}}
        counts2 = list(counts)
        counts2[-1] = 2  # overflow bucket on rank 1
        h1 = {"collective_latency_seconds": {
            "counts": counts2, "sum": 1.5, "count": 3}}
        text = metrics.render_prometheus({0: _snap(0, histograms=h0),
                                          1: _snap(1, histograms=h1)})
        assert 'hvd_collective_latency_seconds_bucket{le="+Inf"} 4' in text
        assert "hvd_collective_latency_seconds_sum 2" in text
        assert "hvd_collective_latency_seconds_count 4" in text
        # cumulative: every bucket line's value is non-decreasing
        vals = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith("hvd_collective_latency_seconds_bucket")]
        assert vals == sorted(vals)

    def test_three_rank_histogram_merge_is_exact(self):
        """Scrape-time merge exactness at np=3: the rendered cumulative
        distribution must equal the element-wise sum of the three ranks'
        bucket arrays — no drops, no double counts, any rank count."""
        n = len(metrics.BUCKET_BOUNDS) + 1
        per_rank = []
        for r in range(3):
            counts = [0] * n
            counts[r] = r + 1          # distinct bucket per rank
            counts[-1] = r             # plus overflow traffic on ranks 1-2
            per_rank.append(counts)
        snaps = {r: _snap(r, histograms={"collective_latency_seconds": {
            "counts": c, "sum": float(r), "count": sum(c)}})
            for r, c in enumerate(per_rank)}
        text = metrics.render_prometheus(snaps)
        merged = [sum(c[i] for c in per_rank) for i in range(n)]
        cumulative, acc = [], 0
        for v in merged:
            acc += v
            cumulative.append(acc)
        got = [float(line.rsplit(" ", 1)[1]) for line in text.splitlines()
               if line.startswith("hvd_collective_latency_seconds_bucket")]
        assert got == cumulative
        assert f"hvd_collective_latency_seconds_count {acc}" in text
        assert "hvd_collective_latency_seconds_sum 3" in text

    def test_malformed_snapshot_is_skipped(self):
        text = metrics.render_prometheus({
            0: _snap(0, counters={"aborts_total": 1}), 1: "garbage"})
        assert "hvd_aborts_total 1" in text


@pytest.mark.smoke
def test_scrape_serves_only_newest_epoch():
    """Elastic staleness gate: a departed rank's last snapshot (stamped
    with the old epoch) must drop out of the scrape once survivors push
    under the new epoch."""
    import urllib.request

    from horovod_tpu.runner.rendezvous import RendezvousServer

    server = RendezvousServer(bind_addr="127.0.0.1")
    port = server.start()
    try:
        old = _snap(3, gauges={"tensor_queue_depth": 9})
        old["epoch"] = 0
        new = _snap(0, gauges={"tensor_queue_depth": 1})
        new["epoch"] = 1
        server.set(metrics.METRICS_SCOPE, "rank-3",
                   json.dumps(old).encode())
        server.set(metrics.METRICS_SCOPE, "rank-0",
                   json.dumps(new).encode())
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert 'hvd_tensor_queue_depth{rank="0"} 1' in text
        assert 'rank="3"' not in text, text
    finally:
        server.stop()


@pytest.mark.smoke
def test_server_request_metrics_and_scrape_fold_in():
    """Control-plane attribution, server side: every HTTP op lands in the
    per-op latency histogram and per-scope counters, and ``GET /metrics``
    folds the server's own registry into the scrape under rank="server"
    (never epoch-gated — the server can't be stale about itself)."""
    import urllib.request

    from horovod_tpu.runner.rendezvous import RendezvousServer
    from horovod_tpu.transport.store import HTTPStoreClient

    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        reg = metrics.registry
        puts0 = reg.get_counter("rendezvous_scope_ops_total",
                                op="put", scope="obs-smoke")
        client = HTTPStoreClient("127.0.0.1", port)
        client.set("obs-smoke", "k", b"v")
        client.get("obs-smoke", "k")
        client.keys("obs-smoke")
        assert reg.get_counter("rendezvous_scope_ops_total",
                               op="put", scope="obs-smoke") == puts0 + 1
        assert reg.get_counter("rendezvous_scope_ops_total",
                               op="keys", scope="obs-smoke") >= 1
        hists = reg.snapshot()["histograms"]
        for op in ("put", "get", "keys"):
            key = metrics.flat("rendezvous_request_seconds", op=op)
            assert hists.get(key, {}).get("count", 0) >= 1, (key, op)
        # the in-flight gauge settled back to 0 after the burst
        assert reg.get_gauge("rendezvous_requests_in_flight") == 0
        # store-lock wait is observed on every guarded acquire
        lock_key = metrics.flat("rendezvous_store_lock_wait_seconds")
        assert hists.get(lock_key, {}).get("count", 0) >= 1
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert 'rank="server"' in text, text[:2000]
        assert "hvd_rendezvous_request_seconds" in text
    finally:
        server.stop()


@pytest.mark.smoke
def test_journal_metrics(tmp_path):
    """Durability-plane attribution: appends/fsyncs/replay/compaction all
    observe, torn tails count, and the generation gauge tracks."""
    from horovod_tpu.transport.store import DurableMemoryStore

    def hist_count(name):
        h = metrics.registry.snapshot()["histograms"]
        return h.get(metrics.flat(name), {}).get("count", 0)

    appends0 = hist_count("journal_append_seconds")
    fsyncs0 = hist_count("journal_fsync_seconds")
    store = DurableMemoryStore(str(tmp_path))
    store.set("s", "k", b"v")
    store.pop("s", "k")
    store.close()
    assert hist_count("journal_append_seconds") == appends0 + 2
    assert hist_count("journal_fsync_seconds") >= fsyncs0 + 2
    assert metrics.registry.get_gauge("journal_generation") == 0

    # A recover replays (and times) the journal; garbage appended after
    # the valid prefix is a torn tail and must increment the counter.
    replays0 = hist_count("journal_replay_seconds")
    torn0 = metrics.registry.get_counter("journal_truncated_tails_total")
    jpath = tmp_path / "journal-00000000"
    with open(jpath, "ab") as f:
        f.write(b"\x01torn-garbage")
    store2 = DurableMemoryStore(str(tmp_path))
    store2.close()
    assert hist_count("journal_replay_seconds") == replays0 + 1
    assert metrics.registry.get_counter(
        "journal_truncated_tails_total") == torn0 + 1


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestFlightRecorder:
    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_EVENTS", "8")
        rec = flight_recorder.FlightRecorder()
        for i in range(50):
            rec.record("frame", n=i)
        events = rec.events()
        assert len(events) == 8
        assert [e["n"] for e in events] == list(range(42, 50))

    def test_dump_is_parseable_and_complete(self, tmp_path):
        flight_recorder.record("cycle", n=3)
        flight_recorder.record("fault", site="tcp.send")
        metrics.inc("faults_injected_total")
        path = flight_recorder.recorder.dump(
            "unit test", path=str(tmp_path / "dump.json"))
        doc = json.loads((tmp_path / "dump.json").read_text())
        assert path == str(tmp_path / "dump.json")
        assert doc["format"] == flight_recorder.DUMP_FORMAT
        assert doc["reason"] == "unit test"
        assert {e["kind"] for e in doc["events"]} == {"cycle", "fault"}
        assert doc["metrics"]["counters"]["faults_injected_total"] == 1
        for e in doc["events"]:
            assert "t_mono" in e and "t_wall" in e and "thread" in e

    def test_dump_dir_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_DIR", str(tmp_path))
        monkeypatch.setenv("HOROVOD_RANK", "7")
        flight_recorder.record("cycle", n=1)
        path = flight_recorder.recorder.dump("dir knob")
        assert path == str(tmp_path / "hvd_flight_recorder"
                           / "hvd_flight_recorder.rank7.json")
        assert json.loads(open(path).read())["rank"] == 7

    def test_disabled_records_and_dumps_nothing(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "0")
        rec = flight_recorder.FlightRecorder()
        rec.record("frame")
        assert rec.events() == []
        assert rec.dump("off", path=str(tmp_path / "no.json")) is None
        assert not (tmp_path / "no.json").exists()

    def test_dump_never_raises_on_bad_path(self):
        assert flight_recorder.recorder.dump(
            "bad", path="/nonexistent-dir-xyz/d.json") is None


# ---------------------------------------------------------------------------
# stall inspector -> metrics surfacing
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestStallMetrics:
    def _controller(self, warn=0.01, shut=0.0):
        from horovod_tpu.common.topology import ProcessTopology
        from horovod_tpu.core.controller import Controller

        topo = ProcessTopology(rank=0, size=2, local_rank=0, local_size=2)
        c = Controller(topo, mesh=None, stall_warning_secs=warn,
                       stall_shutdown_secs=shut)
        c._last_stall_check = 0.0  # force the next check to run
        return c

    def _stall_tensor(self, c, name="stuck", age=10.0):
        from horovod_tpu.core.controller import _TableEntry

        entry = _TableEntry()
        entry.ranks.add(0)
        entry.first_seen = time.monotonic() - age
        c._message_table[name] = entry

    def test_stalled_gauge_counts_overdue_tensors(self):
        c = self._controller(warn=0.01)
        self._stall_tensor(c, "stuck", age=10.0)
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 1
        # recovery: the next check with an empty table zeroes the gauge
        c._message_table.clear()
        c._last_stall_check = 0.0
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 0

    def test_fresh_tensor_not_counted(self):
        c = self._controller(warn=60.0)
        self._stall_tensor(c, "young", age=0.001)
        c._check_stalls()
        assert metrics.registry.get_gauge("stalled_tensors") == 0

    def test_stall_shutdown_increments_counter(self):
        from horovod_tpu.common.exceptions import HorovodInternalError

        c = self._controller(warn=0.0, shut=0.01)
        self._stall_tensor(c, "doomed", age=10.0)
        with pytest.raises(HorovodInternalError, match="stall shutdown"):
            c._check_stalls()
        assert metrics.registry.get_counter("stall_shutdowns_total") == 1


# ---------------------------------------------------------------------------
# online straggler detection (coordinator-side EWMAs)
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestStragglerDetector:
    def _controller(self, thresh=0.05, alpha=0.5, size=3):
        from horovod_tpu.common.topology import ProcessTopology
        from horovod_tpu.core.controller import Controller

        topo = ProcessTopology(rank=0, size=size, local_rank=0,
                               local_size=size)
        c = Controller(topo, mesh=None)
        c.straggler_threshold = thresh
        c.straggler_alpha = alpha
        return c

    def _lagging_entry(self, c, name="lag", ranks=(0, 2), age=1.0):
        from horovod_tpu.core.controller import _TableEntry

        entry = _TableEntry()
        entry.ranks.update(ranks)
        entry.majority_seen = time.monotonic() - age
        c._message_table[name] = entry

    def test_clean_state_early_outs(self):
        # Steady state (no majority stamps, no decaying EWMA) must not
        # even touch the EWMA dict — the hot path's two falsy checks.
        c = self._controller()
        c._update_stragglers()
        assert c._straggler_ewma == {}
        assert metrics.registry.get_gauge("straggler_suspect") is None

    def test_lag_flags_the_missing_rank(self):
        c = self._controller(thresh=0.05, alpha=0.5)
        self._lagging_entry(c, ranks=(0, 2), age=1.0)
        c._update_stragglers()
        # one EWMA step: 0 + 0.5 * (1.0s - 0) — only the missing rank lags
        assert c._straggler_ewma[1] == pytest.approx(0.5, rel=0.05)
        assert c._straggler_ewma[0] == 0.0
        assert c._straggler_ewma[2] == 0.0
        assert c._straggler_suspects == {1}
        assert metrics.registry.get_counter(
            "straggler_flags_total", rank="1") == 1
        assert metrics.registry.get_gauge("straggler_suspect") == 1
        key = metrics.flat("straggler_lag_seconds", rank="1")
        assert metrics.registry.snapshot()["histograms"][key]["count"] == 1
        flagged = [e for e in flight_recorder.recorder.events()
                   if e["kind"] == "straggler"]
        assert len(flagged) == 1 and flagged[0]["rank"] == 1

    def test_hysteresis_clears_at_half_threshold(self):
        c = self._controller(thresh=0.05, alpha=0.5)
        self._lagging_entry(c, age=1.0)
        c._update_stragglers()
        assert c._straggler_suspects == {1}
        c._message_table.clear()
        # decay: lag 0 every cycle, EWMA halves; the suspect must clear
        # only once it falls below thresh/2, and exactly once.
        for _ in range(50):
            c._update_stragglers()
            if not c._straggler_suspects:
                break
        assert not c._straggler_suspects
        assert c._straggler_ewma[1] < c.straggler_threshold / 2
        assert metrics.registry.get_gauge("straggler_suspect") == -1
        assert metrics.registry.get_counter(
            "straggler_flags_total", rank="1") == 1  # one episode, one flag
        kinds = [e["kind"] for e in flight_recorder.recorder.events()]
        assert kinds.count("straggler_cleared") == 1

    def test_mask_bit_majority_path_attributes_lag(self):
        # The cache fast path has no table entries: lag comes from
        # announced-bit majority stamps vs per-rank pending masks.
        c = self._controller(thresh=10.0, alpha=1.0)
        c._mask_bit_majority[3] = time.monotonic() - 0.5
        c._pending_masks = {0: 1 << 3, 2: 1 << 3}  # rank 1 silent on bit 3
        c._update_stragglers()
        assert c._straggler_ewma[1] == pytest.approx(0.5, rel=0.05)
        assert c._straggler_ewma[0] == 0.0
        assert c._straggler_ewma[2] == 0.0

    def test_joined_rank_is_not_blamed(self):
        c = self._controller(thresh=0.05, alpha=1.0)
        c._joined_ranks.add(1)
        self._lagging_entry(c, ranks=(0, 2), age=1.0)
        c._update_stragglers()
        assert c._straggler_ewma.get(1, 0.0) == 0.0
        assert not c._straggler_suspects

    def test_zero_threshold_disables_flagging_not_tracking(self):
        c = self._controller(thresh=0.0, alpha=1.0)
        self._lagging_entry(c, age=1.0)
        c._update_stragglers()
        assert c._straggler_ewma[1] > 0.9  # EWMA still tracks
        assert not c._straggler_suspects   # but nothing flags
        assert metrics.registry.get_gauge("straggler_suspect") is None

    def test_alpha_validation(self, monkeypatch):
        from horovod_tpu.common import env as env_mod

        monkeypatch.setenv(env_mod.HOROVOD_STRAGGLER_EWMA_ALPHA, "0")
        with pytest.raises(ValueError, match="STRAGGLER_EWMA_ALPHA"):
            self._controller()

    def test_stall_suffix_names_worst_laggard(self):
        c = self._controller()
        c._straggler_ewma = {1: 0.4, 2: 0.1}
        suffix = c._lag_suffix([1, 2])
        assert "rank 1" in suffix and "0.400" in suffix
        # a missing rank with no observed lag yields no accusation
        assert c._lag_suffix([0]) == ""

    # -- suspect-reset regression (ISSUE 17 satellite): demotion keys
    #    off live state, never a previous world's leftovers ------------

    def test_decay_clears_suspect_gauge_to_minus_one(self):
        c = self._controller(thresh=0.05, alpha=0.5)
        self._lagging_entry(c, age=1.0)
        c._update_stragglers()
        assert metrics.registry.get_gauge("straggler_suspect") == 1
        c._message_table.clear()
        for _ in range(50):
            c._update_stragglers()
        assert c._straggler_suspects == set()
        assert metrics.registry.get_gauge("straggler_suspect") == -1
        # ...and the decay loop itself un-wedges: once every EWMA is
        # at noise floor, the early-out flag drops back to False.
        assert c._straggler_decaying is False

    def test_fresh_controller_resets_stale_suspect_gauge(self):
        # An elastic epoch restart in the same process builds a NEW
        # controller; the process-global gauge must not keep naming the
        # old world's suspect (the demotion plane reads live state).
        c = self._controller(thresh=0.05, alpha=1.0)
        self._lagging_entry(c, age=1.0)
        c._update_stragglers()
        assert metrics.registry.get_gauge("straggler_suspect") == 1
        c2 = self._controller()
        assert metrics.registry.get_gauge("straggler_suspect") == -1
        assert c2._straggler_decaying is False
        assert c2._straggler_ewma == {}
        # the fresh world's clean cycles stay clean (no wedge from the
        # old controller's state)
        c2._update_stragglers()
        assert c2._straggler_suspects == set()


# ---------------------------------------------------------------------------
# chronic-straggler demotion: the verdict state machine as a pure unit
# (ISSUE 17; docs/elastic.md "self-healing demotion")
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestDemotionPolicy:
    def _policy(self, secs=1.0, cycles=3):
        from horovod_tpu.core.controller import DemotionPolicy

        return DemotionPolicy(secs, cycles)

    def test_disabled_by_default_threshold(self):
        p = self._policy(secs=0.0)
        assert not p.enabled
        assert p.observe(0, {1: 99.0}, {0, 1, 2}) is None

    def test_cycles_validation(self):
        with pytest.raises(ValueError, match="DEMOTE_CYCLES"):
            self._policy(cycles=0)

    def test_hysteresis_window_edges(self):
        # Table-driven: cycles of (ewma map, expected verdict).  The
        # verdict fires exactly ON the Nth consecutive over-threshold
        # cycle, not before, and a single under-threshold cycle resets
        # the streak to zero.
        p = self._policy(secs=1.0, cycles=3)
        world = {0, 1, 2}
        cases = [
            ({1: 2.0}, None),   # streak 1
            ({1: 2.0}, None),   # streak 2
            ({1: 0.5}, None),   # dips under: streak resets
            ({1: 2.0}, None),   # streak 1 again
            ({1: 2.0}, None),   # streak 2
            ({1: 2.0}, 1),      # streak 3 == cycles: verdict
        ]
        for i, (ewma, expected) in enumerate(cases):
            assert p.observe(0, ewma, world) == expected, f"cycle {i}"

    def test_exactly_at_threshold_is_not_over(self):
        # strict >: an EWMA sitting exactly on the knob never streaks
        p = self._policy(secs=1.0, cycles=1)
        assert p.observe(0, {1: 1.0}, {0, 1, 2}) is None
        assert p.observe(0, {1: 1.0001}, {0, 1, 2}) == 1

    def test_whole_world_slow_guard(self):
        # Half-or-more of the active world over threshold = a global
        # stall, not a straggler: nobody is demoted and streaks reset.
        p = self._policy(secs=1.0, cycles=2)
        world = {0, 1, 2, 3}
        slow_world = {1: 5.0, 2: 5.0}          # 2 of 4 = half
        for _ in range(10):
            assert p.observe(0, slow_world, world) is None
        # the stall must not have seeded streaks: rank 1 alone still
        # needs the FULL window from zero
        assert p.observe(0, {1: 5.0}, world) is None
        assert p.observe(0, {1: 5.0}, world) == 1

    def test_two_rank_world_never_demotes(self):
        # At np=2 one slow rank is half the world — the guard blocks
        # demotion by construction, no special case needed.
        p = self._policy(secs=1.0, cycles=1)
        for _ in range(5):
            assert p.observe(0, {1: 99.0}, {0, 1}) is None

    def test_one_demotion_per_epoch_cap(self):
        p = self._policy(secs=1.0, cycles=1)
        world = {0, 1, 2, 3, 4}
        assert p.observe(7, {1: 5.0}, world) == 1
        # rank 3 is just as chronic, but epoch 7 already shed a host
        for _ in range(10):
            assert p.observe(7, {3: 5.0}, world) is None
        # a new epoch re-arms the cap
        assert p.observe(8, {3: 5.0}, world) == 3

    def test_worst_ewma_wins_among_chronic(self):
        p = self._policy(secs=1.0, cycles=2)
        world = {0, 1, 2, 3, 4, 5, 6}
        both = {1: 2.0, 3: 9.0}
        assert p.observe(0, both, world) is None
        assert p.observe(0, both, world) == 3

    def test_recovered_rank_drops_from_streaks(self):
        p = self._policy(secs=1.0, cycles=3)
        world = {0, 1, 2, 3, 4}
        p.observe(0, {1: 5.0, 3: 5.0}, world)
        p.observe(0, {1: 5.0, 3: 5.0}, world)
        # rank 3 recovers; rank 1 completes the window alone
        assert p.observe(0, {1: 5.0}, world) == 1
        # rank 3's streak was wiped, not frozen
        assert p.observe(1, {3: 5.0}, world) is None


# ---------------------------------------------------------------------------
# demotion report parsing (driver side, no sockets) + blacklist strikes
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestDemotionReports:
    def _parse(self, raws, epoch):
        from horovod_tpu.elastic.driver import ElasticDriver

        return ElasticDriver._parse_demotion_reports(raws, epoch)

    def _report(self, epoch=3, rank=1, **extra):
        d = {"epoch": epoch, "rank": rank, "hostname": "h001",
             "ewma": 2.5, "threshold": 1.0, "cycles": 10}
        d.update(extra)
        return json.dumps(d).encode()

    def test_current_epoch_report_parses(self):
        reps = self._parse({"h000:0": self._report(epoch=3)}, epoch=3)
        assert len(reps) == 1
        assert reps[0]["rank"] == 1
        assert reps[0]["reporter"] == "h000:0"

    def test_stale_epoch_report_discarded(self):
        # A report stamped with an older epoch was answered by a later
        # bump already — it must not demote anyone in the new world.
        for stale in (0, 1, 2):
            assert self._parse(
                {"h000:0": self._report(epoch=stale)}, epoch=3) == []
        # future-stamped reports (clock/restart skew) are equally dead
        assert self._parse(
            {"h000:0": self._report(epoch=9)}, epoch=3) == []

    def test_absent_and_malformed_reports_skipped(self):
        raws = {"h000:0": None, "h001:0": b"not json",
                "h002:0": b"[1,2]", "h003:0": json.dumps(
                    {"epoch": 3, "rank": "one"}).encode()}
        assert self._parse(raws, epoch=3) == []

    def test_blacklist_idempotent_while_active(self):
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager
        from horovod_tpu.runner.hosts import parse_hosts

        hm = HostManager(FixedHosts(parse_hosts("a:1,b:1")),
                         blacklist_cooldown=60.0)
        assert hm.blacklist("a", evidence="rank 1 EWMA 2.5s") is True
        expiry = hm._blacklist["a"]
        # repeated strikes within the window: no stacking, expiry KEPT
        assert hm.blacklist("a", evidence="again") is False
        assert hm.blacklist("a") is False
        assert hm._blacklist["a"] == expiry
        assert hm.is_blacklisted("a")
        assert not hm.is_blacklisted("b")

    def test_blacklist_fresh_strike_after_expiry(self):
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager
        from horovod_tpu.runner.hosts import parse_hosts

        hm = HostManager(FixedHosts(parse_hosts("a:1")),
                         blacklist_cooldown=60.0)
        assert hm.blacklist("a") is True
        # simulate cooldown expiry
        hm._blacklist["a"] = hm._now() - 1.0
        assert hm.blacklist("a") is True  # a NEW strike, clock restarted
        assert hm._blacklist["a"] > hm._now()


# ---------------------------------------------------------------------------
# trace merge
# ---------------------------------------------------------------------------


def _trace(rank, wall_base_ns, server_offset_ns, events):
    head = [
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"rank {rank}"}},
        {"name": "clock_sync", "ph": "M", "pid": rank,
         "args": {"wall_base_ns": wall_base_ns,
                  "server_offset_ns": server_offset_ns, "rank": rank}},
    ]
    return head + events


@pytest.mark.smoke
class TestTraceMerge:
    def test_clock_alignment_subtracts_skew(self):
        from horovod_tpu.tools import trace_merge

        # Rank 1's wall clock runs 5 ms ahead of rank 0's, and its
        # server-offset estimate says exactly that: after alignment, two
        # spans that happened at the same server time coincide.
        t0 = _trace(0, 1_000_000_000, 0,
                    [{"name": "A", "ph": "B", "pid": 0, "tid": 1, "ts": 100}])
        t1 = _trace(1, 1_000_000_000 + 5_000_000, 5_000_000,
                    [{"name": "A", "ph": "B", "pid": 1, "tid": 1, "ts": 100}])
        merged = trace_merge.merge([json.loads(json.dumps(t)) for t in (t0, t1)])
        ts = [e["ts"] for e in merged if e.get("ph") == "B"]
        assert ts[0] == pytest.approx(ts[1])

    def test_missing_clock_sync_falls_back_to_concat(self):
        from horovod_tpu.tools import trace_merge

        warnings = []
        t0 = _trace(0, 1_000, 0,
                    [{"name": "A", "ph": "B", "pid": 0, "tid": 1, "ts": 7}])
        t1 = [{"name": "A", "ph": "B", "pid": 1, "tid": 1, "ts": 9}]
        merged = trace_merge.merge([t0, t1], warn=warnings.append)
        assert warnings and "WITHOUT" in warnings[0]
        assert sorted(e["ts"] for e in merged if "ts" in e) == [7, 9]

    def test_truncated_trace_is_repaired(self, tmp_path):
        from horovod_tpu.tools import trace_merge

        p = tmp_path / "trunc.json"
        p.write_text('[\n{"name": "A", "ph": "B", "pid": 0, "ts": 1},\n'
                     '{"name": "B", "ph": "E", "pid": 0, "ts":')  # cut mid-record
        events = trace_merge.load_trace(str(p))
        assert [e["name"] for e in events] == ["A"]

    def test_cli_writes_merged_file(self, tmp_path):
        from horovod_tpu.tools import trace_merge

        for r in range(2):
            (tmp_path / f"t{r}.json").write_text(json.dumps(
                _trace(r, 1_000_000, 0,
                       [{"name": "X", "ph": "B", "pid": r, "tid": 1,
                         "ts": 5, "args": {"cycle": 3}}])))
        out = tmp_path / "merged.json"
        rc = trace_merge.main([str(tmp_path / "t0.json"),
                               str(tmp_path / "t1.json"), "-o", str(out)])
        assert rc == 0
        merged = json.loads(out.read_text())
        assert {e.get("pid") for e in merged if e.get("ph") == "B"} == {0, 1}

    def test_server_trace_merges_unshifted(self):
        """The server is trace_merge's clock base: its own trace carries
        offset 0, so when it is the earliest input its spans merge with
        shift 0 while worker spans are rebased onto its axis."""
        from horovod_tpu.core.timeline import SERVER_TRACE_PID
        from horovod_tpu.tools import trace_merge

        server = _trace(SERVER_TRACE_PID, 1_000_000_000, 0,
                        [{"name": "RV_PUT", "ph": "X",
                          "pid": SERVER_TRACE_PID, "tid": 1,
                          "ts": 40.0, "dur": 10.0}])
        # Worker wall clock runs 7 ms ahead; it started 2 ms of server
        # time after the server's trace began.
        worker = _trace(0, 1_000_000_000 + 9_000_000, 7_000_000,
                        [{"name": "RVC_SET", "ph": "X", "pid": 0,
                          "tid": 1, "ts": 40.0, "dur": 30.0}])
        merged = trace_merge.merge([server, worker])
        ts = {e["pid"]: e["ts"] for e in merged if e.get("ph") == "X"}
        assert ts[SERVER_TRACE_PID] == pytest.approx(40.0)
        assert ts[0] == pytest.approx(40.0 + 2_000.0)

    def test_live_server_trace_lane_and_crash_repair(self, tmp_path):
        """A real traced server: RV_* spans land on the reserved server
        pid with a zero-offset clock_sync, and a crash-truncated copy of
        the file repairs to a valid prefix on load."""
        from horovod_tpu.core.timeline import SERVER_TRACE_PID
        from horovod_tpu.runner.rendezvous import RendezvousServer
        from horovod_tpu.transport.store import HTTPStoreClient
        from horovod_tpu.tools import trace_merge

        path = tmp_path / "server.json"
        server = RendezvousServer("127.0.0.1", trace_path=str(path))
        port = server.start()
        try:
            client = HTTPStoreClient("127.0.0.1", port)
            for i in range(4):
                client.set("scope", f"k{i}", b"v")
            client.keys("scope")
            client.get("scope", "k0")
        finally:
            server.stop()
        events = trace_merge.load_trace(str(path))
        spans = [e for e in events if e.get("ph") == "X"]
        names = {e["name"] for e in spans}
        assert {"RV_PUT", "RV_KEYS", "RV_GET"} <= names, names
        assert {e["pid"] for e in spans} == {SERVER_TRACE_PID}
        sync = trace_merge._clock_sync(events)
        assert sync is not None and sync[1] == SERVER_TRACE_PID
        # Crash contract: cut mid-record (a SIGKILL'd server never writes
        # the closing bracket) and the loader keeps the valid prefix.
        text = path.read_text()
        trunc = tmp_path / "trunc.json"
        trunc.write_text(text[:text.rindex("{") + 10])
        repaired = trace_merge.load_trace(str(trunc))
        assert 0 < len(repaired) < len(events)
        assert all(isinstance(e, dict) for e in repaired)


# ---------------------------------------------------------------------------
# critical-path extraction
# ---------------------------------------------------------------------------


def _cp_ev(name, ph, pid, tid, ts, **args):
    e = {"name": name, "ph": ph, "pid": pid, "tid": tid, "ts": ts}
    if args:
        e["args"] = args
    return e


def _cp_step_events():
    """One negotiation cycle (7), three ranks: rank 1 announced 80 us
    after the span opened (everyone waited for it), rank 0 shows a full
    fuse/wire/reduce breakdown, rank 1's op span ends the step."""
    return [
        # coordinator negotiation lane (pid 0) with readiness instants
        _cp_ev("NEGOTIATE_ALLREDUCE", "B", 0, 9, 100, cycle=7),
        _cp_ev("0", "i", 0, 9, 110),
        _cp_ev("2", "i", 0, 9, 120),
        _cp_ev("1", "i", 0, 9, 180),
        _cp_ev("NEGOTIATE_ALLREDUCE", "E", 0, 9, 185),
        # rank 0 tensor lane: op span with nested lifecycle phases
        _cp_ev("ALLREDUCE", "B", 0, 1, 200, cycle=7),
        _cp_ev("LC_FUSE", "B", 0, 1, 200),           # inherits cycle 7
        _cp_ev("LC_FUSE", "E", 0, 1, 210),
        _cp_ev("LC_WIRE_REDUCE_SCATTER", "B", 0, 1, 215),
        _cp_ev("LC_WIRE_REDUCE_SCATTER", "E", 0, 1, 245),
        _cp_ev("LC_WIRE_ALLGATHER", "B", 0, 1, 245),
        _cp_ev("LC_WIRE_ALLGATHER", "E", 0, 1, 275),
        _cp_ev("ALLREDUCE", "E", 0, 1, 300),
        # ranks 1 and 2: bare op spans; rank 1 ends last
        _cp_ev("ALLREDUCE", "B", 1, 1, 150, cycle=7),
        _cp_ev("ALLREDUCE", "E", 1, 1, 320),
        _cp_ev("ALLREDUCE", "B", 2, 1, 150, cycle=7),
        _cp_ev("ALLREDUCE", "E", 2, 1, 260),
    ]


@pytest.mark.smoke
class TestCriticalPath:
    def test_step_attribution(self):
        from horovod_tpu.tools import critical_path

        doc = critical_path.analyze(_cp_step_events())
        assert doc["format"] == "hvd-critical-path-v1"
        assert doc["ranks_seen"] == [0, 1, 2]
        (step,) = doc["steps"]
        assert step["cycle"] == 7
        assert step["duration_us"] == 220.0        # 100 .. 320
        assert step["critical_rank"] == 1
        assert doc["critical_step_counts"] == {"1": 1}
        p0 = step["phases_us"]["0"]
        # negotiation wait goes to the LAST-ready rank (1), not pid 0
        assert "negotiation_wait" not in step["phases_us"].get("0", {}) \
            or p0["negotiation_wait"] == 0.0
        assert step["phases_us"]["1"]["negotiation_wait"] == 80.0
        assert p0["fusion"] == 10.0
        assert p0["reduce"] == 30.0
        assert p0["wire"] == 30.0
        # dispatch = op span minus the attributed sub-phases
        assert p0["dispatch"] == 100.0 - 70.0
        assert step["phases_us"]["2"]["dispatch"] == 110.0

    def test_fused_batch_counts_wire_once(self):
        from horovod_tpu.tools import critical_path

        # A fused batch emits the same wire span on every member tensor's
        # lane: attribution must union, not sum.
        events = [
            _cp_ev("LC_WIRE_ALLGATHER", "B", 0, 1, 10, cycle=1),
            _cp_ev("LC_WIRE_ALLGATHER", "E", 0, 1, 30),
            _cp_ev("LC_WIRE_ALLGATHER", "B", 0, 2, 10, cycle=1),
            _cp_ev("LC_WIRE_ALLGATHER", "E", 0, 2, 30),
        ]
        doc = critical_path.analyze(events)
        assert doc["totals_us"]["0"]["wire"] == 20.0

    def test_unclosed_span_closes_at_lane_end(self):
        from horovod_tpu.tools import critical_path

        events = [
            _cp_ev("ALLREDUCE", "B", 0, 1, 10, cycle=1),
            _cp_ev("LC_FUSE", "B", 0, 1, 20),
            _cp_ev("LC_FUSE", "E", 0, 1, 40),   # lane's last ts
        ]
        spans = critical_path.reconstruct(events)
        op = next(s for s in spans if s.name == "ALLREDUCE")
        assert op.e == 40
        assert all(s.cycle == 1 for s in spans)  # nested inheritance

    def test_cli_writes_json_report(self, tmp_path, capsys):
        from horovod_tpu.tools import critical_path

        trace = tmp_path / "tl.json"
        trace.write_text(json.dumps(_cp_step_events()))
        out = tmp_path / "cp.json"
        rc = critical_path.main([str(trace), "--json", str(out), "--top", "3"])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["steps"][0]["critical_rank"] == 1
        text = capsys.readouterr().out
        assert "critical rank by step count: rank 1" in text

    def test_no_cycles_degrades_gracefully(self):
        from horovod_tpu.tools import critical_path

        doc = critical_path.analyze([_cp_ev("X", "B", 0, 1, 5),
                                     _cp_ev("X", "E", 0, 1, 9)])
        assert doc["steps"] == []
        assert "HOROVOD_TIMELINE" in critical_path.render_text(doc)


# ---------------------------------------------------------------------------
# control-path attribution (hvd-control-path)
# ---------------------------------------------------------------------------


def _x(name, pid, ts, dur, **args):
    e = {"name": name, "ph": "X", "pid": pid, "tid": 1,
         "ts": float(ts), "dur": float(dur)}
    if args:
        e["args"] = args
    return e


def _churn_events():
    """One churn event window 0..100 µs: a 40 µs client round-trip with a
    server handler, lock wait, and fsync nested inside, plus a respawn."""
    from horovod_tpu.core.timeline import DRIVER_TRACE_PID, SERVER_TRACE_PID

    d, s = DRIVER_TRACE_PID, SERVER_TRACE_PID
    return [
        _x("CHURN_EVENT", d, 0, 100, cause="lease_expiry", epoch=3),
        _x("RVC_SET", d, 10, 40, scope="lease"),
        _x("RV_PUT", s, 15, 30, scope="lease"),
        _x("RV_LOCK_WAIT", s, 20, 10),
        _x("JR_FSYNC", s, 30, 10),
        _x("DRV_SPAWN", d, 60, 30),
    ]


@pytest.mark.smoke
class TestControlPath:
    def test_disjoint_carve_and_coverage(self):
        from horovod_tpu.tools import control_path

        doc = control_path.analyze(_churn_events())
        assert doc["format"] == "hvd-control-path-v1"
        (ev,) = doc["events"]
        assert ev["cause"] == "lease_expiry" and ev["epoch"] == 3
        ph = ev["phases_us"]
        # The lock wait and fsync nest inside the HTTP round-trip: they
        # keep their own phase, HTTP only keeps what they don't explain.
        assert ph["store_lock_wait"] == 10.0       # 20..30
        assert ph["journal_fsync"] == 10.0         # 30..40
        assert ph["http_roundtrip"] == 20.0        # 10..50 minus 20..40
        assert ph["respawn"] == 30.0               # 60..90
        assert ph["driver_tick_wait"] == 0.0
        assert ev["unattributed_us"] == 30.0
        assert ev["coverage"] == pytest.approx(0.7)
        assert doc["coverage"] == pytest.approx(0.7)
        assert doc["phase_share"]["respawn"] == pytest.approx(0.3)

    def test_spans_clip_to_their_window(self):
        from horovod_tpu.core.timeline import DRIVER_TRACE_PID
        from horovod_tpu.tools import control_path

        d = DRIVER_TRACE_PID
        doc = control_path.analyze([
            _x("CHURN_EVENT", d, 0, 100, cause="sim", epoch=1),
            # straddles the window's end: only 80..100 may count
            _x("RVC_GET", d, 80, 40, scope="lease"),
        ])
        (ev,) = doc["events"]
        assert ev["phases_us"]["http_roundtrip"] == 20.0

    def test_b_e_worker_spans_are_ignored(self):
        from horovod_tpu.core.timeline import DRIVER_TRACE_PID
        from horovod_tpu.tools import control_path

        d = DRIVER_TRACE_PID
        doc = control_path.analyze([
            _x("CHURN_EVENT", d, 0, 100, cause="sim", epoch=1),
            {"name": "ALLREDUCE", "ph": "B", "pid": 0, "tid": 1, "ts": 5},
            {"name": "ALLREDUCE", "ph": "E", "pid": 0, "tid": 1, "ts": 95},
        ])
        (ev,) = doc["events"]
        assert all(v == 0.0 for v in ev["phases_us"].values())

    def test_empty_trace_renders_hint(self):
        from horovod_tpu.tools import control_path

        doc = control_path.analyze([])
        assert doc["event_count"] == 0 and doc["coverage"] == 1.0
        assert "CHURN_EVENT" in control_path.render_text(doc)

    def test_cli_json_report(self, tmp_path, capsys):
        from horovod_tpu.tools import control_path

        trace = tmp_path / "merged.json"
        trace.write_text(json.dumps(_churn_events()))
        out = tmp_path / "cp.json"
        rc = control_path.main([str(trace), "--json", str(out)])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["event_count"] == 1
        text = capsys.readouterr().out
        assert "coverage 70.0%" in text
        assert "respawn" in text


# ---------------------------------------------------------------------------
# prometheus text validator (the metrics-smoke lane's checker)
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestPromValidate:
    def test_real_render_is_valid(self):
        from horovod_tpu.tools import prom_validate

        counts = [0] * (len(metrics.BUCKET_BOUNDS) + 1)
        counts[2] = 1
        text = metrics.render_prometheus({
            0: _snap(0, counters={"aborts_total": 2},
                     gauges={"tensor_queue_depth": 1},
                     histograms={"collective_latency_seconds": {
                         "counts": counts, "sum": 0.5, "count": 1}}),
            1: _snap(1, gauges={"straggler_suspect": -1})})
        assert prom_validate.validate(text) == []

    def test_required_family_enforced(self):
        from horovod_tpu.tools import prom_validate

        text = metrics.render_prometheus(
            {0: _snap(0, counters={"aborts_total": 1})})
        errs = prom_validate.validate(
            text, required=["straggler_flags_total"])
        assert any("straggler_flags_total" in e and "missing" in e
                   for e in errs)

    def test_uncataloged_family_rejected(self):
        from horovod_tpu.tools import prom_validate

        text = ("# HELP hvd_bogus_total x\n"
                "# TYPE hvd_bogus_total counter\n"
                "hvd_bogus_total 1\n")
        errs = prom_validate.validate(text)
        assert any("not in CATALOG" in e for e in errs)

    def test_sample_before_metadata_rejected(self):
        from horovod_tpu.tools import prom_validate

        errs = prom_validate.validate("hvd_aborts_total 1\n")
        assert any("before its # TYPE" in e for e in errs)
        assert any("before its # HELP" in e for e in errs)

    def test_non_cumulative_buckets_rejected(self):
        from horovod_tpu.tools import prom_validate

        text = (
            "# HELP hvd_collective_latency_seconds x\n"
            "# TYPE hvd_collective_latency_seconds histogram\n"
            'hvd_collective_latency_seconds_bucket{le="0.1"} 3\n'
            'hvd_collective_latency_seconds_bucket{le="+Inf"} 2\n'
            "hvd_collective_latency_seconds_sum 1\n"
            "hvd_collective_latency_seconds_count 2\n")
        errs = prom_validate.validate(text)
        assert any("not cumulative" in e for e in errs)

    def test_inf_bucket_must_equal_count(self):
        from horovod_tpu.tools import prom_validate

        text = (
            "# HELP hvd_collective_latency_seconds x\n"
            "# TYPE hvd_collective_latency_seconds histogram\n"
            'hvd_collective_latency_seconds_bucket{le="+Inf"} 5\n'
            "hvd_collective_latency_seconds_sum 1\n"
            "hvd_collective_latency_seconds_count 4\n")
        errs = prom_validate.validate(text)
        assert any("+Inf bucket" in e and "_count" in e for e in errs)

    def test_kind_mismatch_rejected(self):
        from horovod_tpu.tools import prom_validate

        text = ("# HELP hvd_aborts_total x\n"
                "# TYPE hvd_aborts_total gauge\n"
                "hvd_aborts_total 1\n")
        errs = prom_validate.validate(text)
        assert any("catalog kind" in e for e in errs)


# ---------------------------------------------------------------------------
# metrics-dump --watch/--rate
# ---------------------------------------------------------------------------


@pytest.mark.smoke
class TestMetricsDumpWatch:
    def test_rates_are_per_second_deltas(self):
        from horovod_tpu.tools import metrics_dump

        prev = {"0": {"rank": 0, "counters": {"x_total": 10},
                      "histograms": {"h": {"count": 2, "sum": 1.0}}}}
        cur = {"0": {"rank": 0, "counters": {"x_total": 30},
                     "gauges": {"depth": 5},
                     "histograms": {"h": {"count": 6, "sum": 3.0}}}}
        out = metrics_dump._rates(prev, cur, 2.0)
        assert "x_total = +10/s" in out       # (30-10)/2s
        assert "depth = 5 (gauge)" in out     # gauges are levels
        assert "+2 obs/s" in out and "mean=0.5" in out

    def test_unchanged_counters_are_omitted(self):
        from horovod_tpu.tools import metrics_dump

        snap = {"0": {"rank": 0, "counters": {"x_total": 10}}}
        out = metrics_dump._rates(snap, snap, 1.0)
        assert "x_total" not in out

    def test_rate_requires_watch(self):
        from horovod_tpu.tools import metrics_dump

        with pytest.raises(SystemExit):
            metrics_dump.main(["--rate"])


# ---------------------------------------------------------------------------
# runtime timeline toggles (satellite: the core/timeline.py docstring's
# promise, with balanced B/E per lane)
# ---------------------------------------------------------------------------


def test_start_stop_timeline_balanced_lanes(tmp_path):
    import os

    from horovod_tpu.core import state as state_mod

    state_mod.reset_global_state()
    os.environ.pop("HOROVOD_SIZE", None)
    import horovod_tpu.frameworks.jax.basics as basics
    import horovod_tpu.frameworks.jax.ops as ops

    basics.init()
    try:
        tl = tmp_path / "toggle.json"
        basics.start_timeline(str(tl), mark_cycles=True)
        for i in range(3):
            ops.allreduce(np.ones(8, np.float32), name=f"tg{i}")
        basics.stop_timeline()
        events = json.loads(tl.read_text())  # completed file parses
        assert state_mod.global_state().timeline is None
        # every lane's B (begin) events are balanced by E (end) events
        per_lane = Counter()
        for e in events:
            if e.get("ph") in ("B", "E"):
                per_lane[(e.get("pid"), e.get("tid"), e["ph"])] += 1
        lanes = {(p, t) for (p, t, _ph) in per_lane}
        assert lanes, "no span events recorded"
        for p, t in lanes:
            assert per_lane[(p, t, "B")] == per_lane[(p, t, "E")], \
                (p, t, per_lane)
        # spans are cycle-tagged and the clock_sync anchor is present
        assert any(e.get("args", {}).get("cycle") for e in events
                   if e.get("ph") == "B")
        assert any(e.get("name") == "clock_sync" for e in events)
        # a second start after stop works (toggle, not one-shot)
        tl2 = tmp_path / "toggle2.json"
        basics.start_timeline(str(tl2))
        ops.allreduce(np.ones(8, np.float32), name="tg_again")
        basics.stop_timeline()
        assert any(e.get("ph") == "B" for e in json.loads(tl2.read_text()))
    finally:
        state_mod.global_state().shutdown()
        state_mod.reset_global_state()


# ---------------------------------------------------------------------------
# np=2 end-to-end proofs (chaos-marked: multiprocess jobs sort last)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.timeout(240)
def test_metrics_scrape_e2e_np2():
    """Acceptance proof (a): a live np=2 job's ``GET /metrics`` serves
    Prometheus text with cross-rank collective latency histograms and
    per-rank gauges."""
    body = """
import time, urllib.request
for i in range(6):
    hvd.allreduce(np.ones(1024, np.float32), name=f"m{i % 2}")
hvd.barrier()
time.sleep(1.2)
hvd.barrier()
if rank == 0:
    addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
    port = os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]
    deadline = time.time() + 30
    text = ""
    while time.time() < deadline:
        text = urllib.request.urlopen(
            f"http://{addr}:{port}/metrics", timeout=5).read().decode()
        if ('hvd_collective_latency_seconds_bucket' in text
                and 'rank="1"' in text):
            break
        time.sleep(0.3)
    assert 'hvd_collective_latency_seconds_bucket' in text, text[:3000]
    assert 'op="ALLREDUCE"' in text, text[:3000]
    assert 'dtype="FLOAT32"' in text, text[:3000]
    assert 'rank="0"' in text and 'rank="1"' in text, text[:3000]
    assert 'hvd_wire_bytes_on_wire_total' in text, text[:3000]
    assert '# TYPE hvd_collective_latency_seconds histogram' in text
    print("SCRAPE_OK", flush=True)
"""
    outs = run_distributed(
        2, body, timeout=180,
        extra_env={"HOROVOD_METRICS_PUSH_SECS": "0.2"})
    assert "SCRAPE_OK" in outs[0], outs[0]


@pytest.mark.chaos
@pytest.mark.timeout(240)
def test_trace_merge_e2e_np2(tmp_path):
    """Acceptance proof (b): per-rank traces from a real np=2 job merge
    into one Chrome trace where both ranks' lanes for the same collective
    share a negotiation cycle id."""
    from horovod_tpu.tools import trace_merge

    tl = tmp_path / "tl.json"
    run_distributed(2, """
for i in range(4):
    hvd.allreduce(np.ones(64, np.float32), name="tm0")
""", timeout=180, extra_env={"HOROVOD_TIMELINE": str(tl)})
    merged_path = tmp_path / "merged.json"
    rc = trace_merge.main([str(tl), f"{tl}.rank1", "-o", str(merged_path)])
    assert rc == 0
    events = json.loads(merged_path.read_text())
    lane_names = {
        (e["pid"], e["tid"]): e["args"]["name"] for e in events
        if e.get("name") == "thread_name" and e.get("ph") == "M"}
    cycles = {0: [], 1: []}
    for e in events:
        if e.get("ph") == "B" and e.get("name") == "ALLREDUCE" \
                and lane_names.get((e["pid"], e["tid"])) == "tm0":
            cycles[e["pid"]].append(e["args"]["cycle"])
    assert cycles[0], "rank 0 recorded no ALLREDUCE spans"
    assert cycles[1], "rank 1 recorded no ALLREDUCE spans"
    assert sorted(cycles[0]) == sorted(cycles[1]), \
        "ranks disagree on the cycle ids of the same collectives"
