"""XLA eager data-plane tests.

Single-process tier exercises the lazy one-device mesh; the multi-process
tier launches real worker processes with ``HOROVOD_DATA_PLANE=xla`` +
``jax.distributed`` (Gloo-backed CPU collectives playing ICI's role), the
same path a TPU pod takes.  Counters in ``horovod_tpu.backend.xla.stats``
prove the device path actually ran — a silent fallback to the TCP ring
would pass correctness checks but fail the stats assertions.

Reference analog: ``test/parallel/test_tensorflow.py`` GPU collective
sections (:336-455) executed under a real multi-process launcher.
"""


import numpy as np
import pytest

from .helpers import run_distributed

jax = pytest.importorskip("jax")


def _free_port() -> int:
    from .helpers import reserve_port

    return reserve_port()


def _xla_env() -> dict:
    return {
        "HOROVOD_DATA_PLANE": "xla",
        "HOROVOD_JAX_COORDINATOR": f"127.0.0.1:{_free_port()}",
    }


_ASSERT_XLA = """
from horovod_tpu.backend.xla import context, stats
assert context().ready, "XLA data plane failed to come up"
"""


def test_xla_multiprocess_allreduce_and_fusion():
    """Sum + average over the 2-process device mesh; several tensors in
    flight fuse into one bucketed collective."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
x = jnp.arange(8, dtype=jnp.float32) + rank
h1 = hvd.allreduce_async(x, op=hvd.Sum, name="a")
h2 = hvd.allreduce_async(x * 2, op=hvd.Sum, name="b")
o1, o2 = hvd.synchronize(h1), hvd.synchronize(h2)
exp = sum(np.arange(8, dtype=np.float32) + r for r in range(size))
assert np.allclose(np.asarray(o1), exp), o1
assert np.allclose(np.asarray(o2), 2 * exp), o2
avg = hvd.allreduce(x, name="c")
assert np.allclose(np.asarray(avg), exp / size)
assert stats.get("allreduce", 0) >= 2, stats
print("XLA_AR_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_AR_OK {r}" in o


def test_xla_multiprocess_broadcast_allgather_bf16():
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
b = jnp.full(5, float(rank + 3))
ob = hvd.broadcast(b, root_rank=1, name="b1")
assert np.allclose(np.asarray(ob), 4.0), ob
g = jnp.full((rank + 1, 2), float(rank), dtype=jnp.float32)
og = hvd.allgather(g, name="g1")
exp_g = np.concatenate(
    [np.full((r + 1, 2), float(r), np.float32) for r in range(size)])
assert np.allclose(np.asarray(og), exp_g), og
xb = jnp.ones(16, dtype=jnp.bfloat16) * (rank + 1)
ob16 = hvd.allreduce(xb, op=hvd.Sum, name="bf")
assert ob16.dtype == jnp.bfloat16
assert np.allclose(np.asarray(ob16, dtype=np.float32), 3.0)
assert stats.get("broadcast", 0) >= 1 and stats.get("allgather", 0) >= 1
print("XLA_BG_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_BG_OK {r}" in o


def test_xla_mixed_device_submission_falls_back_consistently():
    """One rank submits numpy, the other a jax array: the negotiated device
    set is mixed, so BOTH ranks must take the TCP ring (no deadlock)."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
mine = jnp.ones(4, jnp.float32) if rank == 0 else np.ones(4, np.float32)
o = hvd.allreduce(mine, op=hvd.Sum, name="mix")
assert np.allclose(np.asarray(o), size), o
assert stats.get("allreduce", 0) == 0, stats  # device path must NOT run
print("XLA_MIX_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_MIX_OK {r}" in o


def test_xla_join_zero_substitution():
    """A joined rank contributes device zeros so every rank still takes the
    device collective path."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
if rank == 0:
    for i in range(3):
        o = hvd.allreduce(jnp.ones(4, jnp.float32), op=hvd.Sum, name=f"j{i}")
        print("J", i, np.asarray(o).tolist(), flush=True)
    hvd.join()
else:
    o = hvd.allreduce(jnp.ones(4, jnp.float32), op=hvd.Sum, name="j0")
    hvd.join()
print("XLA_JOIN_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_JOIN_OK {r}" in o
    # first collective had both ranks (2.0); later ones ran against zeros
    assert "J 0 [2.0, 2.0, 2.0, 2.0]" in out[0]
    assert "J 1 [1.0, 1.0, 1.0, 1.0]" in out[0]


def test_xla_four_process_world():
    env = _xla_env()
    out = run_distributed(4, _ASSERT_XLA + """
import jax.numpy as jnp
x = jnp.full(1000, float(rank + 1))
o = hvd.allreduce(x, op=hvd.Sum, name="big")
assert np.allclose(np.asarray(o), 10.0), o
print("XLA_4P_OK", rank, flush=True)
""", extra_env=env)
    for r, o in enumerate(out):
        assert f"XLA_4P_OK {r}" in o


def test_xla_single_process_lazy_context():
    """Without HOROVOD_DATA_PLANE, a single-process world still uses the
    device plane lazily the first time a jax array is enqueued."""
    out = run_distributed(1, """
import jax.numpy as jnp
from horovod_tpu.backend.xla import context, stats
o = hvd.allreduce(jnp.arange(4, dtype=jnp.float32), op=hvd.Sum, name="s")
assert np.allclose(np.asarray(o), np.arange(4))
assert context().ready
assert stats.get("allreduce", 0) == 1, stats
print("XLA_1P_OK", rank, flush=True)
""")
    assert "XLA_1P_OK 0" in out[0]


def test_xla_bucket_reuse_no_recompile_churn():
    """Same-size payloads reuse one compiled collective: the compile cache
    should hold ONE allreduce entry for many same-bucket calls."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
for i in range(6):
    hvd.allreduce(jnp.ones(100, jnp.float32) * i, op=hvd.Sum, name=f"r{i}")
# One fused collective+unfuse computation for the whole steady-state run
# (key includes the entry composition; repeated compositions reuse it).
keys = [k for k in context()._compiled if k[0] == "ar.fused"]
assert len(keys) == 1, keys
print("XLA_BUCKET_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_BUCKET_OK {r}" in o


def test_xla_multiprocess_alltoall_uneven_splits():
    """Device alltoall: uneven (src → dst) blocks ride one XLA AllToAll
    (NCCLAlltoall role); received_splits surface like the TCP path."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp
import horovod_tpu.frameworks.jax.ops as ops

# rank 0 sends 1 row to rank 0 and 2 rows to rank 1; rank 1 sends 2/1.
splits = [1, 2] if rank == 0 else [2, 1]
x = jnp.arange(3 * 2, dtype=jnp.float32).reshape(3, 2) + 100 * rank
o, rsplits = ops.alltoall(x, splits=splits, name="da2a",
                          return_received_splits=True)
# recv from r = r's send split toward me: rank0 gets [1, 2], rank1 [2, 1]
exp_rsplits = [1, 2] if rank == 0 else [2, 1]
assert list(rsplits) == exp_rsplits, rsplits
x0 = np.arange(6, dtype=np.float32).reshape(3, 2)
x1 = x0 + 100
exp = np.concatenate([x0[0:1], x1[0:2]]) if rank == 0 \
    else np.concatenate([x0[1:3], x1[2:3]])
assert np.allclose(np.asarray(o), exp), (np.asarray(o), exp)
assert stats.get("alltoall", 0) >= 1, stats
print("XLA_A2A_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_A2A_OK {r}" in o


def test_xla_device_adasum_two_ranks_matches_closed_form():
    """On-device VHDD (XlaAdasum): 2-rank result equals the closed-form
    operator; stats prove the device path ran (reference GPU-Adasum role,
    ``adasum_gpu_operations.cc:38-100``)."""
    out = run_distributed(2, _ASSERT_XLA + """
import jax.numpy as jnp

a = jnp.asarray(np.array([1.0, 0.5, -1.0], np.float32) * (rank + 1))
res = np.asarray(hvd.allreduce(a, op=hvd.Adasum, name="dev.adasum"))

g0 = np.array([1.0, 0.5, -1.0]); g1 = 2 * g0
dot = g0 @ g1
exp = (1 - dot/(2*(g0@g0)))*g0 + (1 - dot/(2*(g1@g1)))*g1
assert np.allclose(res, exp, atol=1e-5), (res, exp)
assert stats.get("adasum", 0) >= 1, stats
print("XLA_ADASUM_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_ADASUM_OK {r}" in o


def test_xla_device_adasum_four_ranks_tree():
    """4 ranks: the on-device recursion must equal the host VHDD tree —
    pairwise combine (0,1) and (2,3), then combine the pair results."""
    out = run_distributed(4, _ASSERT_XLA + """
import jax.numpy as jnp

def combine(a, b):
    dot = float(a @ b); na = float(a @ a); nb = float(b @ b)
    ca = 1 - dot/(2*na) if na else 1.0
    cb = 1 - dot/(2*nb) if nb else 1.0
    return ca*a + cb*b

vecs = [np.array([1.0, 2.0], np.float32),
        np.array([0.5, -1.0], np.float32),
        np.array([2.0, 0.0], np.float32),
        np.array([-1.0, 1.0], np.float32)]
mine = jnp.asarray(vecs[rank])
res = np.asarray(hvd.allreduce(mine, op=hvd.Adasum, name="dev.adasum4"))
exp = combine(combine(vecs[0], vecs[1]), combine(vecs[2], vecs[3]))
assert np.allclose(res, exp, atol=1e-4), (res, exp)
assert stats.get("adasum", 0) >= 1, stats
print("XLA_ADASUM4_OK", rank, flush=True)
""", extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"XLA_ADASUM4_OK {r}" in o


@pytest.mark.skipif(not hasattr(jax.lax, "ragged_all_to_all"),
                    reason="this jax has no lax.ragged_all_to_all: the "
                           "deterministic pre-check flips the fallback "
                           "before any dispatch, which is the correct "
                           "behavior but leaves nothing to exercise here")
def test_ragged_fallback_only_on_capability_errors():
    """VERDICT r3 weak #4: a transient dispatch fault (e.g. OOM) must NOT
    flip the sticky ragged→bucketed fallback — on one rank only, that
    would desync the dispatch sequence across the mesh.  Only compile-time
    capability rejections may flip it (they resolve identically on every
    rank)."""
    out = run_distributed(1, """
import jax.numpy as jnp
import horovod_tpu.backend.xla as xla_mod
from horovod_tpu.backend.xla import XlaAlltoall
from horovod_tpu.common.exceptions import HorovodInternalError

# Pretend we're on TPU so the ragged branch is taken.
xla_mod._device_platform = lambda ctx: "tpu"

# 1. transient fault: op fails, fallback NOT flipped
def _boom(self, *a, **k):
    raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while dispatching")
orig = XlaAlltoall._ragged
XlaAlltoall._ragged = _boom
try:
    hvd.alltoall(jnp.arange(4, dtype=jnp.float32), name="a2a.t1")
    raise SystemExit("expected the transient fault to surface")
except HorovodInternalError as e:
    assert "RESOURCE_EXHAUSTED" in str(e), e
assert not XlaAlltoall._ragged_broken, "transient fault flipped the fallback"

# 2. capability rejection: falls back to bucketed, succeeds, flips sticky
def _unimpl(self, *a, **k):
    raise NotImplementedError("ragged_all_to_all not supported")
XlaAlltoall._ragged = _unimpl
res = np.asarray(hvd.alltoall(jnp.arange(4, dtype=jnp.float32), name="a2a.t2"))
assert np.allclose(res, np.arange(4)), res
assert XlaAlltoall._ragged_broken, "capability rejection did not flip"
XlaAlltoall._ragged = orig
print("RAGGED_GUARD_OK", rank, flush=True)
""", timeout=240)
    assert "RAGGED_GUARD_OK 0" in out[0]
