"""Timeline output + error-path regression tests (reference analogs:
``test/integration/test_timeline.py`` and the review findings on init retry,
handle leaks, and broadcast-under-join)."""

import json
import os
import tempfile

import numpy as np
import pytest

from .helpers import run_distributed


def test_timeline_written_and_parseable(tmp_path):
    tl = tmp_path / "timeline.json"
    run_distributed(2, """
for i in range(3):
    hvd.allreduce(np.ones(16, np.float32), name=f"t{i}")
hvd.allgather(np.ones(2, np.float32), name="g0")
""", extra_env={"HOROVOD_TIMELINE": str(tl),
                "HOROVOD_TIMELINE_MARK_CYCLES": "1"})
    events = json.loads(tl.read_text())
    names = {e.get("args", {}).get("name") for e in events if e.get("ph") == "M"}
    assert {"t0", "t1", "t2", "g0"} <= names
    phases = {e["name"] for e in events if e.get("ph") == "B"}
    assert any(p.startswith("NEGOTIATE_ALLREDUCE") for p in phases)
    assert "ALLREDUCE" in phases and "ALLGATHER" in phases
    assert any(e.get("name") == "CYCLE" for e in events)


def test_init_failure_is_retryable(monkeypatch):
    """A failed init (bad rendezvous) must not brick the process."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.core import state as state_mod

    state_mod.reset_global_state()
    monkeypatch.setenv("HOROVOD_SIZE", "2")
    monkeypatch.setenv("HOROVOD_RANK", "0")
    monkeypatch.delenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", raising=False)
    import horovod_tpu.frameworks.jax.basics as basics

    with pytest.raises(HorovodInternalError):
        basics.init()
    assert not basics.is_initialized()
    # retry as a single-process job succeeds
    monkeypatch.setenv("HOROVOD_SIZE", "1")
    basics.init()
    assert basics.is_initialized()
    import horovod_tpu.frameworks.jax.ops as ops

    out = ops.allreduce(np.arange(4.0, dtype=np.float32), name="retry_ok")
    np.testing.assert_allclose(out, np.arange(4.0))
    state_mod.global_state().shutdown()
    state_mod.reset_global_state()


def test_failed_enqueue_releases_handle():
    from horovod_tpu.common.exceptions import DuplicateNameError
    from horovod_tpu.core import state as state_mod

    state_mod.reset_global_state()
    os.environ.pop("HOROVOD_SIZE", None)
    import horovod_tpu.frameworks.jax.basics as basics
    import horovod_tpu.frameworks.jax.ops as ops

    basics.init()
    try:
        # block completion by never cycling? size=1 completes fast; use a
        # name collision window instead: enqueue two with same name quickly.
        before = len(ops._handles._events)
        h = ops.allreduce_async(np.ones(4, np.float32), name="leak_check")
        try:
            while True:
                ops.allreduce_async(np.ones(4, np.float32), name="leak_check")
        except DuplicateNameError:
            pass
        except Exception:
            pass  # completed before the second enqueue — fine either way
        ops.synchronize(h)
        # no leaked events beyond the in-flight ones we resolved
        assert len(ops._handles._events) <= before + 1
    finally:
        state_mod.global_state().shutdown()
        state_mod.reset_global_state()


def test_broadcast_with_joined_rank_errors():
    run_distributed(2, """
from horovod_tpu.common.exceptions import HorovodInternalError
if rank == 1:
    hvd.join()
else:
    try:
        hvd.broadcast(np.ones(4, np.float32), root_rank=0, name="bc_join")
        raise SystemExit("expected HorovodInternalError")
    except HorovodInternalError as e:
        assert "joined" in str(e).lower(), str(e)
    hvd.join()
""")


def test_stall_shutdown_aborts_job():
    """HOROVOD_STALL_SHUTDOWN_TIME_SECONDS must hard-abort instead of
    hanging forever when a rank never submits (reference
    stall_inspector.h:77-80; the flag was previously parsed but dead)."""
    out = run_distributed(2, """
import time
from horovod_tpu.common.exceptions import HorovodInternalError
if rank == 0:
    try:
        hvd.allreduce(np.ones(4), op=hvd.Sum, name="never")
        print("STALL_NOT_DETECTED", flush=True)
    except HorovodInternalError as e:
        assert "stall shutdown" in str(e), e
        print("STALL_ABORT_OK", flush=True)
else:
    time.sleep(30)   # never submits 'never'
print("DONE", rank, flush=True)
""", timeout=120, expect_failure=True,
                          extra_env={
                              "HOROVOD_STALL_CHECK_TIME_SECONDS": "1",
                              "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "3",
                          })
    assert "STALL_ABORT_OK" in out[0], out[0]


def test_duplicate_inflight_name_raises_to_caller_table_path():
    """A second enqueue of an in-flight name must surface
    DuplicateNameError to the CALLER (reference delivers
    DUPLICATE_NAME_ERROR to the callback, common.h:164-167) — here on the
    cold table path, where the first negotiation is still pending because
    the peer has not submitted yet."""
    out = run_distributed(2, """
import time
from horovod_tpu.common.exceptions import DuplicateNameError
from horovod_tpu.frameworks.jax import ops

if rank == 0:
    h = ops.allreduce_async(np.ones(4, np.float32), op=hvd.Sum, name="dup")
    try:
        ops.allreduce_async(np.ones(4, np.float32), op=hvd.Sum, name="dup")
        print("DUP_NOT_RAISED", flush=True)
    except DuplicateNameError:
        print("DUP_TABLE_OK", flush=True)
    out = ops.synchronize(h)       # first op still completes cleanly
    assert np.allclose(np.asarray(out), 2.0), out
else:
    time.sleep(2)                  # keep rank 0's first op in flight
    out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="dup")
    assert np.allclose(np.asarray(out), 2.0), out
print("DONE", rank, flush=True)
""", timeout=180)
    assert "DUP_TABLE_OK" in out[0], out[0]
    assert "DUP_NOT_RAISED" not in out[0]


def test_duplicate_inflight_name_raises_to_caller_mask_path():
    """Same contract on the steady-state mask fast path: after enough
    rounds for the name's negotiation to ride cache bits, a resubmission
    racing the in-flight op must still raise to the caller — and the
    runtime must keep working for that name afterwards."""
    out = run_distributed(2, """
import time
from horovod_tpu.common.exceptions import DuplicateNameError
from horovod_tpu.frameworks.jax import ops

for _ in range(6):                 # reach the cache/mask fast path
    hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="dup")

if rank == 0:
    h = ops.allreduce_async(np.ones(4, np.float32), op=hvd.Sum, name="dup")
    try:
        ops.allreduce_async(np.ones(4, np.float32), op=hvd.Sum, name="dup")
        print("DUP_NOT_RAISED", flush=True)
    except DuplicateNameError:
        print("DUP_MASK_OK", flush=True)
    out = ops.synchronize(h)
    assert np.allclose(np.asarray(out), 2.0), out
else:
    time.sleep(2)
    out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="dup")
    assert np.allclose(np.asarray(out), 2.0), out

# the name stays usable after the rejected resubmission
final = hvd.allreduce(np.full(4, float(rank), np.float32), op=hvd.Sum,
                      name="dup")
assert np.allclose(np.asarray(final), 1.0), final
print("DONE", rank, flush=True)
""", timeout=180)
    assert "DUP_MASK_OK" in out[0], out[0]
    assert "DUP_NOT_RAISED" not in out[0]
