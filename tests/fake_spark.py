"""A minimal SparkContext stand-in for testing horovod_tpu.spark.

Implements only the surface ``spark.run`` touches —
``parallelize(seq, n).mapPartitionsWithIndex(f).collect()`` plus
``defaultParallelism`` — executing every partition CONCURRENTLY in its own
spawned subprocess, like real Spark executors (hvd.init must see isolated
processes)."""

from __future__ import annotations

import multiprocessing as mp
from typing import Any, Callable, List

import cloudpickle

_ctx = mp.get_context("spawn")


def _part_runner(conn, blob):
    f, index, chunk = cloudpickle.loads(blob)
    try:
        out = ("ok", list(f(index, iter(chunk))))
    except BaseException as e:  # noqa: BLE001 — marshalled to driver
        out = ("err", repr(e))
    conn.send_bytes(cloudpickle.dumps(out))
    conn.close()


class FakeRDD:
    def __init__(self, chunks: List[list]):
        self._chunks = chunks
        self._fn = None

    def mapPartitionsWithIndex(self, f: Callable) -> "FakeRDD":
        rdd = FakeRDD(self._chunks)
        rdd._fn = f
        return rdd

    def collect(self) -> List[Any]:
        assert self._fn is not None
        procs = []
        for i, chunk in enumerate(self._chunks):
            parent, child = _ctx.Pipe()
            p = _ctx.Process(
                target=_part_runner,
                args=(child, cloudpickle.dumps((self._fn, i, chunk))),
                daemon=True)
            p.start()
            child.close()
            procs.append((p, parent))
        results: List[Any] = []
        for p, parent in procs:
            status, value = cloudpickle.loads(parent.recv_bytes())
            p.join(timeout=30)
            if status != "ok":
                raise RuntimeError(f"spark task failed: {value}")
            results.extend(value)
        return results


class FakeSparkContext:
    def __init__(self, default_parallelism: int = 2):
        self.defaultParallelism = default_parallelism

    def parallelize(self, seq, numSlices: int) -> FakeRDD:
        data = list(seq)
        chunks = [[] for _ in range(numSlices)]
        for i, item in enumerate(data):
            chunks[i % numSlices].append(item)
        return FakeRDD(chunks)


class FakeDataFrame:
    """DataFrame stand-in for the Store-partitioned plane: rows (dicts)
    pre-chunked into partitions; ``.rdd.mapPartitionsWithIndex`` runs each
    partition in its own spawned subprocess like FakeRDD."""

    def __init__(self, rows: List[dict], num_partitions: int = 2):
        self._rows = list(rows)
        self._n = num_partitions

    @property
    def rdd(self) -> FakeRDD:
        chunks = [self._rows[i::self._n] for i in range(self._n)]
        return FakeRDD(chunks)

    def count(self) -> int:
        return len(self._rows)

    def select(self, *cols: str) -> "FakeDataFrame":
        return FakeDataFrame([{c: r[c] for c in cols} for r in self._rows],
                             num_partitions=self._n)

    def collect(self) -> List[dict]:
        return list(self._rows)
