"""Fused conv1x1+BN-stats kernel: numerics vs the unfused composition.

The pallas kernel (`horovod_tpu/kernels/conv_bn_stats.py`) targets the
measured ResNet-50 plateau (docs/perf_r4.md §5: BN statistics re-read
every activation).  On this CPU rig it runs in interpret mode; the
contract pinned here — values, statistics, gradients, and module output
equal to flax's Conv+BatchNorm — is tile-size independent, so the
compiled TPU path computes the same thing (benchmarks/resnet_levers.py
measures its speed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.kernels import FusedConv1x1BN, matmul_bn_stats


def _ref(x, w):
    y = jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    return y, jnp.sum(y, axis=0), jnp.sum(y * y, axis=0)


@pytest.mark.smoke
@pytest.mark.parametrize("m,k,n", [
    (64, 32, 48),        # everything unaligned -> padding on all axes
    (256, 256, 256),     # exact single/multi blocks
    (300, 130, 70),      # ragged
])
def test_matmul_stats_matches_reference(m, k, n):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n), jnp.float32)
    y, s1, s2 = matmul_bn_stats(x, w, 128, 128, 128, True)
    yr, s1r, s2r = _ref(x, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                               rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                               rtol=1e-5, atol=1e-2)


@pytest.mark.smoke
def test_matmul_stats_bf16_inputs():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(128, 64), jnp.bfloat16)
    w = jnp.asarray(rng.randn(64, 96), jnp.bfloat16)
    y, s1, s2 = matmul_bn_stats(x, w, 128, 128, 128, True)
    assert y.dtype == jnp.bfloat16
    assert s1.dtype == s2.dtype == jnp.float32
    yr = jnp.dot(x, w, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(yr),
                               rtol=2e-2, atol=2e-1)
    # stats accumulate in f32 from the f32 accumulator tile
    np.testing.assert_allclose(np.asarray(s1), np.asarray(jnp.sum(yr, 0)),
                               rtol=2e-2, atol=2.0)


@pytest.mark.smoke
def test_matmul_stats_gradients_match():
    """The custom VJP must equal autodiff of the unfused composition for
    a loss that touches y, s1, AND s2 (the BN-shaped dependency)."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(96, 40), jnp.float32)
    w = jnp.asarray(rng.randn(40, 24), jnp.float32)

    def loss_fused(x, w):
        y, s1, s2 = matmul_bn_stats(x, w, 128, 128, 128, True)
        mean = s1 / y.shape[0]
        var = s2 / y.shape[0] - mean * mean
        return jnp.sum((y - mean) * jax.lax.rsqrt(var + 1e-5)) \
            + 0.1 * jnp.sum(s2)

    def loss_ref(x, w):
        y, s1, s2 = _ref(x, w)
        mean = s1 / y.shape[0]
        var = s2 / y.shape[0] - mean * mean
        return jnp.sum((y - mean) * jax.lax.rsqrt(var + 1e-5)) \
            + 0.1 * jnp.sum(s2)

    gf = jax.grad(loss_fused, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-3)


def _flax_pair(features, strides, use_running_average):
    import flax.linen as nn

    class Pair(nn.Module):
        @nn.compact
        def __call__(self, x):
            y = nn.Conv(features, (1, 1), strides, use_bias=False,
                        dtype=jnp.float32, param_dtype=jnp.float32)(x)
            return nn.BatchNorm(
                use_running_average=use_running_average, momentum=0.9,
                epsilon=1e-5, dtype=jnp.float32,
                param_dtype=jnp.float32)(y)

    return Pair()


@pytest.mark.smoke
@pytest.mark.parametrize("strides", [(1, 1), (2, 2)])
def test_fused_module_matches_flax_conv_bn_train(strides):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 8, 8, 16), jnp.float32)
    fused = FusedConv1x1BN(features=24, strides=strides, dtype=jnp.float32)
    fv = fused.init(jax.random.PRNGKey(0), x)
    ref = _flax_pair(24, strides, use_running_average=False)
    rv = ref.init(jax.random.PRNGKey(0), x)
    # share the conv kernel + BN affine params
    kernel = np.asarray(rng.randn(16, 24), np.float32) * 0.2
    fparams = {"params": {"kernel": jnp.asarray(kernel),
                          "scale": fv["params"]["scale"],
                          "bias": fv["params"]["bias"]},
               "batch_stats": fv["batch_stats"]}
    rparams = {"params": {"Conv_0": {"kernel": jnp.asarray(
                              kernel[None, None])},
                          "BatchNorm_0": {
                              "scale": fv["params"]["scale"],
                              "bias": fv["params"]["bias"]}},
               "batch_stats": rv["batch_stats"]}
    out_f, mut_f = fused.apply(fparams, x, mutable=["batch_stats"])
    out_r, mut_r = ref.apply(rparams, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                               rtol=1e-4, atol=1e-4)
    for key in ("mean", "var"):
        f = np.asarray(jax.tree_util.tree_leaves(
            {k: v for k, v in mut_f["batch_stats"].items() if key in str(k)}
            or [mut_f["batch_stats"]["mean" if key == "mean" else "var"]])[0])
        r = np.asarray([v for path, v in
                        jax.tree_util.tree_flatten_with_path(
                            mut_r["batch_stats"])[0]
                        if key in jax.tree_util.keystr(path)][0])
        np.testing.assert_allclose(f, r, rtol=1e-4, atol=1e-4,
                                   err_msg=f"running {key} diverged")


@pytest.mark.smoke
def test_fused_module_eval_uses_running_stats():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 4, 4, 8), jnp.float32)
    mod_t = FusedConv1x1BN(features=8, dtype=jnp.float32)
    variables = mod_t.init(jax.random.PRNGKey(0), x)
    mod_e = FusedConv1x1BN(features=8, dtype=jnp.float32,
                           use_running_average=True)
    out = mod_e.apply(variables, x)
    # fresh init: mean 0 / var 1 -> eval output == scale*y + bias == y
    y = jnp.dot(x.reshape(-1, 8), variables["params"]["kernel"])
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, 8),
        np.asarray(y) / np.sqrt(1 + 1e-5), rtol=1e-4, atol=1e-4)


def test_resnet_bottleneck_with_fused_bn_trains():
    """ResNet (bottleneck) with fuse_conv1x1_bn=True: init, one
    value_and_grad step, finite loss/grads, batch_stats updated — the
    integration the levers bench measures on real TPU."""
    import optax

    from horovod_tpu.models.resnet import BottleneckBlock, ResNet

    model = ResNet(stage_sizes=[1, 1], block_cls=BottleneckBlock,
                   num_classes=10, num_filters=8, dtype=jnp.float32,
                   fuse_conv1x1_bn=True)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    y = jnp.asarray([1, 2], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    param_paths = [jax.tree_util.keystr(p) for p, _ in
                   jax.tree_util.tree_flatten_with_path(
                       variables["params"])[0]]
    assert any("FusedConv1x1BN" in p or "fused_proj" in p
               for p in param_paths), param_paths[:10]

    def loss_fn(params):
        logits, mut = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, mut

    (loss, mut), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        variables["params"])
    assert np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert flat and all(np.isfinite(np.asarray(g)).all() for g in flat)
    # running stats moved off their init values
    ms = [np.asarray(v) for path, v in
          jax.tree_util.tree_flatten_with_path(mut["batch_stats"])[0]
          if "mean" in jax.tree_util.keystr(path)]
    assert any(np.abs(m).max() > 0 for m in ms), "running means never updated"
    # eval path (running stats, plain matmul) also runs
    logits_eval = model.apply(
        {"params": variables["params"],
         "batch_stats": mut["batch_stats"]}, x, train=False)
    assert np.isfinite(np.asarray(logits_eval)).all()


@pytest.mark.smoke
def test_fused_flag_rejects_other_bn_levers():
    """fuse_conv1x1_bn is hardwired to fp32 one-pass stats; combining it
    with the other BN levers must raise, not silently mix algorithms."""
    from horovod_tpu.models.resnet import BottleneckBlock, ResNet

    for kw in ({"bn_f32_stats": False}, {"bn_fast_variance": False}):
        model = ResNet(stage_sizes=[1], block_cls=BottleneckBlock,
                       num_classes=4, num_filters=8, dtype=jnp.float32,
                       fuse_conv1x1_bn=True, **kw)
        with pytest.raises(ValueError, match="fuse_conv1x1_bn"):
            model.init(jax.random.PRNGKey(0),
                       jnp.ones((1, 16, 16, 3), jnp.float32), train=True)


def test_sharded_kernel_matches_single_device():
    """shard_map flavor on the 8-device virtual mesh: per-shard kernels +
    psum'd statistics must equal the single-device kernel (values AND the
    gradient through a BN-shaped loss) — the multi-chip integration that
    plain pallas_call cannot get from GSPMD."""
    from horovod_tpu.kernels import sharded_matmul_bn_stats
    from horovod_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=8))
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(16 * 8, 32), jnp.float32)   # 16 rows/shard
    w = jnp.asarray(rng.randn(32, 24), jnp.float32)

    def loss_sharded(x, w):
        y, s1, s2 = sharded_matmul_bn_stats(x, w, mesh)
        mean = s1 / y.shape[0]
        var = s2 / y.shape[0] - mean * mean
        return jnp.sum((y - mean) * jax.lax.rsqrt(var + 1e-5))

    def loss_single(x, w):
        y, s1, s2 = matmul_bn_stats(x, w, 128, 128, 128, True)
        mean = s1 / y.shape[0]
        var = s2 / y.shape[0] - mean * mean
        return jnp.sum((y - mean) * jax.lax.rsqrt(var + 1e-5))

    ys, s1s, s2s = sharded_matmul_bn_stats(x, w, mesh)
    yr, s1r, s2r = matmul_bn_stats(x, w, 128, 128, 128, True)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yr),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s1s), np.asarray(s1r),
                               rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2s), np.asarray(s2r),
                               rtol=1e-5, atol=1e-2)
    gs = jax.grad(loss_sharded, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_single, argnums=(0, 1))(x, w)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-3)


def test_fused_resnet_trains_on_sharded_mesh():
    """ResNet(fuse_conv1x1_bn=True, fused_bn_mesh=mesh) under the real
    sharded train step on the 8-device virtual mesh: compiles, executes,
    finite loss — the configuration a multi-chip TPU bench would run."""
    import optax

    from horovod_tpu.models.resnet import BottleneckBlock, ResNet
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    mesh = build_mesh(MeshSpec(data=8))
    model = ResNet(stage_sizes=[1], block_cls=BottleneckBlock,
                   num_classes=10, num_filters=8, dtype=jnp.float32,
                   fuse_conv1x1_bn=True, fused_bn_mesh=mesh)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, size=(8,)), jnp.int32)
    tx = optax.sgd(0.1)
    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=False)
    batch = shard_batch(mesh, {"x": x, "y": y})
    state, loss = step(state, batch)
    assert np.isfinite(float(loss)), loss
