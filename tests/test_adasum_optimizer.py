"""Adasum delta-space optimizers (jax/optax + torch) vs the closed-form
operator.

Reference math (``adasum.h:194-450``): for two contributions a, b,

    a' = (1 − a·b / (2‖a‖²))·a + (1 − a·b / (2‖b‖²))·b

The delta optimizers apply this to parameter DELTAS (local optimizer step
results), not gradients (reference ``tensorflow/__init__.py:368-462``,
``torch/optimizer.py:210-379``).
"""

from __future__ import annotations

import textwrap

import numpy as np
import pytest

from tests.helpers import run_distributed


def adasum_combine(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    dot = float(np.dot(a.ravel(), b.ravel()))
    na = float(np.dot(a.ravel(), a.ravel()))
    nb = float(np.dot(b.ravel(), b.ravel()))
    ca = 1.0 - dot / (2 * na) if na else 1.0
    cb = 1.0 - dot / (2 * nb) if nb else 1.0
    return ca * a + cb * b


def test_jax_adasum_delta_two_ranks():
    """SGD deltas are −lr·g per rank; the merged update must equal the
    closed-form Adasum combine of the two deltas."""
    body = textwrap.dedent("""
    import jax.numpy as jnp
    import optax
    from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer

    lr = 0.5
    tx = optax.sgd(lr)
    dopt = DistributedOptimizer(tx, op="adasum")
    params = {"w": jnp.array([1.0, 2.0, 3.0])}
    st = dopt.init(params)
    grads = {"w": jnp.array([1.0, 0.5, -1.0]) * (rank + 1)}
    updates, st = dopt.update(grads, st, params)

    # expected: adasum_combine(-lr*g0, -lr*g1)
    g0 = np.array([1.0, 0.5, -1.0]); g1 = 2 * g0
    a, b = -lr * g0, -lr * g1
    dot = a @ b
    exp = (1 - dot/(2*(a@a)))*a + (1 - dot/(2*(b@b)))*b
    got = np.asarray(updates["w"])
    assert np.allclose(got, exp, atol=1e-5), (got, exp)
    print("JAX_ADASUM_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "JAX_ADASUM_OK" in out


def test_torch_adasum_delta_two_ranks():
    pytest.importorskip("torch")
    body = textwrap.dedent("""
    import torch
    import horovod_tpu.torch as hvdt

    lr = 0.5
    w0 = torch.tensor([1.0, 2.0, 3.0])
    p = torch.nn.Parameter(w0.clone())
    opt = torch.optim.SGD([p], lr=lr)
    dopt = hvdt.DistributedOptimizer(opt, op=hvdt.Adasum)

    g = torch.tensor([1.0, 0.5, -1.0]) * (rank + 1)
    p.grad = g.clone()
    dopt.step()

    g0 = np.array([1.0, 0.5, -1.0]); g1 = 2 * g0
    a, b = -lr * g0, -lr * g1
    dot = a @ b
    exp_delta = (1 - dot/(2*(a@a)))*a + (1 - dot/(2*(b@b)))*b
    exp = np.array([1.0, 2.0, 3.0]) + exp_delta
    assert np.allclose(p.detach().numpy(), exp, atol=1e-5), \\
        (p.detach().numpy(), exp)
    print("TORCH_ADASUM_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "TORCH_ADASUM_OK" in out


def test_torch_adasum_momentum_delta():
    """Momentum makes the local delta ≠ −lr·g; the operator must combine
    the ACTUAL deltas (catches gradient-space implementations)."""
    pytest.importorskip("torch")
    body = textwrap.dedent("""
    import torch
    import horovod_tpu.torch as hvdt

    lr, mom = 0.1, 0.9
    p = torch.nn.Parameter(torch.tensor([2.0, -1.0]))
    opt = torch.optim.SGD([p], lr=lr, momentum=mom)
    dopt = hvdt.DistributedOptimizer(opt, op=hvdt.Adasum)

    def ref_delta(g, buf):
        buf = mom * buf + g
        return -lr * buf, buf

    g_mine = np.array([1.0, 1.0]) * (rank + 1)
    bufs = [np.zeros(2), np.zeros(2)]
    deltas = []
    for r in range(2):
        d, bufs[r] = ref_delta(np.array([1.0, 1.0]) * (r + 1), bufs[r])
        deltas.append(d)
    a, b = deltas
    dot = a @ b
    exp_delta = (1 - dot/(2*(a@a)))*a + (1 - dot/(2*(b@b)))*b

    p.grad = torch.tensor(g_mine, dtype=torch.float32)
    dopt.step()
    exp = np.array([2.0, -1.0]) + exp_delta
    assert np.allclose(p.detach().numpy(), exp, atol=1e-5), \\
        (p.detach().numpy(), exp)
    print("TORCH_ADASUM_MOM_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "TORCH_ADASUM_MOM_OK" in out


def test_adasum_identical_deltas_idempotent():
    """Adasum of two identical contributions is their mean — so identical
    ranks behave exactly like single-process training."""
    body = textwrap.dedent("""
    import jax.numpy as jnp
    import optax
    from horovod_tpu.frameworks.jax.optimizer import DistributedAdasumOptimizer

    tx = optax.sgd(0.25)
    dopt = DistributedAdasumOptimizer(tx)
    params = {"w": jnp.array([4.0, -2.0])}
    st = dopt.init(params)
    grads = {"w": jnp.array([1.0, 3.0])}
    updates, st = dopt.update(grads, st, params)
    # identical a == b: a' = (1-1/2)a + (1-1/2)b = a
    assert np.allclose(np.asarray(updates["w"]), -0.25 * np.array([1.0, 3.0]),
                       atol=1e-6)
    print("ADASUM_IDEM_OK", rank)
    """)
    for out in run_distributed(2, body, timeout=180):
        assert "ADASUM_IDEM_OK" in out
