"""Simulated-cluster harness (horovod_tpu/sim/, docs/sim_cluster.md):
determinism of the shaped wire + churn schedule, and an end-to-end churn
run through the REAL driver and journaled server at small np.  The
bounded np=128 "large mesh" run lives in ci/chaos.sh (with
HOROVOD_LOCK_DEBUG=1 and a zero-lock-cycle assertion).
"""

import json

import pytest

from horovod_tpu.sim.cluster import COORDINATED_ABORT, SimCluster
from horovod_tpu.sim.wire import OP_OVERHEAD_BYTES, ShapedStore, ShapedWire


# ---------------------------------------------------------------------------
# shaped wire


def test_wire_jitter_stream_is_deterministic_per_link():
    a = ShapedWire("h000", seed=7, latency_s=0.001, jitter_s=0.0005,
                   bandwidth_bps=1e9)
    b = ShapedWire("h000", seed=7, latency_s=0.001, jitter_s=0.0005,
                   bandwidth_bps=1e9)
    other_link = ShapedWire("h001", seed=7, latency_s=0.001,
                            jitter_s=0.0005, bandwidth_bps=1e9)
    seq_a = [a.delay(1024) for _ in range(8)]
    seq_b = [b.delay(1024) for _ in range(8)]
    assert seq_a == seq_b
    assert seq_a != [other_link.delay(1024) for _ in range(8)]
    # preview() is a pure function: it never consumes the live stream.
    assert a.preview(1024, 8) == b.preview(1024, 8)
    assert [round(v, 9) for v in seq_a] != a.preview(1024, 8) or \
        seq_a == seq_b  # previews restart the stream from the beginning


def test_shaped_store_charges_batch_once(monkeypatch):
    """N ops through ``batch`` cost ONE latency term; the same N ops
    per-op cost N — the asymmetry the batching A/B measures."""
    from horovod_tpu.transport.store import MemoryStore

    sleeps = []
    monkeypatch.setattr("horovod_tpu.sim.wire.time.sleep",
                        lambda s: sleeps.append(s))
    wire = ShapedWire("link", seed=0, latency_s=0.010, jitter_s=0.0,
                      bandwidth_bps=1e9)
    store = ShapedStore(MemoryStore(), wire)
    ops = [("set", "s", f"k{i}", b"v") for i in range(10)]
    assert store.batch(ops) == [True] * 10
    assert len(sleeps) == 1
    batched_cost = sleeps[0]
    sleeps.clear()
    for _, scope, key, value in ops:
        store.set(scope, key, value)
    assert len(sleeps) == 10
    assert sum(sleeps) > 5 * batched_cost  # latency paid 10x, not 1x
    assert wire.injected_s == pytest.approx(batched_cost + sum(sleeps))
    assert store.get("s", "k0") == b"v"
    # Byte model sanity: bigger payloads cost more on a finite link.
    slow = ShapedWire("slow", seed=0, latency_s=0.0, jitter_s=0.0,
                      bandwidth_bps=1e6)
    assert slow.delay(10 * OP_OVERHEAD_BYTES) > slow.delay(1)


# ---------------------------------------------------------------------------
# schedule + digest determinism (the artifact's reproducibility witness)


def test_sim_schedule_and_digest_deterministic_under_seed():
    a = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    b = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    other = SimCluster(64, slots_per_host=8, seed=43, trace=False)
    assert a.schedule(6) == b.schedule(6)
    assert a.determinism_digest(6) == b.determinism_digest(6)
    assert a.determinism_digest(6) != other.determinism_digest(6)
    # The last event is always the coordinated abort.
    assert a.schedule(6)[-1] == (COORDINATED_ABORT, None)
    # Victims come from the static slot layout.
    for kind, victim in a.schedule(6)[:-1]:
        assert victim in a.identities


# ---------------------------------------------------------------------------
# end-to-end churn at small np (tier-1 sized; np=128 rides ci/chaos.sh)


def test_sim_churn_epochs_and_coordinated_abort_np16(monkeypatch):
    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    cluster = SimCluster(16, slots_per_host=8, seed=7, lease_timeout=1.0,
                         renew_period=0.2)
    rec = cluster.run(events=3)
    assert rec["np"] == 16 and rec["hosts"] == 2
    # Every scheduled event advanced exactly one epoch, abort included.
    assert rec["final_epoch"] == 3
    assert [e["epoch"] for e in rec["events"]] == [1, 2, 3]
    assert rec["events"][-1]["kind"] == COORDINATED_ABORT
    # The run produced the same attribution document a live run would,
    # at the required coverage floor.
    attr = rec["attribution"]
    assert attr["coverage"] >= 0.90, attr
    assert attr["phase_share"]["http_roundtrip"] > 0.0
    assert rec["sim_wire_delay_s"] > 0.0
    assert rec["journal_bytes"] > 0
    assert rec["determinism"]["digest"] == \
        SimCluster(16, slots_per_host=8, seed=7,
                   trace=False).determinism_digest(3)
    json.dumps(rec)  # artifact must be JSON-serializable as-is


# ---------------------------------------------------------------------------
# self-healing demotion lane (docs/elastic.md "self-healing demotion")


def test_sim_demotion_schedule_and_digest_deterministic():
    a = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    b = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    other = SimCluster(64, slots_per_host=8, seed=43, trace=False)
    assert a.demotion_schedule(3) == b.demotion_schedule(3)
    assert a.demotion_digest(3) == b.demotion_digest(3)
    assert a.demotion_digest(3) != other.demotion_digest(3)
    plan = a.demotion_schedule(3)
    # Distinct victims, never the coordinator's host.
    assert len(set(plan)) == 3
    assert a.hostnames[0] not in plan
    # The demotion lane shares nothing with the churn schedule: asking
    # for it must not perturb churn digests for the same seed.
    assert a.determinism_digest(6) == \
        SimCluster(64, slots_per_host=8, seed=42,
                   trace=False).determinism_digest(6)
    with pytest.raises(ValueError):
        a.demotion_schedule(len(a.hostnames))


def test_sim_demotion_np16(monkeypatch):
    """A demotion report through the REAL driver at np=16: blacklist,
    epoch advance attributed to cause=demotion, and the flag->first-round
    latency curve — the np=128 artifact run rides ci/chaos.sh."""
    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    cluster = SimCluster(16, slots_per_host=8, seed=7, lease_timeout=1.0,
                         renew_period=0.2)
    rec = cluster.run_demotion(demotions=1)
    assert rec["metric"] == "sim_demotion"
    assert rec["np"] == 16 and rec["hosts"] == 2
    # One shed host of 8 slots: the capacity floor self-lowered to 8.
    assert rec["min_np"] == 8
    assert rec["final_epoch"] == 1
    assert rec["driver_demotion_transitions"] == 1
    (event,) = rec["events"]
    assert event["victim_host"] == rec["determinism"]["schedule"][0]
    assert 0 < event["flag_to_epoch_ms"] <= event["flag_to_first_round_ms"]
    assert rec["attribution"]["coverage"] >= 0.90, rec["attribution"]
    assert rec["determinism"]["digest"] == SimCluster(
        16, slots_per_host=8, seed=7, trace=False,
        min_np=rec["min_np"]).demotion_digest(1)
    json.dumps(rec)  # artifact must be JSON-serializable as-is


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_sim_demotion_np128_artifact(monkeypatch):
    """Scale proof + the committed artifact's non-fabrication witness:
    generates ``benchmarks/results/sim_demotion_np128.json`` through the
    real driver at np=128 and asserts every claim the artifact makes —
    the digest reproduces from a fresh same-seed cluster, every scheduled
    demotion became a cause=demotion driver transition, and attribution
    coverage holds the 0.90 floor.  Run by ci/chaos.sh."""
    import os

    from .helpers import REPO_ROOT

    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    cluster = SimCluster(128, slots_per_host=8, seed=42,
                         lease_timeout=1.5, renew_period=0.25)
    rec = cluster.run_demotion(demotions=3)
    assert rec["np"] == 128 and rec["hosts"] == 16
    assert rec["final_epoch"] == 3
    assert rec["driver_demotion_transitions"] == 3
    assert [e["victim_host"] for e in rec["events"]] == \
        rec["determinism"]["schedule"]
    for e in rec["events"]:
        assert 0 < e["flag_to_epoch_ms"] <= e["flag_to_first_round_ms"]
    assert rec["attribution"]["coverage"] >= 0.90, rec["attribution"]
    # Non-fabrication: the digest is a pure function of (seed, topology,
    # capacity floor, wire shaping) — a hand-edited artifact cannot
    # produce it without re-running the harness.
    assert rec["determinism"]["digest"] == SimCluster(
        128, slots_per_host=8, seed=42, trace=False,
        min_np=rec["min_np"]).demotion_digest(3)
    out = os.path.join(REPO_ROOT, "benchmarks", "results",
                       "sim_demotion_np128.json")
    with open(out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    with open(out) as f:
        assert json.loads(f.read()) == rec

# ---------------------------------------------------------------------------
# zero-restart reshard lane (docs/elastic.md "Live resharding")


def test_sim_reshard_schedule_and_digest_deterministic():
    a = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    b = SimCluster(64, slots_per_host=8, seed=42, trace=False)
    other = SimCluster(64, slots_per_host=8, seed=43, trace=False)
    assert a.reshard_schedule(4) == b.reshard_schedule(4)
    assert a.reshard_digest(4) == b.reshard_digest(4)
    assert a.reshard_digest(4) != other.reshard_digest(4)
    # The reshard lane shares nothing with the churn or demotion
    # schedules: asking for it must not perturb their digests.
    assert a.determinism_digest(6) == \
        SimCluster(64, slots_per_host=8, seed=42,
                   trace=False).determinism_digest(6)
    assert a.demotion_digest(3) == \
        SimCluster(64, slots_per_host=8, seed=42,
                   trace=False).demotion_digest(3)


def test_sim_reshard_np16(monkeypatch):
    """A preemption kill through the REAL driver at np=16: lease expiry,
    reshard-marked publish, survivor acks, commit record, cause=reshard
    transition, zero fallbacks — the np=512 artifact run is the same
    runner via ``python -m horovod_tpu.sim --reshards``."""
    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    monkeypatch.delenv("HOROVOD_RESHARD", raising=False)
    cluster = SimCluster(16, slots_per_host=8, seed=7, lease_timeout=1.0,
                         renew_period=0.2)
    rec = cluster.run_reshard(kills=1)
    assert rec["metric"] == "sim_reshard"
    assert rec["np"] == 16 and rec["reshard_enabled"] is True
    assert rec["final_epoch"] == 1
    (event,) = rec["events"]
    assert event["marked"] is True
    assert event["victim"] == rec["determinism"]["schedule"][0]
    assert 0 < event["kill_to_epoch_ms"] <= event["kill_to_commit_ms"] \
        <= event["kill_to_first_round_ms"]
    assert rec["driver_reshard_transitions"] == 1
    assert rec["reshard_fallbacks"] == 0
    assert rec["attribution"]["coverage"] >= 0.90, rec["attribution"]
    assert rec["determinism"]["digest"] == SimCluster(
        16, slots_per_host=8, seed=7, trace=False).reshard_digest(1)
    json.dumps(rec)  # artifact must be JSON-serializable as-is


def test_sim_reshard_kill_switch_baseline_arm(monkeypatch):
    """HOROVOD_RESHARD=0 is the committed A/B's baseline arm: the same
    kill advances the epoch with NO marker, NO pending commit, and NO
    cause=reshard transition."""
    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    monkeypatch.setenv("HOROVOD_RESHARD", "0")
    cluster = SimCluster(16, slots_per_host=8, seed=7, lease_timeout=1.0,
                         renew_period=0.2, trace=False)
    rec = cluster.run_reshard(kills=1)
    assert rec["reshard_enabled"] is False
    assert rec["final_epoch"] == 1
    assert rec["events"][0]["marked"] is False
    assert rec["driver_reshard_transitions"] == 0
    assert rec["reshard_fallbacks"] == 0


def test_sim_reshard_respects_min_np_quorum_during_demotion(monkeypatch):
    """Reshard/demotion interplay regression: a demotion that lands the
    world exactly AT quorum advances (and, with resharding on, rides the
    reshard path as a pure shrink); churn that would take it BELOW
    ``min_np`` must park the driver at the capacity gate — the epoch
    holds and no reshard is ever armed for a sub-quorum world."""
    import time

    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    monkeypatch.delenv("HOROVOD_RESHARD", raising=False)
    cluster = SimCluster(4, slots_per_host=1, seed=7, lease_timeout=1.0,
                         renew_period=0.2, trace=False, min_np=3)
    assert cluster.min_np == 3
    cluster.start()
    try:
        for _ in range(2):
            cluster.renewal_round()
            time.sleep(cluster.renew_period)
        # Demotion to exactly min_np: allowed, and the advance is a
        # reshard-marked pure shrink (no joiners) that commits.
        target = cluster.driver.epoch + 1
        victim_host = cluster.hostnames[1]
        cluster.inject_demotion(victim_host)
        cluster.await_epoch(target, timeout=30.0)
        assert cluster.driver._reshard_pending is not None
        cluster.ack_round(cluster.driver.epoch)
        for w in cluster.workers.values():
            if w.hostname == victim_host:
                w.renewing = False
        cluster.await_reshard_commit(timeout=30.0)
        # Second demotion would leave 2 < min_np=3: the capacity gate
        # must hold the epoch and never arm a reshard.
        epoch_at_quorum = cluster.driver.epoch
        cluster.inject_demotion(cluster.hostnames[2])
        deadline = time.monotonic() + 4 * cluster.lease_timeout
        while time.monotonic() < deadline:
            cluster.renewal_round()
            cluster.driver._wakeup.set()
            time.sleep(cluster.renew_period)
        assert cluster.driver.epoch == epoch_at_quorum, \
            "driver advanced the epoch below min_np quorum"
        assert cluster.driver._reshard_pending is None, \
            "a reshard was armed for a sub-quorum world"
        assert not cluster.driver.finished()
    finally:
        cluster.stop()

# ---------------------------------------------------------------------------
# negotiation fan-in sim (horovod_tpu/sim/negotiation.py, docs/data_plane.md
# "Negotiation fan-in"): the REAL coordinator mask path at large np over an
# arithmetic wire clock — no processes, no sleeping.


def test_sim_negotiation_counters_and_bit_exactness():
    """np=64 smoke of every claim the big artifact makes: the real
    coordinator ingests O(ranks) star frames vs O(hosts) fan-in frames
    (counter-asserted against controller_ingress_frames_total's backing
    counter), the agreed mask is bit-identical across shapes, and the
    fabricated trace attributes >= 0.90 of every step."""
    from horovod_tpu.sim.negotiation import SimNegotiation

    rec = SimNegotiation(64, slots_per_host=8, seed=0).run(cycles=3)
    assert rec["star"]["ingress_frames_per_cycle"] == 63
    assert rec["fanin"]["ingress_frames_per_cycle"] == 7 + 7
    assert rec["star"]["reply_mask"] == rec["fanin"]["reply_mask"] != 0
    assert rec["fanin"]["cycle_ms_p50"] < rec["star"]["cycle_ms_p50"]
    for mode in ("star", "fanin"):
        assert rec["attribution"][mode]["coverage"] >= 0.90, \
            rec["attribution"]
    assert rec["attribution"]["fanin"]["fanin_share"] > 0


def test_sim_negotiation_digest_deterministic():
    from horovod_tpu.sim.negotiation import SimNegotiation

    a = SimNegotiation(128, slots_per_host=8, seed=3)
    b = SimNegotiation(128, slots_per_host=8, seed=3)
    other = SimNegotiation(128, slots_per_host=8, seed=4)
    assert a.determinism_digest() == b.determinism_digest()
    assert a.determinism_digest() != other.determinism_digest()


@pytest.mark.slow
def test_sim_negotiation_np4096_artifact():
    """Regenerates ``benchmarks/results/sim_negotiation_np4096.json``
    (the committed star-vs-tree latency curves, np=1024-4096) through
    the real coordinator and asserts every claim it makes — monotone
    ingress reduction, bit-identical masks at every size, attribution
    coverage >= 0.90, and digests that reproduce from fresh same-seed
    sims (the non-fabrication witness).  Run by ci/chaos.sh."""
    import os

    from horovod_tpu.sim.negotiation import SimNegotiation, run_curve

    from .helpers import REPO_ROOT

    rec = run_curve([1024, 2048, 4096], slots_per_host=8, seed=0,
                    cycles=6)
    assert [p["np"] for p in rec["curve"]] == [1024, 2048, 4096]
    for p in rec["curve"]:
        star, fanin = p["star"], p["fanin"]
        assert star["ingress_frames_per_cycle"] == p["np"] - 1
        assert fanin["ingress_frames_per_cycle"] == \
            (p["hosts"] - 1) + (p["slots_per_host"] - 1)
        assert star["reply_mask"] == fanin["reply_mask"] != 0
        assert p["cycle_speedup_p50"] > 2.0, p
        for mode in ("star", "fanin"):
            assert p["attribution"][mode]["coverage"] >= 0.90, \
                p["attribution"]
        # Non-fabrication: pure function of (seed, topology, shaping).
        assert rec["determinism"]["digests"][str(p["np"])] == \
            SimNegotiation(p["np"], slots_per_host=8,
                           seed=0).determinism_digest()
    out = os.path.join(REPO_ROOT, "benchmarks", "results",
                       "sim_negotiation_np4096.json")
    with open(out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    with open(out) as f:
        assert json.loads(f.read()) == rec
