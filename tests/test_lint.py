"""hvd-lint: per-rule fixtures + the zero-violation contract on the tree.

Every rule gets three fixtures — one violating, one clean, one suppressed
with a justification — so a rule that silently stops firing (or starts
over-firing) fails here, not in review.  The capstone test runs the full
pass over ``horovod_tpu/`` and asserts zero violations: landing a change
that breaks an invariant makes THIS file fail with the right rule code.
"""

from __future__ import annotations

import os
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from horovod_tpu.tools.lint import (  # noqa: E402
    Project,
    lint_paths,
    lint_source,
    main,
)
from horovod_tpu.tools.lint.rules import RULE_CODES  # noqa: E402

PKG = os.path.join(REPO_ROOT, "horovod_tpu")
PROJECT = Project(root=REPO_ROOT)


def run(src: str, path: str = "<fixture>"):
    return lint_source(textwrap.dedent(src), path=path, project=PROJECT)


def codes(violations):
    return sorted({v.code for v in violations})


@pytest.fixture(scope="module")
def tree_violations():
    """One full-tree pass shared by every test that needs it."""
    return lint_paths([PKG], PROJECT)


# ---------------------------------------------------------------------------
# HVD001 — blocking call while holding a lock
# ---------------------------------------------------------------------------

HVD001_WITH = """
    import threading, time
    lock = threading.Lock()
    def f():
        with lock:
            time.sleep(1)
"""

HVD001_ACQUIRE = """
    import time
    class C:
        def f(self):
            self._lock.acquire()
            try:
                data = self.sock.recv(4)
            finally:
                self._lock.release()
"""

HVD001_CLEAN = """
    import threading, time
    lock = threading.Lock()
    def f():
        with lock:
            x = 1
        time.sleep(1)
        done.wait(timeout=5)
"""

HVD001_SUPPRESSED = """
    import threading, time
    lock = threading.Lock()
    def f():
        with lock:
            time.sleep(1)  # hvdlint: disable=HVD001 -- fixture: bounded by test harness
"""


def test_hvd001_with_block():
    vs = run(HVD001_WITH)
    assert codes(vs) == ["HVD001"]
    assert "time.sleep" in vs[0].message


def test_hvd001_acquire_release_region():
    vs = run(HVD001_ACQUIRE)
    assert codes(vs) == ["HVD001"]
    assert "socket" in vs[0].message


def test_hvd001_clean():
    assert run(HVD001_CLEAN) == []


def test_hvd001_suppressed():
    assert run(HVD001_SUPPRESSED) == []


def test_hvd001_string_join_not_flagged():
    # str.join takes a positional iterable; thread joins take none.
    src = """
        import threading
        lock = threading.Lock()
        def f(parts, t):
            with lock:
                s = ",".join(parts)
            t.join()
    """
    assert run(src) == []


# ---------------------------------------------------------------------------
# HVD002 — raw HOROVOD_* env literal outside common/env.py
# ---------------------------------------------------------------------------

HVD002_VIOLATING = """
    import os
    a = os.environ.get("HOROVOD_FOO")
    b = os.getenv("HOROVOD_BAR", "1")
    os.environ["HOROVOD_BAZ"] = "x"
    c = env_mod.get_int("HOROVOD_QUX", 0)
"""

HVD002_CLEAN = """
    import os
    from horovod_tpu.common import env as env_mod
    a = env_mod.get_str(env_mod.HOROVOD_ELASTIC)
    b = os.environ.get(env_mod.HOROVOD_RANK)
    c = os.environ.get("NOT_A_KNOB")
"""

HVD002_SUPPRESSED = """
    import os
    a = os.environ.get("HOROVOD_FOO")  # hvdlint: disable=HVD002 -- fixture: pretend legacy shim
"""


def test_hvd002_violating():
    vs = run(HVD002_VIOLATING)
    assert codes(vs) == ["HVD002"]
    assert len(vs) == 4
    assert {"HOROVOD_FOO", "HOROVOD_BAR", "HOROVOD_BAZ", "HOROVOD_QUX"} == {
        v.message.split("'")[1] for v in vs}


def test_hvd002_clean():
    assert run(HVD002_CLEAN) == []


def test_hvd002_env_py_itself_exempt():
    path = os.path.join(PKG, "common", "env.py")
    assert run(HVD002_VIOLATING, path=path) == []


def test_hvd002_suppressed():
    assert run(HVD002_SUPPRESSED) == []


# ---------------------------------------------------------------------------
# HVD003 — fault sites
# ---------------------------------------------------------------------------

HVD003_VIOLATING = """
    from horovod_tpu.common import faults
    def f():
        if faults.ACTIVE:
            faults.inject("tcp.rcv")
"""

HVD003_CLEAN = """
    from horovod_tpu.common import faults
    def f():
        if faults.ACTIVE:
            faults.inject("tcp.recv", rank=0, peer=1)
"""

HVD003_SUPPRESSED = """
    from horovod_tpu.common import faults
    def f():
        faults.inject("tcp.rcv")  # hvdlint: disable=HVD003 -- fixture: deliberately bogus site
"""


def test_hvd003_registry_is_populated():
    # The rule is only as good as the registry parse; guard it.
    assert "tcp.recv" in PROJECT.fault_sites
    assert len(PROJECT.fault_sites) >= 6


def test_hvd003_unknown_site():
    vs = run(HVD003_VIOLATING)
    assert codes(vs) == ["HVD003"]
    assert "tcp.rcv" in vs[0].message


def test_hvd003_known_site():
    assert run(HVD003_CLEAN) == []


def test_hvd003_suppressed():
    assert run(HVD003_SUPPRESSED) == []


def test_hvd003_every_site_documented():
    doc_path = os.path.join(REPO_ROOT, "docs", "fault_injection.md")
    with open(doc_path, encoding="utf-8") as f:
        doc = f.read()
    for site in PROJECT.fault_sites:
        assert f"`{site}`" in doc, (
            f"fault site {site!r} missing from docs/fault_injection.md")


# ---------------------------------------------------------------------------
# HVD004 — swallowed exception in thread bodies
# ---------------------------------------------------------------------------

HVD004_VIOLATING = """
    import threading
    def _worker_loop():
        while True:
            try:
                step()
            except Exception:
                pass
    threading.Thread(target=_worker_loop, name="w", daemon=True).start()
"""

HVD004_CLEAN = """
    import threading
    def _worker_loop():
        while True:
            try:
                step()
            except Exception as e:
                log.error("worker died: %s", e)
    def _other_loop():
        try:
            step()
        except ValueError:
            pass  # narrow type: fine
    def not_a_thread_body():
        try:
            step()
        except Exception:
            pass  # broad, but not a thread body: HVD004 does not apply
"""

HVD004_SUPPRESSED = """
    def _worker_loop():
        try:
            step()
        except Exception:  # hvdlint: disable=HVD004 -- fixture: probe loop, errors expected
            pass
"""


def test_hvd004_violating():
    vs = run(HVD004_VIOLATING)
    assert codes(vs) == ["HVD004"]
    assert "_worker_loop" in vs[0].message


def test_hvd004_clean():
    assert run(HVD004_CLEAN) == []


def test_hvd004_base_exception():
    # BaseException is broader than Exception — the one-word change that
    # would reopen the silent-loop-death class must not lint clean.
    src = """
        import threading
        def _worker_loop():
            try:
                step()
            except BaseException:
                pass
        threading.Thread(target=_worker_loop, name="w").start()
    """
    assert codes(run(src)) == ["HVD004"]


def test_hvd004_suppressed():
    assert run(HVD004_SUPPRESSED) == []


def test_hvd004_thread_subclass_run():
    src = """
        import threading
        class Pump(threading.Thread):
            def __init__(self):
                super().__init__(name="pump")
            def run(self):
                try:
                    go()
                except Exception:
                    pass
    """
    assert codes(run(src)) == ["HVD004"]


def test_hvd004_stash_and_surface_is_loud():
    # Capturing the exception object for the parent to surface (error
    # list, attribute) is propagation, not a silent swallow.
    src = """
        import threading
        errs = []
        def _worker_loop():
            try:
                step()
            except BaseException as e:
                errs.append(e)
        threading.Thread(target=_worker_loop, name="w").start()
    """
    assert run(src) == []


# ---------------------------------------------------------------------------
# HVD005 — wire-tag invariants (scoped to core/messages.py)
# ---------------------------------------------------------------------------

MESSAGES_PATH = os.path.join(PKG, "core", "messages.py")

HVD005_DUPLICATE = """
    A_MAGIC = 0x11111111
    B_MAGIC = 0x11111111
    class F:
        def to_bytes(self):
            w = Writer()
            w.u32(A_MAGIC)
            return w.getvalue()
    class G:
        def to_bytes(self):
            w = Writer()
            w.u32(B_MAGIC)
            return w.getvalue()
"""

HVD005_MISSING_MAGIC = """
    A_MAGIC = 0x11111111
    class F:
        def to_bytes(self):
            w = Writer()
            w.u8(1)
            return w.getvalue()
"""

HVD005_MAGIC_NOT_FIRST = """
    A_MAGIC = 0x11111111
    class F:
        def to_bytes(self):
            w = Writer()
            w.u8(2)
            w.u32(A_MAGIC)
            return w.getvalue()
"""

HVD005_CTRL_BIT = """
    A_MAGIC = 0x11111111
    FLAG = 1 << 63
    class F:
        def to_bytes(self):
            w = Writer()
            w.u32(A_MAGIC)
            return w.getvalue()
"""

HVD005_CLEAN = """
    A_MAGIC = 0x11111111
    B_MAGIC = 0x22222222
    class F:
        def to_bytes(self):
            w = Writer()
            w.u32(A_MAGIC)
            return w.getvalue()
"""


def test_hvd005_duplicate_magic():
    vs = run(HVD005_DUPLICATE, path=MESSAGES_PATH)
    assert codes(vs) == ["HVD005"]
    assert "duplicates" in vs[0].message


def test_hvd005_missing_magic():
    vs = run(HVD005_MISSING_MAGIC, path=MESSAGES_PATH)
    assert codes(vs) == ["HVD005"]
    assert "to_bytes" in vs[0].message


def test_hvd005_magic_not_first_write():
    # A u8 written before the u32 magic shifts the leading bytes off the
    # tag even though a magic u32 exists somewhere in to_bytes.
    vs = run(HVD005_MAGIC_NOT_FIRST, path=MESSAGES_PATH)
    assert codes(vs) == ["HVD005"]
    assert "first field" in vs[0].message


def test_hvd005_ctrl_bit():
    # The top-bit literal violates both the messages-layer contract
    # (HVD005: don't touch the transport's control bit) and the registry
    # split (HVD008: bit 56-63 literals live in frame_bits.py only).
    vs = run(HVD005_CTRL_BIT, path=MESSAGES_PATH)
    assert codes(vs) == ["HVD005", "HVD008"]
    assert "control-frame" in next(
        v.message for v in vs if v.code == "HVD005")


def test_hvd005_clean_and_scoped():
    assert run(HVD005_CLEAN, path=MESSAGES_PATH) == []
    # The same duplicate-magic source outside core/messages.py is not
    # this rule's business.
    assert run(HVD005_DUPLICATE) == []


# -- extended header layout (integrity plane): frame_bits.py contract --

FRAME_BITS_PATH = os.path.join(PKG, "transport", "frame_bits.py")

HVD005_BITS_CLEAN = """
    import struct
    _LEN = struct.Struct("<Q")
    _CRC = struct.Struct("<I")
    _CTRL_FLAG = 1 << 63
    _DEFER_FLAG = 1 << 62
    _DIGEST_FLAG = 1 << 61
"""

HVD005_BITS_WRONG_LEN = """
    import struct
    _LEN = struct.Struct("<I")
    _CRC = struct.Struct("<I")
    _CTRL_FLAG = 1 << 63
    _DEFER_FLAG = 1 << 62
    _DIGEST_FLAG = 1 << 61
"""

HVD005_BITS_NO_CRC = """
    import struct
    _LEN = struct.Struct("<Q")
    _CTRL_FLAG = 1 << 63
    _DEFER_FLAG = 1 << 62
    _DIGEST_FLAG = 1 << 61
"""

HVD005_BITS_NO_CTRL = """
    import struct
    _LEN = struct.Struct("<Q")
    _CRC = struct.Struct("<I")
    _DEFER_FLAG = 1 << 62
    _DIGEST_FLAG = 1 << 61
"""

HVD005_BITS_WRONG_DEFER = """
    import struct
    _LEN = struct.Struct("<Q")
    _CRC = struct.Struct("<I")
    _CTRL_FLAG = 1 << 63
    _DEFER_FLAG = 1 << 60
    _DIGEST_FLAG = 1 << 61
"""

HVD005_MESSAGES_CRC = """
    import zlib
    A_MAGIC = 0x11111111
    class F:
        def to_bytes(self):
            w = Writer()
            w.u32(A_MAGIC)
            w.u32(zlib.crc32(bytes(w.buf)))
            return w.getvalue()
"""


def test_hvd005_transport_header_clean():
    assert run(HVD005_BITS_CLEAN, path=FRAME_BITS_PATH) == []
    # The bit-56..63 literals are RESERVED for frame_bits.py — owning
    # them there is the contract, not a violation (HVD008 is scoped out).


def test_hvd005_transport_wrong_len_format():
    vs = run(HVD005_BITS_WRONG_LEN, path=FRAME_BITS_PATH)
    assert codes(vs) == ["HVD005"]
    assert "_LEN" in vs[0].message and "'<Q'" in vs[0].message


def test_hvd005_transport_missing_crc_struct():
    vs = run(HVD005_BITS_NO_CRC, path=FRAME_BITS_PATH)
    assert codes(vs) == ["HVD005"]
    assert "_CRC" in vs[0].message


def test_hvd005_transport_missing_ctrl_flag():
    vs = run(HVD005_BITS_NO_CTRL, path=FRAME_BITS_PATH)
    assert codes(vs) == ["HVD005"]
    assert "_CTRL_FLAG" in vs[0].message


def test_hvd005_transport_flag_on_wrong_bit():
    # A flag declared on the WRONG bit is the same contract break as a
    # missing one: the reservation names a position, not just a name.
    vs = run(HVD005_BITS_WRONG_DEFER, path=FRAME_BITS_PATH)
    assert codes(vs) == ["HVD005"]
    assert "_DEFER_FLAG" in vs[0].message


def test_hvd005_real_frame_bits_passes():
    vs = lint_paths([os.path.join(PKG, "transport", "frame_bits.py")],
                    PROJECT)
    assert vs == [], vs


def test_hvd005_messages_must_not_crc():
    # The CRC envelope is the transport's; a second checksum computed in
    # messages.py would drift from it (two integrity layers, no owner).
    vs = run(HVD005_MESSAGES_CRC, path=MESSAGES_PATH)
    assert codes(vs) == ["HVD005"]
    assert "crc" in vs[0].message.lower()
    # ...and crc32 outside the scoped files is not this rule's business.
    assert run(HVD005_MESSAGES_CRC) == []


# ---------------------------------------------------------------------------
# HVD006 — anonymous threads
# ---------------------------------------------------------------------------

HVD006_VIOLATING = """
    import threading
    threading.Thread(target=print, daemon=True).start()
"""

HVD006_CLEAN = """
    import threading
    threading.Thread(target=print, name="printer", daemon=True).start()
"""

HVD006_SUPPRESSED = """
    import threading
    threading.Thread(target=print, daemon=True).start()  # hvdlint: disable=HVD006 -- fixture: throwaway
"""

HVD006_SUBCLASS_VIOLATING = """
    import threading
    class Pump(threading.Thread):
        def __init__(self, stream):
            super().__init__(daemon=True)
            self._stream = stream
"""

HVD006_SUBCLASS_CLEAN = """
    import threading
    class Pump(threading.Thread):
        def __init__(self, stream, name):
            super().__init__(daemon=True, name=name)
            self._stream = stream
    class Pump2(threading.Thread):
        def __init__(self):
            super().__init__(daemon=True)
            self.name = "pump2"
"""


def test_hvd006_violating():
    assert codes(run(HVD006_VIOLATING)) == ["HVD006"]


def test_hvd006_clean():
    assert run(HVD006_CLEAN) == []


def test_hvd006_suppressed():
    assert run(HVD006_SUPPRESSED) == []


def test_hvd006_thread_subclass():
    # Subclass instantiation has no target= kw, so the Thread(...) check
    # never fires — the subclass __init__ itself must name the thread.
    vs = run(HVD006_SUBCLASS_VIOLATING)
    assert codes(vs) == ["HVD006"]
    assert "Pump" in vs[0].message
    assert run(HVD006_SUBCLASS_CLEAN) == []


def test_hvd006_executor_needs_name_prefix():
    src = """
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(max_workers=2)
    """
    assert codes(run(src)) == ["HVD006"]


# ---------------------------------------------------------------------------
# HVD007 — metric names must come from (and be documented in) the catalog
# ---------------------------------------------------------------------------

HVD007_VIOLATING = """
    from horovod_tpu.core import metrics
    def f():
        metrics.inc("nonexistent_metric_total")
"""

HVD007_CLEAN = """
    from horovod_tpu.core import metrics
    from horovod_tpu.core.timeline import phase_stats, wire_stats
    def f(dt):
        metrics.inc("faults_injected_total")
        metrics.set_gauge("tensor_queue_depth", 3)
        metrics.observe("collective_latency_seconds", dt, op="ALLREDUCE")
        wire_stats.add("bytes_on_wire", 128)
        phase_stats.add("negotiate", dt)
        unrelated.observe("whatever")  # not the metrics receiver
"""

HVD007_SUPPRESSED = """
    from horovod_tpu.core import metrics
    def f():
        metrics.inc("nonexistent_metric_total")  # hvdlint: disable=HVD007 -- fixture: testing the suppression path
"""


def test_hvd007_catalog_is_populated():
    names = PROJECT.metric_catalog
    assert "collective_latency_seconds" in names
    assert "bytes_on_wire" in names     # wire_stats literal
    assert "negotiate" in names         # phase_stats literal


def test_hvd007_unknown_metric():
    vs = run(HVD007_VIOLATING)
    assert codes(vs) == ["HVD007"]
    assert "nonexistent_metric_total" in vs[0].message


def test_hvd007_stats_add_checked_too():
    vs = run("""
        from horovod_tpu.core.timeline import wire_stats
        def f():
            wire_stats.add("bytes_on_wrie", 4)
    """)
    assert codes(vs) == ["HVD007"]


def test_hvd007_computed_name_rejected():
    vs = run("""
        from horovod_tpu.core import metrics
        def f(name):
            metrics.inc(name)
    """)
    assert codes(vs) == ["HVD007"]
    assert "literal" in vs[0].message


def test_hvd007_clean():
    assert run(HVD007_CLEAN) == []


def test_hvd007_suppressed():
    assert run(HVD007_SUPPRESSED) == []


def test_hvd007_every_metric_documented():
    """The real registry file must pass (every CATALOG entry backticked
    in docs/observability.md) — the HVD003 doc-mirror contract, metrics
    flavor.  Checked via the real file so a catalog addition without its
    doc row fails here by name."""
    path = os.path.join(PKG, "core", "metrics.py")
    vs = lint_paths([path], PROJECT)
    assert [v for v in vs if v.code == "HVD007"] == [], vs


def test_hvd007_undocumented_metric_detected(tmp_path):
    """A catalog entry absent from the doc must be flagged — proven with
    a shadow project root whose doc is empty-ish but whose registry has
    one extra name."""
    shadow = tmp_path / "root"
    (shadow / "horovod_tpu" / "core").mkdir(parents=True)
    (shadow / "docs").mkdir()
    (shadow / "horovod_tpu" / "core" / "metrics.py").write_text(
        'CATALOG = {"documented_total": ("counter", "x"),\n'
        '           "undocumented_total": ("counter", "y")}\n')
    (shadow / "docs" / "observability.md").write_text(
        "only `documented_total` appears here\n")
    vs = lint_paths([str(shadow / "horovod_tpu" / "core" / "metrics.py")],
                    Project(root=str(shadow)))
    assert codes(vs) == ["HVD007"]
    assert "undocumented_total" in vs[0].message


# ---------------------------------------------------------------------------
# HVD008 — frame-header bit literals live only in transport/frame_bits.py
# ---------------------------------------------------------------------------

HVD008_VIOLATING = """
    MY_CTRL = 1 << 63
"""

HVD008_DTYPE_LANE = """
    def stamp(code):
        return code << 56
"""

HVD008_REBIND = """
    import struct
    _CTRL_FLAG = 1 << 40
"""

HVD008_WIRE_CODE_REBIND = """
    _WIRE_DTYPE_INT8 = 3
"""

HVD008_WIRE_CODE_CLEAN = """
    from horovod_tpu.transport.frame_bits import (_WIRE_DTYPE_INT8,
                                                  _WIRE_DTYPE_ONEBIT,
                                                  _WIRE_DTYPE_TOPK)
    def codec_code(name):
        return {"int8": _WIRE_DTYPE_INT8, "onebit": _WIRE_DTYPE_ONEBIT,
                "topk": _WIRE_DTYPE_TOPK}[name]
"""

HVD008_CLEAN = """
    from horovod_tpu.transport.frame_bits import _CTRL_FLAG, _FLAGS_MASK
    def is_ctrl(word):
        return bool(word & _CTRL_FLAG)
    LOW_BIT = 1 << 8          # below the flag lane: not wire framing
    WIDE = (1 << 64) - 1      # a width mask, not a lane position
"""

HVD008_SUPPRESSED = """
    MY_CTRL = 1 << 63  # hvdlint: disable=HVD008 -- fixture: testing the suppression path
"""


def test_hvd008_bit_literal():
    vs = run(HVD008_VIOLATING)
    assert codes(vs) == ["HVD008"]
    assert "frame_bits" in vs[0].message


def test_hvd008_dtype_lane_literal():
    # Re-deriving the dtype lane shift (bit 56) is the same fork as the
    # flag bits, even when the left operand is a variable.
    vs = run(HVD008_DTYPE_LANE)
    assert codes(vs) == ["HVD008"]


def test_hvd008_registry_name_rebind():
    # Shadowing a registry name forks the contract even with an
    # off-lane value.
    vs = run(HVD008_REBIND)
    assert codes(vs) == ["HVD008"]
    assert "_CTRL_FLAG" in vs[0].message


def test_hvd008_wire_dtype_code_rebind():
    # Re-defining a wire-dtype CODE outside frame_bits.py forks the
    # compression skew contract — two peers could stamp the same lane
    # value for different codecs and mis-decode instead of aborting.
    vs = run(HVD008_WIRE_CODE_REBIND)
    assert codes(vs) == ["HVD008"]
    assert "_WIRE_DTYPE_INT8" in vs[0].message


def test_hvd008_wire_dtype_code_import_is_clean():
    assert run(HVD008_WIRE_CODE_CLEAN) == []


def test_hvd008_clean():
    assert run(HVD008_CLEAN) == []


def test_hvd008_suppressed():
    assert run(HVD008_SUPPRESSED) == []


def test_hvd008_scoped_out_of_frame_bits():
    # The registry itself is the one place the literals belong (the
    # fixture still trips HVD005's header-contract check there, which is
    # that rule's business, not this one's).
    vs = run(HVD008_VIOLATING, path=FRAME_BITS_PATH)
    assert [v for v in vs if v.code == "HVD008"] == []


# ---------------------------------------------------------------------------
# HVD009 — shm control words move only through the accessor helpers
# ---------------------------------------------------------------------------

SHM_PATH = os.path.join(PKG, "transport", "shm.py")

HVD009_VIOLATING = """
    import struct
    _U64 = struct.Struct("<Q")
    _OFF_L2H_HEAD = 256
    def peek_head(buf):
        return _U64.unpack_from(buf, _OFF_L2H_HEAD)[0]
"""

HVD009_ATTR_VIOLATING = """
    import struct
    _U32 = struct.Struct("<I")
    def peek_bell(buf, p):
        return _U32.unpack_from(buf, p.in_data_bell_off)[0]
"""

HVD009_CLEAN = """
    import struct
    _HDR = struct.Struct("<II")
    def walk(blob, off):
        return _HDR.unpack_from(blob, off)
"""

HVD009_SUPPRESSED = """
    import struct
    _U64 = struct.Struct("<Q")
    _OFF_L2H_HEAD = 256
    def peek_head(buf):
        return _U64.unpack_from(buf, _OFF_L2H_HEAD)[0]  # hvdlint: disable=HVD009 -- fixture: testing the suppression path
"""

HVD009_SHM_ACCESSOR_CLEAN = """
    import struct
    _U64 = struct.Struct("<Q")
    def _load_u64(buf, off):
        return _U64.unpack_from(buf, off)[0]
    def _store_u64(buf, off, value):
        _U64.pack_into(buf, off, value)
"""

HVD009_SHM_BARE_STRUCT = """
    import struct
    _HDR = struct.Struct("<II")
    def sidestep(buf, off):
        return _HDR.unpack_from(buf, off)
"""


def test_hvd009_offset_constant():
    vs = run(HVD009_VIOLATING)
    assert codes(vs) == ["HVD009"]
    assert "_OFF_L2H_HEAD" in vs[0].message


def test_hvd009_offset_attribute():
    vs = run(HVD009_ATTR_VIOLATING)
    assert codes(vs) == ["HVD009"]
    assert "in_data_bell_off" in vs[0].message


def test_hvd009_clean_bare_offset_elsewhere():
    # journal.py-style framed walks over a blob use plain offsets; only
    # the shm header-offset vocabulary marks a control word.
    assert run(HVD009_CLEAN) == []


def test_hvd009_suppressed():
    assert run(HVD009_SUPPRESSED) == []


def test_hvd009_shm_accessors_are_the_allowlist():
    # Inside transport/shm.py the four accessors may move raw structs...
    assert run(HVD009_SHM_ACCESSOR_CLEAN, path=SHM_PATH) == []
    # ...and ANY other struct move in that file is a hole in the
    # model-checked access set, offset vocabulary or not.
    vs = run(HVD009_SHM_BARE_STRUCT, path=SHM_PATH)
    assert codes(vs) == ["HVD009"]
    assert "accessors" in vs[0].message


# ---------------------------------------------------------------------------
# HVD000 — suppression hygiene
# ---------------------------------------------------------------------------

def test_suppression_requires_justification():
    src = """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                time.sleep(1)  # hvdlint: disable=HVD001
    """
    vs = run(src)
    # The unjustified suppression is itself a violation AND does not
    # silence the original finding.
    assert codes(vs) == ["HVD000", "HVD001"]
    assert "justification" in next(
        v.message for v in vs if v.code == "HVD000")


def test_suppression_unknown_code_is_error():
    src = 'x = 1  # hvdlint: disable=HVD999 -- bogus\n'
    vs = run(src)
    assert codes(vs) == ["HVD000"]
    assert "HVD999" in vs[0].message


def test_suppression_on_preceding_comment_line():
    src = """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                # hvdlint: disable=HVD001 -- fixture: applies to next line
                time.sleep(1)
    """
    assert run(src) == []


# ---------------------------------------------------------------------------
# the tree-wide contract
# ---------------------------------------------------------------------------

def test_tree_is_clean(tree_violations):
    assert tree_violations == [], "\n".join(
        f"{v.path}:{v.line}: {v.code} {v.message}" for v in tree_violations)


def test_no_anonymous_threads_in_tree(tree_violations):
    # Satellite contract: lockdep and the stall inspector must be able to
    # attribute every background thread by name.
    assert [v for v in tree_violations if v.code == "HVD006"] == []


# ---------------------------------------------------------------------------
# HVD010 — rendezvous scope names come from transport/scopes.py
# ---------------------------------------------------------------------------

SCOPES_PATH = os.path.join(PKG, "transport", "scopes.py")

HVD010_VIOLATING = """
    def renew(store, identity, payload):
        store.set("lease", identity, payload)
"""

HVD010_BATCH_VIOLATING = """
    def publish(store, identity, blob):
        store.batch([("set", "rank_and_size", identity, blob)])
"""

HVD010_REBIND = """
    LEASE_SCOPE = "lease"
"""

HVD010_CLEAN = """
    from horovod_tpu.transport.scopes import LEASE_SCOPE
    def renew(store, identity, payload):
        store.set(LEASE_SCOPE, identity, payload)
    def local_lookup(fetched):
        return fetched.get("epoch_ack")      # dict key, not a wire scope
    def own_namespace(store, key):
        return store.get("myapp_private", key)   # unregistered scope
"""

HVD010_SUPPRESSED = """
    def renew(store, identity, payload):
        store.set("lease", identity, payload)  # hvdlint: disable=HVD010 -- fixture: testing the suppression path
"""


def test_hvd010_call_literal():
    vs = run(HVD010_VIOLATING)
    assert codes(vs) == ["HVD010"]
    assert "scopes.py" in vs[0].message


def test_hvd010_batch_tuple_literal():
    vs = run(HVD010_BATCH_VIOLATING)
    assert codes(vs) == ["HVD010"]
    assert "rank_and_size" in vs[0].message


def test_hvd010_registry_name_rebind():
    vs = run(HVD010_REBIND)
    assert codes(vs) == ["HVD010"]
    assert "LEASE_SCOPE" in vs[0].message


def test_hvd010_clean():
    assert run(HVD010_CLEAN) == []


def test_hvd010_suppressed():
    assert run(HVD010_SUPPRESSED) == []


def test_hvd010_scoped_out_of_scopes_registry():
    # The registry file itself is where the literals belong.
    vs = run(HVD010_REBIND, path=SCOPES_PATH)
    assert [v for v in vs if v.code == "HVD010"] == []


def test_hvd010_registry_parsed_not_imported():
    # The project parses scope VALUES out of transport/scopes.py's AST;
    # the wire names the control plane depends on must all be there.
    scopes = set(PROJECT.scope_registry)
    assert {"lease", "rank_and_size", "epoch_ack", "reset_request",
            "demotion_report", "driver", "metrics"} <= scopes


@pytest.mark.parametrize("code,fixture", [
    ("HVD001", HVD001_WITH),
    ("HVD002", HVD002_VIOLATING),
    ("HVD003", HVD003_VIOLATING),
    ("HVD004", HVD004_VIOLATING),
    ("HVD006", HVD006_VIOLATING),
    ("HVD007", HVD007_VIOLATING),
    ("HVD008", HVD008_VIOLATING),
    ("HVD009", HVD009_VIOLATING),
    ("HVD010", HVD010_VIOLATING),
])
def test_seeded_violation_fails_with_right_code(tmp_path, code, fixture):
    """Seeding any single violation into a linted tree must fail the pass
    with exactly that rule code (the acceptance-criteria probe)."""
    seeded = tmp_path / "seeded.py"
    seeded.write_text(textwrap.dedent(fixture))
    vs = lint_paths([str(tmp_path)], PROJECT)
    assert codes(vs) == [code]


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(HVD006_VIOLATING))
    assert main([str(tmp_path), "--root", REPO_ROOT]) == 1
    out = capsys.readouterr().out
    assert "HVD006" in out
    good = tmp_path / "sub"
    good.mkdir()
    (good / "ok.py").write_text("x = 1\n")
    assert main([str(good), "--root", REPO_ROOT]) == 0


def test_rule_codes_catalog():
    assert RULE_CODES == {"HVD000", "HVD001", "HVD002", "HVD003",
                          "HVD004", "HVD005", "HVD006", "HVD007",
                          "HVD008", "HVD009", "HVD010"}
