"""Model zoo + sharded train-step tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import (
    MLP,
    ResNet18,
    Transformer,
    tiny_config,
)
from horovod_tpu.models.training import (
    create_train_state,
    make_seq_parallel_train_step,
    make_sharded_train_step,
)
from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch


pytestmark = pytest.mark.smoke


def test_mlp_forward():
    model = MLP(features=(32,), num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((2, 28, 28, 1)))
    out = model.apply(params, jnp.ones((2, 28, 28, 1)))
    assert out.shape == (2, 10)


def test_resnet_forward_and_bn_stats():
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    assert "batch_stats" in variables
    out, updated = model.apply(variables, x, train=True,
                               mutable=["batch_stats"])
    assert out.shape == (2, 10)
    assert jnp.isfinite(out).all()


def test_transformer_full_attention_forward():
    cfg = tiny_config(attention="full")
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_gspmd_train_step_dp_tp_loss_decreases():
    mesh = build_mesh(MeshSpec(data=4, model=2))
    cfg = tiny_config(attention="full")
    model = Transformer(cfg)
    tx = optax.adam(1e-2)
    tokens = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (8, 1))
    batch = shard_batch(mesh, {"x": tokens, "y": tokens})
    state = create_train_state(model, jax.random.PRNGKey(0), tokens, tx,
                               mesh=mesh)
    step = make_sharded_train_step(model, tx, mesh, donate=False)
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_gspmd_resnet_train_step_with_bn():
    mesh = build_mesh(MeshSpec(data=-1))
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(1e-2)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 32, 32, 3), jnp.float32)
    y = jnp.asarray(np.arange(8) % 10, jnp.int32)
    batch = shard_batch(mesh, {"x": x, "y": y})
    state = create_train_state(model, jax.random.PRNGKey(0), x, tx, mesh=mesh,
                               init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=False)
    state2, loss = step(state, batch)
    assert np.isfinite(float(loss))
    # batch_stats must have moved (BN sees the global batch under GSPMD).
    before = jax.tree_util.tree_leaves(state.batch_stats)[0]
    after = jax.tree_util.tree_leaves(state2.batch_stats)[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_seq_parallel_train_step(attention):
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    cfg = tiny_config(attention=attention, max_len=64)
    model = Transformer(cfg)
    tx = optax.adam(1e-2)
    tokens = jnp.tile(jnp.arange(32, dtype=jnp.int32)[None], (4, 1))

    # init outside shard_map with full-attention twin: identical params tree
    init_model = Transformer(tiny_config(attention="full", max_len=64))
    state = create_train_state(init_model, jax.random.PRNGKey(0),
                               tokens, tx)
    step = make_seq_parallel_train_step(model, tx, mesh, donate=False)
    losses = []
    for _ in range(4):
        state, loss = step(state, tokens, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_seq_parallel_matches_full_attention_loss():
    """Ring-attention loss == full-attention loss on identical params."""
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    tx = optax.sgd(0.0)
    tokens = jnp.tile(jnp.arange(32, dtype=jnp.int32)[None], (4, 1))

    full_model = Transformer(tiny_config(attention="full", max_len=64,
                                         dtype=jnp.float32))
    ring_model = Transformer(tiny_config(attention="ring", max_len=64,
                                         dtype=jnp.float32))
    state = create_train_state(full_model, jax.random.PRNGKey(1), tokens, tx)

    full_step = make_sharded_train_step(full_model, tx, donate=False)
    ring_step = make_seq_parallel_train_step(ring_model, tx, mesh,
                                             donate=False)
    _, full_loss = full_step(state, {"x": tokens, "y": tokens})
    _, ring_loss = ring_step(state, tokens, tokens)
    np.testing.assert_allclose(float(ring_loss), float(full_loss), rtol=1e-5)
