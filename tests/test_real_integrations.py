"""Integration smokes against the REAL ray / pyspark / mxnet libraries.

VERDICT r2 #8: `tests/fake_ray.py` / `tests/fake_spark.py` encode the
builder's *belief* about those APIs; nothing checked the belief.  These
tests run the same surfaces against the genuine libraries — they skip
cleanly when a library is absent (the default CI image has none of the
three) and run in the dedicated lane (`ci/real_integrations.sh`, pinned
versions in `ci/requirements-integrations.txt`).

Reference analog: `test/single/test_ray.py` uses real
``ray.init(local_mode=True)``; `test/integration/test_spark*.py` uses a
real local pyspark session.
"""

from __future__ import annotations

import numpy as np
import pytest


class TestRealRay:
    def setup_method(self):
        ray = pytest.importorskip("ray", reason="real-ray lane only")
        ray.init(local_mode=True, ignore_reinit_error=True,
                 include_dashboard=False)

    def teardown_method(self):
        import ray

        ray.shutdown()

    def test_ray_executor_single_slot(self):
        from horovod_tpu.ray import RayExecutor

        ex = RayExecutor(RayExecutor.create_settings(), num_workers=1)
        ex.start()
        try:
            def fn():
                import horovod_tpu as hvd

                hvd.init()
                out = np.asarray(hvd.allreduce(
                    np.ones(3, np.float32), op=hvd.Sum, name="t"))
                r = hvd.rank()
                hvd.shutdown()
                return r, out.tolist()

            results = ex.run(fn)
            assert results[0][0] == 0
            assert results[0][1] == [1.0, 1.0, 1.0]
        finally:
            ex.shutdown()


def test_real_pyspark_run():
    pyspark = pytest.importorskip("pyspark", reason="real-pyspark lane only")
    from pyspark import SparkConf, SparkContext

    import horovod_tpu.spark as hvd_spark

    conf = SparkConf().setMaster("local[2]").setAppName("hvd-real-spark")
    sc = SparkContext.getOrCreate(conf)
    try:
        def task():
            import horovod_tpu as hvd

            hvd.init()
            out = np.asarray(hvd.allreduce(
                np.ones(2, np.float32) * (hvd.rank() + 1),
                op=hvd.Sum, name="s"))
            r, s = hvd.rank(), hvd.size()
            hvd.shutdown()
            return r, s, out.tolist()

        results = hvd_spark.run(task, num_proc=2, sc=sc)
        assert sorted(r[0] for r in results) == [0, 1]
        assert all(r[1] == 2 for r in results)
        assert all(r[2] == [3.0, 3.0] for r in results)
    finally:
        sc.stop()


def test_real_pyspark_estimator_store_plane(tmp_path):
    pyspark = pytest.importorskip("pyspark", reason="real-pyspark lane only")
    keras = pytest.importorskip("keras")
    from pyspark.sql import SparkSession

    from horovod_tpu.spark.common import LocalStore, prepare_dataset, read_shards

    spark = SparkSession.builder.master("local[2]").getOrCreate()
    try:
        rows = [([float(i), float(i * 2)], float(i % 2)) for i in range(20)]
        df = spark.createDataFrame(rows, ["features", "label"]) \
            .repartition(4)
        store = LocalStore(str(tmp_path))
        manifest = prepare_dataset(df, store, ["features"], ["label"],
                                   validation=0.2)
        assert manifest["train_rows"] + manifest["val_rows"] == 20
        x, y = read_shards(store, manifest, 0, 2)
        assert x.shape[1] == 2
    finally:
        spark.stop()


def test_real_pyspark_ml_pipeline(tmp_path):
    """The pyspark.ml veneer (VERDICT r3 #6): KerasEstimator inside a real
    ``Pipeline``, params get/set, ``transform`` appending predictions, and
    ML persistence round-trip."""
    pyspark = pytest.importorskip("pyspark", reason="real-pyspark lane only")
    keras = pytest.importorskip("keras")
    from pyspark.ml import Pipeline
    from pyspark.sql import SparkSession

    from horovod_tpu.spark.ml import KerasEstimator, KerasModel

    spark = SparkSession.builder.master("local[2]").getOrCreate()
    try:
        rows = [([float(i) / 10.0, float(i % 3)], float(i % 2))
                for i in range(24)]
        df = spark.createDataFrame(rows, ["features", "label"])

        net = keras.Sequential([keras.layers.Input(shape=(2,)),
                                keras.layers.Dense(4, activation="tanh"),
                                keras.layers.Dense(1)])
        est = KerasEstimator(model=net,
                             optimizer=keras.optimizers.SGD(0.05),
                             loss="mse", batch_size=8, epochs=1,
                             num_proc=2)
        # Params surface (CrossValidator compatibility)
        assert est.getBatchSize() == 8
        est.setEpochs(2)
        assert est.getEpochs() == 2
        assert est.copy().getEpochs() == 2

        pipe = Pipeline(stages=[est])
        model = pipe.fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        preds = out.select("prediction").collect()
        assert len(preds) == 24 and len(preds[0][0]) == 1

        # ML persistence round-trip
        path = str(tmp_path / "hvd_keras_model")
        fitted = model.stages[0]
        fitted.write().overwrite().save(path)
        loaded = KerasModel.read().load(path)
        out2 = loaded.transform(df).select("prediction").collect()
        assert np.allclose([p[0] for p in preds], [p[0] for p in out2],
                           atol=1e-6)
    finally:
        spark.stop()


def test_real_mxnet_binding_smoke():
    mx = pytest.importorskip("mxnet", reason="real-mxnet lane only")

    import horovod_tpu.mxnet as hvd

    hvd.init()
    try:
        x = mx.nd.ones((4,))
        out = hvd.allreduce(x, name="mx.t")
        assert np.allclose(out.asnumpy(), np.ones(4))
        # DistributedTrainer wraps a Gluon trainer end-to-end
        net = mx.gluon.nn.Dense(2)
        net.initialize()
        trainer = hvd.DistributedTrainer(
            net.collect_params(), "sgd", {"learning_rate": 0.1})
        with mx.autograd.record():
            loss = net(mx.nd.ones((3, 4))).sum()
        loss.backward()
        trainer.step(3)
    finally:
        hvd.shutdown()


def test_real_mxnet_engine_ordering():
    """Interleaved NDArray mutations around in-place collectives must
    serialize with the REAL async dependency engine (reference pushes
    engine var deps, mpi_ops.cc:182-191; our bridge relies on
    asnumpy/write sync points).  x_{k+1} = 2*x_k + 1 from 1 gives
    2^(n+1)-1; any stale read breaks the closed form."""
    mx = pytest.importorskip("mxnet", reason="real-mxnet lane only")

    import horovod_tpu.mxnet as hvd

    hvd.init()
    try:
        x = mx.nd.ones((4096,))
        for _ in range(10):
            x *= 2.0
            hvd.allreduce_(x, name="mx.ord")
            x += 1.0
        assert np.allclose(x.asnumpy(), 2.0 ** 11 - 1.0), x.asnumpy()[:4]
    finally:
        hvd.shutdown()
