"""Launcher tests: slot-assignment math (reference `test/single/test_run.py`
style) + a real end-to-end `hvdrun` launch with 2 local workers."""

import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner.hosts import (
    HostInfo,
    get_host_assignments,
    parse_hosts,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_hosts():
    hosts = parse_hosts("a:4, b:2,c")
    assert hosts == [HostInfo("a", 4), HostInfo("b", 2), HostInfo("c", 1)]


def test_host_assignments_homogeneous():
    slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank) for s in slots] == [
        ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
    assert all(s.local_size == 2 and s.cross_size == 2 and s.size == 4
               for s in slots)


def test_host_assignments_heterogeneous_cross_scope():
    slots = get_host_assignments(parse_hosts("a:2,b:1"), 3)
    by_rank = {s.rank: s for s in slots}
    # local_rank 0 exists on both hosts -> cross_size 2
    assert by_rank[0].cross_size == 2 and by_rank[2].cross_size == 2
    # local_rank 1 exists only on host a -> cross scope of size 1
    assert by_rank[1].cross_size == 1 and by_rank[1].cross_rank == 0


def test_host_assignments_insufficient_slots():
    with pytest.raises(ValueError):
        get_host_assignments(parse_hosts("a:1"), 2)


def test_hvdrun_end_to_end(tmp_path):
    """Real launch: 2 local workers allreduce through the full stack."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        out = hvd.allreduce(np.full(3, float(hvd.rank() + 1)), op=hvd.Sum)
        assert np.allclose(np.asarray(out), 3.0), out
        print("LAUNCHED_OK", hvd.rank(), flush=True)
        hvd.shutdown()
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--output-filename", str(tmp_path / "logs"),
         sys.executable, str(script)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "LAUNCHED_OK 0" in proc.stdout and "LAUNCHED_OK 1" in proc.stdout
    # --output-filename tee
    assert (tmp_path / "logs" / "rank.0" / "stdout").exists()


def test_hvdrun_propagates_failure(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text(
        "import horovod_tpu as hvd\nhvd.init()\n"
        "import sys\nsys.exit(3 if hvd.rank() == 1 else 0)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        cwd=REPO_ROOT, text=True, capture_output=True, timeout=120)
    assert proc.returncode == 3, (proc.returncode, proc.stdout, proc.stderr)


def _worker_fn(scale):
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum)
    hvd.shutdown()
    return float(out[0]) * scale


def test_programmatic_run():
    import horovod_tpu.runner as runner

    from .helpers import retry_backoff

    # One retry for load-starvation failures (worker starved of CPU on a
    # contended box → mesh connect/recv faults or a rank that dies before
    # posting its result, which surfaces as RuntimeError/TimeoutError),
    # mirroring helpers.run_distributed's policy.
    try:
        results = runner.run(_worker_fn, args=(2.0,), np=2)
    except Exception:  # noqa: BLE001 — one retry, then the real failure
        retry_backoff(1)
        results = runner.run(_worker_fn, args=(2.0,), np=2)
    assert results == [6.0, 6.0], results
