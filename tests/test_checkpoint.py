"""Self-healing checkpoint layer: atomic publish, CRC manifests, rotation.

These are the rank-0-LOCAL primitives (no collectives), tested in-process;
the distributed flavors (rank-0-writes + broadcast, kill-mid-save chaos)
live in ``test_framework_api.py`` and ``test_fault_injection.py``.
"""

import json
import os

import numpy as np
import pytest

from horovod_tpu.frameworks.jax import checkpoint as ck

pytestmark = pytest.mark.smoke


def _state(step: int):
    return {"w": np.arange(4, dtype=np.float32) * step,
            "step": np.asarray(step)}


def _corrupt_one_payload_byte(snap: str) -> str:
    """Flip one byte in the largest payload file; returns the file."""
    victim, size = None, -1
    for dirpath, _, filenames in os.walk(snap):
        for name in filenames:
            full = os.path.join(dirpath, name)
            if os.path.getsize(full) > size:
                victim, size = full, os.path.getsize(full)
    with open(victim, "rb") as f:
        raw = bytearray(f.read())
    raw[len(raw) // 2] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(raw))
    return victim


def test_publish_writes_manifest_with_crc_and_step(tmp_path):
    snap = str(tmp_path / "snap")
    manifest = ck._publish_snapshot(snap, _state(7))
    assert os.path.isdir(snap)
    with open(ck._manifest_path(snap)) as f:
        on_disk = json.load(f)
    assert on_disk == manifest
    assert on_disk["step"] == 7          # harvested from the state tree
    assert on_disk["files"] > 0
    crc, _, nfiles = ck._payload_crc(snap)
    assert (crc, nfiles) == (on_disk["crc32"], on_disk["files"])
    assert ck.snapshot_valid(snap) == (True, "ok")
    # no temp litter left behind
    assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]


def test_snapshot_invalid_without_manifest(tmp_path):
    snap = str(tmp_path / "snap")
    ck._publish_snapshot(snap, _state(1))
    os.remove(ck._manifest_path(snap))
    ok, reason = ck.snapshot_valid(snap)
    assert not ok and "no manifest" in reason


def test_snapshot_invalid_on_payload_corruption(tmp_path):
    snap = str(tmp_path / "snap")
    ck._publish_snapshot(snap, _state(1))
    _corrupt_one_payload_byte(snap)
    ok, reason = ck.snapshot_valid(snap)
    assert not ok and "CRC mismatch" in reason


def test_snapshot_invalid_on_garbage_manifest(tmp_path):
    snap = str(tmp_path / "snap")
    ck._publish_snapshot(snap, _state(1))
    with open(ck._manifest_path(snap), "w") as f:
        f.write("{not json")
    ok, reason = ck.snapshot_valid(snap)
    assert not ok and "unreadable" in reason


def test_publish_overwrite_replaces_and_revalidates(tmp_path):
    snap = str(tmp_path / "snap")
    ck._publish_snapshot(snap, _state(1))
    ck._publish_snapshot(snap, _state(2))
    assert ck.snapshot_valid(snap) == (True, "ok")
    out = ck._restore_payload(snap, None)
    assert int(out["step"]) == 2
    # the move-aside overwrite protocol cleans up after itself
    litter = [n for n in os.listdir(tmp_path)
              if ".old-" in n or ".tmp-" in n]
    assert not litter, litter


def test_list_snapshots_orders_and_filters(tmp_path):
    base = str(tmp_path / "run")
    for seq in (1, 3, 2):
        ck._publish_snapshot(f"{base}.{seq:08d}", _state(seq))
    # litter that must NOT be listed: manifests, temp dirs, other names
    os.makedirs(f"{base}.00000009.tmp-123")
    os.makedirs(str(tmp_path / "unrelated.00000004"))
    snaps = ck._list_snapshots(base)
    assert [seq for seq, _ in snaps] == [3, 2, 1]
    assert all(p.startswith(base + ".") for _, p in snaps)
