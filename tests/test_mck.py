"""hvd-mck: the checker's acceptance contract, pinned as tests.

Four claims, each of which is load-bearing for trusting the shm ring:

- **tso proves**: the exhaustive bounded run over every scenario is
  complete (not truncated) and violation-free — the deployment claim.
- **weak refutes**: allowing store-store reordering must FIND the
  missed wakeup, with a concrete minimal schedule.  A checker that
  cannot rediscover the bug the protocol was designed against proves
  nothing by passing.
- **mutants die**: every seeded protocol bug (mutations.py) is killed
  within the configured bounds, each by one of its expected violation
  classes, each with a reproducing schedule.
- **truncation is honest**: hitting the schedule cap is reported as
  incomplete and fails the CI smoke gate — never silently passes as
  exhaustive.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from horovod_tpu.tools.mck import main  # noqa: E402
from horovod_tpu.tools.mck.explore import check, explore  # noqa: E402
from horovod_tpu.tools.mck.model import (  # noqa: E402
    V_MISSED_WAKEUP,
)
from horovod_tpu.tools.mck.mutations import MUTATIONS  # noqa: E402
from horovod_tpu.tools.mck.scenarios import SCENARIOS  # noqa: E402


# ---------------------------------------------------------------------------
# tso: the deployment claim
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_tso_exhaustive_and_clean(name):
    res = check(SCENARIOS[name], "tso")
    assert res.complete, (
        f"tso run over {name!r} truncated at {res.schedules} schedules — "
        "an incomplete exploration is not a proof")
    assert res.ok, (
        f"tso violations in {name!r}: "
        + "; ".join(f"{v.name}: {v.detail}" for v in res.violations.values()))
    assert res.schedules > 1  # it actually explored interleavings


def test_tso_is_deterministic():
    # Replay-based DFS over generators must be exactly reproducible:
    # same scenario, same bound, same schedule count, step for step.
    a = explore(SCENARIOS["wrap"], "tso")
    b = explore(SCENARIOS["wrap"], "tso")
    assert (a.schedules, a.max_depth) == (b.schedules, b.max_depth)


# ---------------------------------------------------------------------------
# weak: the counterfactual must fail
# ---------------------------------------------------------------------------

def test_weak_finds_missed_wakeup():
    res = check(SCENARIOS["basic"], "weak")
    assert V_MISSED_WAKEUP in res.violations, (
        "weak mode failed to find the missed wakeup store-store "
        f"reordering causes (found: {sorted(res.violations)})")
    viol = res.violations[V_MISSED_WAKEUP]
    # The counterexample is a concrete, non-empty schedule a human can
    # replay, found at a minimal preemption bound.
    assert viol.schedule, "counterexample carries no schedule"
    assert res.min_bound is not None and res.min_bound <= res.bound


def test_weak_counterexample_tells_the_reordering_story():
    # The schedule is the human-facing artifact: it must show the
    # out-of-order store-buffer flush AND the victim going to sleep on
    # the bell — the two halves of the missed wakeup.
    res = check(SCENARIOS["basic"], "weak")
    trace = "\n".join(res.violations[V_MISSED_WAKEUP].schedule)
    assert "flush(" in trace, (
        "a weak-ordering counterexample must involve a store-buffer "
        f"flush:\n{trace}")
    assert "FUTEX_WAIT" in trace and "sleep" in trace, (
        f"no sleeper on the counterexample path:\n{trace}")


# ---------------------------------------------------------------------------
# the mutation-kill suite: the checker's checker
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_mutation_killed(name):
    mut = MUTATIONS[name]
    res = check(SCENARIOS[mut.scenario], "tso", mutation=mut)
    caught = set(res.violations) & mut.expected
    assert caught, (
        f"mutant {name!r} SURVIVED the exhaustive run (expected one of "
        f"{sorted(mut.expected)}, found {sorted(res.violations)}): the "
        "configured bounds no longer catch seeded protocol bugs")
    for cls in caught:
        assert res.violations[cls].schedule, (
            f"kill of {name!r} by {cls} carries no reproducing schedule")


def test_mutation_suite_is_nontrivial():
    # At least the ISSUE's four classic ring bugs, each on a side and
    # scenario where it can actually bite.
    assert len(MUTATIONS) >= 4
    assert {"swap_publish_bump", "drop_bell_precheck",
            "free_space_off_by_one", "skip_final_wake"} <= set(MUTATIONS)


# ---------------------------------------------------------------------------
# truncation honesty + CLI contract
# ---------------------------------------------------------------------------

def test_truncated_run_is_not_a_proof():
    res = explore(SCENARIOS["basic"], "tso", max_schedules=3)
    assert res.truncated and not res.complete
    assert res.schedules <= 3


def test_cli_tso_smoke_passes(capsys):
    assert main(["--mode", "tso", "--smoke", "-q"]) == 0
    out = capsys.readouterr().out
    assert "no violations" in out or "ok" in out.lower()


def test_cli_weak_fails_with_counterexample(capsys):
    assert main(["--mode", "weak", "--scenario", "basic", "-q"]) == 1
    out = capsys.readouterr().out
    assert V_MISSED_WAKEUP in out


def test_cli_mutants_all_killed(capsys):
    assert main(["--mutants", "-q"]) == 0
    out = capsys.readouterr().out
    assert "mutants killed" in out


def test_cli_smoke_trips_on_truncation(capsys):
    assert main(["--mode", "tso", "--scenario", "basic", "--smoke",
                 "--max-schedules", "3", "-q"]) == 2


def test_cli_unknown_names(capsys):
    assert main(["--scenario", "nope"]) == 2
    assert main(["--mutation", "nope"]) == 2


def test_cli_json_report(tmp_path, capsys):
    path = tmp_path / "mck.json"
    assert main(["--mode", "tso", "--scenario", "basic", "-q",
                 "--json", str(path)]) == 0
    doc = json.loads(path.read_text())
    assert doc["tool"] == "hvd-mck"
    assert doc["mode"] == "tso"
    assert doc["ok"] and doc["complete"]
    run = doc["runs"][0]
    assert run["scenario"] == "basic"
    assert run["complete"] and run["violations"] == []
