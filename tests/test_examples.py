"""Smoke-run every BASELINE example config under a real 2-process hvdrun
launch with CI-sized knobs (BASELINE.md: "examples running unmodified" is
the acceptance bar; reference CI runs its examples the same way)."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch_once(cmd, env, timeout):
    """One launcher invocation in its OWN process group: a timeout kill
    must reach the worker grandchildren too (killing only the launcher
    leaves orphans holding the output pipes — communicate() would block
    on them, and they'd keep loading the box for the retry)."""
    import signal

    proc = subprocess.Popen(cmd, cwd=REPO_ROOT, text=True, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
        return proc.returncode, out, err, False
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, err = proc.communicate()
        return proc.returncode, out, err, True


def _hvdrun(np_, script_args, timeout=420, extra_cli=()):
    from .helpers import (
        _log_retry,
        _timeout_scale,
        infra_retryable,
        retry_backoff,
    )

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TF_CPP_MIN_LOG_LEVEL="2")
    from .helpers import scaled_mesh_startup_timeout

    env.setdefault("HOROVOD_MESH_STARTUP_TIMEOUT",
                   scaled_mesh_startup_timeout())
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "-np", str(np_), *extra_cli, sys.executable, *script_args]
    # Same load-scaled-timeout + infra-retry intent as
    # helpers.run_distributed.  The launcher interleaves rank streams, so
    # the per-rank gate is approximated: retry only when infra text is
    # present AND no product-assert marker is — one rank's peer-death
    # text must not mask a sibling's real crash.
    for attempt in (0, 1, 2):
        code, out, err, timed_out = _launch_once(
            cmd, env, timeout * _timeout_scale())
        if code == 0:
            break
        blob = out + err
        retryable = (timed_out or infra_retryable(AssertionError(blob))) \
            and "AssertionError" not in blob
        if attempt == 2 or not retryable:
            break
        _log_retry(f"_hvdrun attempt {attempt + 1}: timed_out={timed_out}")
        retry_backoff(attempt + 1)
    assert code == 0, (
        f"timed_out={timed_out} (budget {timeout * _timeout_scale():.0f}s)",
        out[-2000:], err[-2000:])
    return out


def test_keras_mnist(tmp_path):
    tf = pytest.importorskip("tensorflow")  # noqa: F841
    out = _hvdrun(2, ["examples/keras/keras_mnist.py", "--epochs", "1"])
    assert "FINAL rank0 loss=" in out


def test_tensorflow2_synthetic_benchmark():
    tf = pytest.importorskip("tensorflow")  # noqa: F841
    out = _hvdrun(2, ["examples/tensorflow2/tensorflow2_synthetic_benchmark.py",
                      "--num-iters", "1", "--num-warmup-batches", "1",
                      "--num-batches-per-iter", "1", "--batch-size", "2",
                      "--image-size", "32"])
    assert "img/sec" in out.lower() or "images/sec" in out.lower()


def test_pytorch_imagenet_resnet50(tmp_path):
    torch = pytest.importorskip("torch")  # noqa: F841
    out = _hvdrun(2, ["examples/pytorch/pytorch_imagenet_resnet50.py",
                      "--epochs", "1", "--synthetic-batches", "2",
                      "--image-size", "32", "--batch-size", "2",
                      "--checkpoint-format",
                      str(tmp_path / "ck-{epoch}.pth.tar")])
    assert "epoch 0" in out


def test_adasum_bert_pretraining():
    # Two ranks each compile the BERT pretraining step — the heaviest
    # compile in the suite; the default 420 s budget is marginal even
    # before load scaling (sole failure of full runs 3 and 4).
    out = _hvdrun(2, ["examples/adasum/adasum_bert_pretraining.py",
                      "--steps", "3", "--batch-size", "2",
                      "--seq-len", "16"], timeout=900)
    assert "ADASUM BERT DONE" in out


def test_elastic_tensorflow2_resnet50(tmp_path):
    tf = pytest.importorskip("tensorflow")  # noqa: F841
    discover = tmp_path / "discover.sh"
    discover.write_text("#!/bin/sh\necho localhost:2\n")
    discover.chmod(0o755)
    out = _hvdrun(2, ["examples/elastic/tensorflow2_resnet50_elastic.py",
                      "--batches", "6", "--commit-every", "3",
                      "--batch-size", "2", "--image-size", "32"],
                  extra_cli=["--min-np", "1",
                             "--host-discovery-script", str(discover)])
    assert "ELASTIC RESNET DONE" in out


def test_jax_synthetic_wfbp_mode():
    """The overlapped-step flavor of the native example (docs/perf_r4.md):
    two ranks, XLA data plane, in-program gradient allreduce."""
    out = _hvdrun(
        2, ["examples/jax/jax_synthetic_benchmark.py", "--mode", "wfbp",
            "--batch-size", "4", "--image-size", "32",
            "--num-warmup-batches", "1", "--num-iters", "1",
            "--num-batches-per-iter", "2"],
        extra_cli=("--data-plane", "xla"), timeout=420)
    assert "Total img/sec" in out
