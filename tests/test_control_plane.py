"""Control-plane survivability: journal replay, torn-write recovery,
server restart, and driver crash-recovery (docs/control_plane.md).

The fast, in-process half of the survivability proof; the end-to-end
SIGKILL-and-restart chaos runs live in tests/test_fault_injection.py's
chaos lane.
"""

import json
import random
import shutil

import pytest

from horovod_tpu.transport.journal import (
    OP_DELETE,
    OP_SET,
    decode_op,
    encode_op,
    iter_frames,
    pack_frame,
)
from horovod_tpu.transport.store import (
    LEASE_SCOPE,
    DurableMemoryStore,
    HTTPStoreClient,
)
from horovod_tpu.runner.rendezvous import ExternalRendezvous, RendezvousServer


# ---------------------------------------------------------------------------
# frame / op encoding


class TestFrames:
    def test_op_roundtrip(self):
        for op, key, value in [(OP_SET, "scope/key", b"value"),
                               (OP_SET, "a/b", b""),
                               (OP_DELETE, "metrics/rank-0", b"")]:
            assert decode_op(encode_op(op, key, value)) == (op, key, value)

    def test_iter_frames_stops_at_crc_mismatch(self):
        blob = pack_frame(b"one") + pack_frame(b"two")
        corrupt = bytearray(blob)
        corrupt[-1] ^= 0xFF  # flip a byte of the second payload
        assert [p for _, p in iter_frames(bytes(corrupt))] == [b"one"]

    def test_iter_frames_rejects_absurd_length(self):
        import struct

        # A corrupt header claiming a huge payload must read as "torn",
        # not attempt the allocation.
        blob = struct.pack("<QI", 2 ** 62, 0) + b"x" * 64
        assert list(iter_frames(blob)) == []


# ---------------------------------------------------------------------------
# journal replay exactness


def _apply_random_ops(store, mirror, rng, n_ops):
    scopes = ["rank_and_size", "lease", "metrics"]
    for _ in range(n_ops):
        scope = rng.choice(scopes)
        key = f"k{rng.randrange(12)}"
        if rng.random() < 0.25 and mirror:
            flat = rng.choice(sorted(mirror))
            s, k = flat.split("/", 1)
            store.delete(s, k)
            mirror.pop(flat, None)
        else:
            value = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 64)))
            store.set(scope, key, value)
            mirror[f"{scope}/{key}"] = value


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_replay_equals_precrash_state_random_ops(tmp_path, seed):
    """Property: for a randomized op sequence (sets/deletes across scopes,
    with compactions forced every few ops), a fresh store over the same
    directory replays to the EXACT pre-close state."""
    rng = random.Random(seed)
    jdir = str(tmp_path / f"j{seed}")
    store = DurableMemoryStore(jdir, fsync=False,
                               snapshot_every=rng.choice([3, 7, 1000]))
    mirror = {}
    _apply_random_ops(store, mirror, rng, 120)
    store.close()

    recovered = DurableMemoryStore(jdir, fsync=False)
    assert recovered._data == mirror
    recovered.close()


def test_torn_write_every_offset_recovers_longest_prefix(tmp_path):
    """Truncate the journal at EVERY byte offset of the final record: the
    replay must recover exactly the state before that record — never
    misparse, never lose an earlier op (the PR-4 every-prefix fuzz
    discipline applied to the WAL)."""
    jdir = tmp_path / "j"
    store = DurableMemoryStore(str(jdir), fsync=False,
                               snapshot_every=10 ** 9)
    store.set("s", "a", b"alpha")
    store.set("s", "b", b"beta")
    store.delete("s", "a")
    state_before_final = dict(store._data)
    store.set("s", "final", b"the-final-record-payload")
    state_with_final = dict(store._data)
    store.close()

    jpath = jdir / "journal-00000000"
    blob = jpath.read_bytes()
    ends = [end for end, _ in iter_frames(blob)]
    assert ends[-1] == len(blob)
    final_start = ends[-2]

    # Sanity: the untruncated journal replays the full state.
    full = DurableMemoryStore(str(jdir), fsync=False)
    assert full._data == state_with_final
    full.close()

    for cut in range(final_start, len(blob)):
        case = tmp_path / f"cut{cut}"
        shutil.copytree(jdir, case)
        with open(case / "journal-00000000", "r+b") as f:
            f.truncate(cut)
        recovered = DurableMemoryStore(str(case), fsync=False)
        assert recovered._data == state_before_final, f"cut at {cut}"
        # The torn tail was truncated away: appending must extend the
        # valid prefix, not concatenate after garbage.
        recovered.set("s", "post", b"post-recovery")
        recovered.close()
        again = DurableMemoryStore(str(case), fsync=False)
        assert again._data == {**state_before_final,
                               "s/post": b"post-recovery"}, f"cut at {cut}"
        again.close()
        shutil.rmtree(case)


def test_aborted_compaction_falls_back_to_previous_generation(tmp_path):
    """A snapshot without its commit marker (crash mid-compaction) is
    ignored; the previous generation still holds every op."""
    jdir = tmp_path / "j"
    store = DurableMemoryStore(str(jdir), fsync=False, snapshot_every=5)
    for i in range(8):  # compacts at op 5 -> generation 1
        store.set("s", f"k{i}", b"v%d" % i)
    expect = dict(store._data)
    store.close()
    assert (jdir / "snap-00000001").exists()

    # Simulate a crash mid-compaction to generation 2: valid frames but
    # no SNAP_END commit marker, and no journal-2 yet.
    torn = pack_frame(b"HVDSNAP1") + pack_frame(
        encode_op(OP_SET, "s/k0", b"stale"))
    (jdir / "snap-00000002").write_bytes(torn)

    recovered = DurableMemoryStore(str(jdir), fsync=False)
    assert recovered._data == expect
    recovered.close()


def test_journal_disabled_is_plain_memory_store(tmp_path):
    store = DurableMemoryStore(None)
    store.set("s", "k", b"v")
    assert store.get("s", "k") == b"v"
    assert store.pop("s", "k") == b"v"
    assert store.pop("s", "k") is None
    store.close()
    assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------------------------------
# batched transactions (ISSUE 15 tentpole): atomic group journaling


def test_batch_group_torn_at_every_offset_is_all_or_nothing(tmp_path):
    """Truncate the journal at EVERY byte offset of a batched
    transaction's group frame: replay must land on exactly the pre-batch
    state (frame torn ⇒ NONE of the group's ops) or the post-batch state
    (frame intact ⇒ ALL of them) — a partially-applied batch must be
    unobservable at every single cut point."""
    jdir = tmp_path / "j"
    store = DurableMemoryStore(str(jdir), fsync=False,
                               snapshot_every=10 ** 9)
    store.set("s", "keep", b"keep-me")
    store.set("s", "doomed", b"delete-me")
    state_before = dict(store._data)
    results = store.batch([
        ("set", "s", "a", b"alpha"),
        ("set", "lease", "h0:0", b'{"renewals": 1}'),
        ("delete", "s", "doomed"),
        ("get", "s", "keep"),
        ("set", "s", "a", b"alpha-2"),  # same-key overwrite inside group
        ("keys", "s"),
    ])
    assert results[3] == b"keep-me"
    assert results[5] == ["a", "keep"]
    state_after = dict(store._data)
    assert state_after != state_before
    store.close()

    jpath = jdir / "journal-00000000"
    blob = jpath.read_bytes()
    ends = [end for end, _ in iter_frames(blob)]
    assert ends[-1] == len(blob)
    group_start = ends[-2]

    seen = set()
    for cut in range(group_start, len(blob) + 1):
        case = tmp_path / f"cut{cut}"
        shutil.copytree(jdir, case)
        with open(case / "journal-00000000", "r+b") as f:
            f.truncate(cut)
        recovered = DurableMemoryStore(str(case), fsync=False)
        if recovered._data == state_before:
            seen.add("none")
        elif recovered._data == state_after:
            seen.add("all")
        else:
            pytest.fail(f"partial batch visible at cut {cut}: "
                        f"{recovered._data}")
        recovered.close()
        shutil.rmtree(case)
    assert seen == {"none", "all"}


def test_batch_http_roundtrip_per_op_results(monkeypatch):
    """One signed ``POST /batch`` carries ordered PUT/GET/DELETE/KEYS and
    returns positional per-op results with the same semantics as the
    per-op routes."""
    monkeypatch.setenv("HOROVOD_SECRET_KEY", "cp-test-secret")
    server = RendezvousServer("127.0.0.1", job_secret=b"cp-test-secret")
    port = server.start()
    client = HTTPStoreClient("127.0.0.1", port)
    results = client.batch([
        ("set", "s", "a", b"1"),
        ("set", "s", "b", b"2"),
        ("get", "s", "a"),
        ("get", "s", "absent"),
        ("keys", "s"),
        ("delete", "s", "a"),
        ("delete", "s", "a"),  # second delete: already gone
        ("keys", "s"),
    ])
    assert results == [True, True, b"1", None, ["a", "b"],
                       True, False, ["b"]]
    assert client._batch_unsupported is False
    server.stop()


def test_batch_falls_back_per_op_against_old_protocol_server(monkeypatch):
    """A server without the /batch route (old protocol, or the knob held
    off for A/B) answers 404; the client degrades to per-op calls with
    identical results and remembers (sticky) not to retry /batch."""
    monkeypatch.setenv("HOROVOD_SECRET_KEY", "cp-test-secret")
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_BATCH", "0")  # server-side off
    server = RendezvousServer("127.0.0.1", job_secret=b"cp-test-secret")
    port = server.start()
    monkeypatch.delenv("HOROVOD_RENDEZVOUS_BATCH")  # client-side on
    client = HTTPStoreClient("127.0.0.1", port)
    ops = [("set", "s", "k", b"v"), ("get", "s", "k"), ("keys", "s"),
           ("delete", "s", "k"), ("get", "s", "k")]
    assert client.batch(ops) == [True, b"v", ["k"], True, None]
    assert client._batch_unsupported is True
    # Sticky: the second batch goes straight to per-op, still correct.
    assert client.batch([("set", "s", "x", b"y"), ("get", "s", "x")]) \
        == [True, b"y"]
    server.stop()


# ---------------------------------------------------------------------------
# host-level fan-in failure behavior (docs/control_plane.md)


def test_fanin_aggregator_death_degrades_to_direct_push(tmp_path):
    """The chaos property the fan-in must keep: peers spool only under a
    LIVE aggregator heartbeat; when the aggregator dies, submit() returns
    False within ~1.5 periods and the caller pushes directly — the host
    never goes silent, so no surviving rank's lease expires."""
    import time as time_mod

    from horovod_tpu.elastic.fanin import HostFanin
    from horovod_tpu.transport.store import MemoryStore

    store = MemoryStore()
    period = 0.05
    spool = str(tmp_path / "spool")
    agg = HostFanin(store, local_rank=0, period=period, spool_dir=spool)
    peer = HostFanin(store, local_rank=1, period=period, spool_dir=spool)

    def lease_op(rank, n):
        return ("set", LEASE_SCOPE, f"h0:{rank}",
                json.dumps({"renewals": n}).encode())

    # Before the aggregator's first forward there is no heartbeat:
    # the peer must push directly (False), not trust the spool.
    assert peer.submit([lease_op(1, 1)]) is False
    store.batch([lease_op(1, 1)])  # what the caller does on False

    # Aggregator forwards: its own ops + any spooled peer ops land in
    # ONE batch, and the heartbeat goes live.
    assert agg.submit([lease_op(0, 1)]) is True
    assert store.get(LEASE_SCOPE, "h0:0") is not None

    # Live aggregator: the peer's ops are spooled (True) and the NEXT
    # aggregator period delivers them.
    assert peer.submit([lease_op(1, 2)]) is True
    assert agg.submit([lease_op(0, 2)]) is True
    assert json.loads(store.get(LEASE_SCOPE, "h0:1"))["renewals"] == 2

    # An UNCHANGED spool is not re-forwarded: a dead peer's stale lease
    # must age out, not be renewed on its behalf.
    store.delete(LEASE_SCOPE, "h0:1")
    assert agg.submit([lease_op(0, 3)]) is True
    assert store.get(LEASE_SCOPE, "h0:1") is None

    # Aggregator dies (stops submitting): once the heartbeat goes stale
    # the peer degrades to direct pushes — no silence, no hang.
    time_mod.sleep(2.5 * period)
    assert peer.submit([lease_op(1, 3)]) is False
    store.batch([lease_op(1, 3)])
    assert json.loads(store.get(LEASE_SCOPE, "h0:1"))["renewals"] == 3


# ---------------------------------------------------------------------------
# server restart + keys endpoint


def test_server_restart_replays_state_and_serves_keys(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_SECRET_KEY", "cp-test-secret")
    jdir = str(tmp_path / "j")
    server = RendezvousServer("127.0.0.1", job_secret=b"cp-test-secret",
                              journal_dir=jdir)
    port = server.start()
    client = HTTPStoreClient("127.0.0.1", port)
    client.set("rank_and_size", "localhost:0", b'{"rank": 0}')
    client.set(LEASE_SCOPE, "localhost:0", b'{"renewals": 3}')
    client.set(LEASE_SCOPE, "otherhost:0", b'{"renewals": 1}')
    assert client.keys(LEASE_SCOPE) == ["localhost:0", "otherhost:0"]
    server.stop()  # SIGKILL-alike for state purposes: nothing flushed late

    server2 = RendezvousServer("127.0.0.1", job_secret=b"cp-test-secret",
                               journal_dir=jdir)
    port2 = server2.start()
    client2 = HTTPStoreClient("127.0.0.1", port2)
    assert client2.get("rank_and_size", "localhost:0") == b'{"rank": 0}'
    assert client2.keys(LEASE_SCOPE) == ["localhost:0", "otherhost:0"]
    assert client2.keys("empty_scope") == []
    server2.stop()


def test_external_rendezvous_adapter_matches_server_surface(tmp_path,
                                                            monkeypatch):
    monkeypatch.setenv("HOROVOD_SECRET_KEY", "cp-test-secret")
    server = RendezvousServer("127.0.0.1", job_secret=b"cp-test-secret")
    port = server.start()
    ext = ExternalRendezvous("127.0.0.1", port)
    assert ext.port == port
    ext.publish_slots([{
        "hostname": "localhost", "rank": 0, "local_rank": 0,
        "cross_rank": 0, "size": 1, "local_size": 1, "cross_size": 1,
        "epoch": 0,
    }])
    raw = ext.get("rank_and_size", "localhost:0")
    assert json.loads(raw.decode())["rank"] == 0
    assert ext.keys("rank_and_size") == ["localhost:0"]
    ext.stop()  # no-op: must NOT kill the external server
    assert ext.get("rank_and_size", "localhost:0") is not None
    server.stop()


# ---------------------------------------------------------------------------
# driver crash-recovery


def test_driver_recovers_epoch_and_readopts_leased_workers(tmp_path,
                                                           monkeypatch):
    """A restarted driver over a journaled store re-adopts the epoch and
    every live-leased identity instead of respawning the world."""
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import parse_hosts

    monkeypatch.delenv("HOROVOD_SECRET_KEY", raising=False)
    jdir = str(tmp_path / "j")
    hosts = "localhost:1,127.0.0.1:1"

    server = RendezvousServer("127.0.0.1", journal_dir=jdir)
    server.start()
    spawned = []
    driver = ElasticDriver(server,
                           HostManager(FixedHosts(parse_hosts(hosts))),
                           min_np=2, lease_timeout=60.0)
    driver.start(lambda slot, epoch: spawned.append(
        (f"{slot.hostname}:{slot.local_rank}", epoch)))
    assert sorted(spawned) == [("127.0.0.1:0", 0), ("localhost:0", 0)]
    # Workers renew their leases (what the metrics pusher does).
    for identity in ("localhost:0", "127.0.0.1:0"):
        server.set(LEASE_SCOPE, identity,
                   json.dumps({"renewals": 1, "epoch": 0}).encode())
    driver.stop()
    driver._discovery_thread.join(timeout=10)
    server.stop()  # driver + server die together (launcher crash)

    server2 = RendezvousServer("127.0.0.1", journal_dir=jdir)
    server2.start()
    spawned2 = []
    driver2 = ElasticDriver(server2,
                            HostManager(FixedHosts(parse_hosts(hosts))),
                            min_np=2, lease_timeout=60.0)
    assert driver2.recover_from_store() is True
    assert driver2.epoch == driver.epoch
    driver2.start(lambda slot, epoch: spawned2.append(
        (f"{slot.hostname}:{slot.local_rank}", epoch)))
    # Live-leased workers re-adopted: NOBODY respawned, epoch unchanged.
    assert spawned2 == []
    assert driver2.epoch == driver.epoch
    driver2.stop()
    driver2._discovery_thread.join(timeout=10)
    server2.stop()


def test_driver_recover_is_noop_on_fresh_store(tmp_path):
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import parse_hosts

    server = RendezvousServer("127.0.0.1")
    server.start()
    driver = ElasticDriver(server,
                           HostManager(FixedHosts(parse_hosts("localhost:1"))),
                           min_np=1)
    assert driver.recover_from_store() is False
    assert driver.epoch == 0
    server.stop()


# ---------------------------------------------------------------------------
# control-plane attribution (docs/observability.md)


def test_churn_attribution_covers_90pct_at_np8():
    """Acceptance floor for hvd-control-path: over a real np=8 churn run
    (traced server + traced driver-side client), the disjoint phase carve
    must explain at least 90% of every churn event's wall time."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "controller_sim", os.path.join(
            os.path.dirname(__file__), "..", "benchmarks",
            "controller_sim.py"))
    controller_sim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(controller_sim)

    rec = controller_sim.run_churn_case(8, events=3, trace=True)
    attr = rec["attribution"]
    assert attr["coverage"] >= 0.90, attr
    # The carve must name the dominant cost, not dump it in one bucket:
    # churn is HTTP round-trips with a real journal-fsync share.
    assert attr["phase_share"]["http_roundtrip"] > 0.3, attr
    assert attr["phase_share"]["journal_fsync"] > 0.0, attr
