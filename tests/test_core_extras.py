"""ResponseCache, ParameterManager, Adasum tests.

Mirrors the reference's split: cache/tuner logic unit-tested in-process
(`test/single/` style), Adasum numerics under real worker processes against
the closed-form operator (`test_adasum_pytorch.py` style).
"""

import numpy as np
import pytest

from horovod_tpu.core.messages import DataType, Request, RequestType
from horovod_tpu.core.parameter_manager import (
    _CODECS,
    _sign_test_p,
    BayesianOptimization,
    CodecArm,
    GaussianProcess,
    ParameterManager,
)
from horovod_tpu.core.response_cache import (
    CoordinatorCache,
    WorkerCacheMirror,
    cache_key,
)

from .helpers import run_distributed


def _req(name="t", shape=(4,), rank=1):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, tensor_type=DataType.FLOAT32,
                   tensor_shape=list(shape))


class TestResponseCache:
    def test_insert_lookup_rehydrate(self):
        cache = CoordinatorCache(capacity=8)
        bit, evicted = cache.maybe_insert(_req())
        assert bit == 0 and evicted == []
        assert cache.lookup(cache_key(_req())) == 0
        re = cache.rehydrate(0, rank=3)
        assert re.request_rank == 3 and re.tensor_name == "t"
        # same key again: no new assignment
        assert cache.maybe_insert(_req()) == (None, [])

    def test_shape_change_evicts_stale_entry(self):
        cache = CoordinatorCache(capacity=8)
        bit0, _ = cache.maybe_insert(_req(shape=(4,)))
        bit1, evicted = cache.maybe_insert(_req(shape=(8,)))
        assert evicted == [bit0] and bit1 != bit0
        # old bit resolves through the tombstone for a few cycles
        assert cache.rehydrate(bit0, rank=1) is not None
        for _ in range(5):
            cache.tick()
        assert cache.rehydrate(bit0, rank=1) is None

    def test_lru_eviction_and_mirror(self):
        cache = CoordinatorCache(capacity=2)
        mirror = WorkerCacheMirror()
        assignments, evictions = [], []
        for i in range(3):
            bit, ev = cache.maybe_insert(_req(name=f"t{i}"))
            assignments.append((bit, _req(name=f"t{i}")))
            evictions.extend(ev)
        assert len(cache) == 2 and evictions  # t0 evicted
        mirror.apply(assignments, evictions)
        assert mirror.hit(_req(name="t0")) is None
        assert mirror.hit(_req(name="t2")) is not None
        # mirror miss on changed shape
        assert mirror.hit(_req(name="t2", shape=(9,))) is None

    def test_uncacheable_ops_skipped(self):
        cache = CoordinatorCache(capacity=8)
        req = _req()
        req.request_type = RequestType.ALLGATHER
        assert cache.maybe_insert(req) == (None, [])


class TestParameterManager:
    def test_gp_regression_interpolates(self):
        gp = GaussianProcess(length_scale=0.5, noise=1e-6)
        x = np.array([[0.0, 0.0], [1.0, 1.0], [0.5, 0.5]])
        y = np.array([0.0, 1.0, 0.5])
        gp.fit(x, y)
        mu, sigma = gp.predict(np.array([[0.5, 0.5]]))
        assert abs(mu[0] - 0.5) < 0.05
        assert sigma[0] < 0.2

    def test_bo_suggestions_in_bounds(self):
        bo = BayesianOptimization(seed=1)
        for i in range(6):
            fusion_mb, cycle = bo.suggest()
            assert 0.0 <= fusion_mb <= 64.0
            assert 1.0 <= cycle <= 25.0
            bo.observe((fusion_mb, cycle), float(i))

    def test_manager_settles_on_best(self):
        pm = ParameterManager(enabled=True, warmup_samples=1,
                              steps_per_sample=2, max_samples=4)
        changes = []
        for _ in range(40):
            tuned = pm.update(nbytes=1 << 20)
            if tuned is not None:
                changes.append(tuned)
        assert changes, "tuner never moved"
        assert pm._done
        # settled values must be a previously suggested configuration
        assert pm.fusion_threshold_bytes >= 0
        assert 1.0 <= pm.cycle_time_ms <= 25.0
        # no further movement after settling
        assert pm.update(nbytes=1 << 20) is None



    def test_idle_cycles_do_not_advance_samples(self, tmp_path):
        """The background loop ticks every cycle_time_ms even when idle;
        zero-byte cycles must not close samples (else the tuner scores
        noise — reference parameter_manager.cc:148-159 steps by actual
        reductions)."""
        pm = ParameterManager(enabled=True, warmup_samples=0,
                              steps_per_sample=2, max_samples=2)
        for _ in range(50):
            assert pm.update(nbytes=0) is None
        assert pm._samples_seen == 0
        pm.update(nbytes=100)
        for _ in range(50):
            pm.update(nbytes=0)
        assert pm._samples_seen == 0      # still mid-sample
        assert pm.update(nbytes=100) is not None   # closes the sample
        assert pm._samples_seen == 1

    def test_idle_gap_not_billed_to_sample_score(self, monkeypatch):
        """An idle gap BETWEEN samples must not inflate the next sample's
        elapsed time: the clock restarts on the first counted step."""
        from horovod_tpu.core import parameter_manager as pm_mod

        now = [0.0]
        monkeypatch.setattr(pm_mod.time, "monotonic", lambda: now[0])
        pm = ParameterManager(enabled=True, warmup_samples=0,
                              steps_per_sample=2, max_samples=8)
        now[0] = 100.0                 # long idle gap after init
        for _ in range(5):
            pm.update(nbytes=0)        # idle ticks during the gap
        pm.update(nbytes=1000)         # first counted step: clock restarts
        now[0] = 101.0
        pm.update(nbytes=1000)         # closes the sample after 1s
        # score must be 2000 bytes / 1s, not 2000/101s
        assert pm._bo._ys, "sample was not observed"
        assert abs(pm._bo._ys[-1] - 2000.0) < 1.0, pm._bo._ys

    def test_sample_clock_pins_unbiased_rate(self, monkeypatch):
        """Regression for the ADVICE r5 N/(N-1) bias: from sample 2 on,
        the clock anchors at the PREVIOUS sample's close, so N counted
        steps score over N inter-step intervals.  The old first-step
        restart scored this scenario at 2000 bytes/s (2x) instead of
        1000."""
        from horovod_tpu.core import parameter_manager as pm_mod

        now = [0.0]
        monkeypatch.setattr(pm_mod.time, "monotonic", lambda: now[0])
        pm = ParameterManager(enabled=True, warmup_samples=0,
                              steps_per_sample=2, max_samples=8)
        # sample 1: counted steps at t=1, 2 (first-ever sample keeps the
        # first-step clock start — no earlier close exists)
        for t in (1.0, 2.0):
            now[0] = t
            pm.update(nbytes=1000)
        # sample 2: counted steps at t=3, 4 → 2000 bytes over the two
        # intervals since the t=2 close = exactly 1000 bytes/s.
        for t in (3.0, 4.0):
            now[0] = t
            pm.update(nbytes=1000)
        assert pm._bo._ys, "sample 2 was not observed"
        assert abs(pm._bo._ys[-1] - 1000.0) < 1e-6, pm._bo._ys

    def test_autotune_log_csv_artifact(self, tmp_path):
        """--autotune-log-file emits the per-sample CSV record family the
        reference writes via HOROVOD_AUTOTUNE_LOG
        (parameter_manager.h:112, .cc:81,266-291): a header naming the
        tunables, one row per sample with (params, score), and a final
        best row when the tuner settles."""
        log = tmp_path / "autotune.csv"
        pm = ParameterManager(enabled=True, warmup_samples=1,
                              steps_per_sample=2, max_samples=3,
                              log_path=str(log))
        for _ in range(40):
            pm.update(nbytes=1 << 20)
        assert pm._done
        lines = log.read_text().strip().splitlines()
        assert lines[0] == ("sample,cycle_time_ms,"
                            "tensor_fusion_threshold_mb,score_bytes_per_sec")
        samples, best = lines[1:-1], lines[-1]
        assert len(samples) == 4  # warmup + max_samples
        for i, row in enumerate(samples):
            idx, cycle, fusion_mb, score = row.split(",")
            assert int(idx) == i + 1
            assert 0.0 < float(cycle) <= 50.0
            assert float(fusion_mb) >= 0.0
            assert float(score) > 0.0
        b0, bcycle, bfusion, bscore = best.split(",")
        assert b0 == "best"
        # the settled params are what the manager now reports
        assert abs(float(bcycle) - pm.cycle_time_ms) < 0.01
        assert abs(float(bfusion)
                   - pm.fusion_threshold_bytes / 1048576.0) < 0.01

    def test_codec_sign_test_matches_ab_harness(self):
        """The local gate must be numerically identical to the PR-10 A/B
        harness sign test — one formula, two call sites."""
        from benchmarks.ab_harness import sign_test_p

        for wins in range(0, 12):
            for losses in range(0, 12):
                assert _sign_test_p(wins, losses) == \
                    sign_test_p(wins, losses), (wins, losses)

    def test_codec_dimension_default_off(self):
        """HOROVOD_AUTOTUNE_CODEC defaults off: no arm, baseline codec
        reported, and the established 4-column CSV schema untouched
        (test_autotune_log_csv_artifact asserts the header verbatim)."""
        pm = ParameterManager(enabled=True, warmup_samples=0,
                              steps_per_sample=1, max_samples=2)
        assert pm._codec_arm is None
        assert pm.codec_under_test == "none"
        for _ in range(5):
            pm.update(nbytes=1 << 20)
        assert pm.recommended_codec == "none"

    def test_codec_arm_pairs_baseline_then_candidate(self):
        """Samples alternate baseline/candidate and candidates rotate
        round-robin, so every codec keeps accruing sign-test pairs."""
        arm = CodecArm()
        seen = []
        for i in range(2 * len(_CODECS[1:])):
            seen.append(arm.under_test)
            arm.observe(100.0)
        assert seen[0::2] == ["none"] * len(_CODECS[1:])
        assert seen[1::2] == list(_CODECS[1:])

    def test_codec_recommended_only_on_significant_win(self):
        """A candidate needs a lopsided paired record to clear the gate:
        6-0 over "none" is p=0.03125 < 0.05 and is recommended; a 3-3
        split (p=1.0) and even a 4-1 edge (p=0.375) are not.  Ties are
        discarded, like the harness."""
        codecs = ("none", "int8")
        win6 = CodecArm(codecs=codecs)
        for _ in range(6):
            win6.observe(100.0)     # baseline
            win6.observe(150.0)     # candidate wins
        assert win6.recommendation() == ("int8", _sign_test_p(6, 0))

        split = CodecArm(codecs=codecs)
        for cand in (150.0, 150.0, 150.0, 50.0, 50.0, 50.0):
            split.observe(100.0)
            split.observe(cand)
        assert split.recommendation() == ("none", 1.0)

        edge = CodecArm(codecs=codecs)
        for cand in (150.0, 150.0, 150.0, 150.0, 50.0):
            edge.observe(100.0)
            edge.observe(cand)
        assert edge.recommendation() == ("none", 1.0)

        ties = CodecArm(codecs=codecs)
        for _ in range(20):
            ties.observe(100.0)
            ties.observe(100.0)     # tie: no pair recorded
        assert ties._wins["int8"] == 0 and ties._losses["int8"] == 0
        assert ties.recommendation() == ("none", 1.0)

    def test_codec_column_in_autotune_log(self, tmp_path):
        """With the arm on, every CSV row carries the codec the sample
        was attributed to and the best row carries the sign-test-gated
        verdict — the report-only surface the env knob promises."""
        log = tmp_path / "autotune.csv"
        pm = ParameterManager(enabled=True, warmup_samples=1,
                              steps_per_sample=2, max_samples=4,
                              log_path=str(log), tune_codec=True)
        for _ in range(40):
            pm.update(nbytes=1 << 20)
        assert pm._done
        lines = log.read_text().strip().splitlines()
        assert lines[0].endswith(",codec")
        for row in lines[1:-1]:
            assert row.split(",")[-1] in _CODECS
        best = lines[-1].split(",")
        assert best[0] == "best" and len(best) == 5
        assert best[-1] == pm.recommended_codec
        # Real cycles are near-identical in score; a significant codec
        # win cannot appear from a handful of noisy pairs.
        assert pm.recommended_codec == "none"

    def test_codec_knob_wires_into_state(self, monkeypatch):
        """HOROVOD_AUTOTUNE_CODEC=1 at init turns the arm on for the
        coordinator's manager (core/state.py wiring); without it the
        manager tunes but reports the baseline codec only."""
        import horovod_tpu.frameworks.jax.basics as basics
        from horovod_tpu.common import env as env_mod
        from horovod_tpu.core import state as state_mod

        monkeypatch.delenv("HOROVOD_SIZE", raising=False)
        monkeypatch.setenv(env_mod.HOROVOD_AUTOTUNE, "1")
        monkeypatch.setenv(env_mod.HOROVOD_AUTOTUNE_CODEC, "1")
        state_mod.reset_global_state()
        basics.init()
        try:
            pm = state_mod.global_state().parameter_manager
            assert pm is not None and pm._codec_arm is not None
            assert pm.codec_under_test == "none"   # baseline half first
        finally:
            state_mod.global_state().shutdown()
            state_mod.reset_global_state()


class TestStallInspector:
    """Coordinator-side stall inspector (``controller._check_stalls``):
    the shutdown path, the mask-path cached-tensor flavor, and the
    both-knobs-disabled early return."""

    def _controller(self, warn=0.0, shut=0.0, size=3, cache=1024):
        from horovod_tpu.common.topology import ProcessTopology
        from horovod_tpu.core.controller import Controller

        topo = ProcessTopology(rank=0, size=size, local_size=size)
        return Controller(topo, mesh=None, stall_warning_secs=warn,
                          stall_shutdown_secs=shut, cache_capacity=cache)

    def _age_everything(self, ctrl, by: float) -> None:
        """Backdate every stall clock so the next check sees `by` seconds
        of age without the test sleeping."""
        import time

        past = time.monotonic() - by
        ctrl._last_stall_check = past
        for entry in ctrl._message_table.values():
            entry.first_seen = past
        for bit in list(ctrl._mask_bit_since):
            ctrl._mask_bit_since[bit] = past

    def test_both_knobs_disabled_early_return(self):
        ctrl = self._controller(warn=0.0, shut=0.0)
        ctrl._increment(_req(name="stuck", rank=1))
        self._age_everything(ctrl, by=10_000.0)
        before = ctrl._last_stall_check
        ctrl._check_stalls()  # no raise, no clock advance: fully disabled
        assert ctrl._last_stall_check == before
        assert "stuck" in ctrl._message_table

    def test_shutdown_path_names_tensor_and_missing_ranks(self):
        from horovod_tpu.common.exceptions import HorovodInternalError

        ctrl = self._controller(warn=0.0, shut=5.0)
        ctrl._increment(_req(name="grad/w0", rank=1))  # ranks 0,2 missing
        self._age_everything(ctrl, by=6.0)
        with pytest.raises(HorovodInternalError) as ei:
            ctrl._check_stalls()
        msg = str(ei.value)
        assert "stall shutdown" in msg
        assert "grad/w0" in msg
        assert "[0, 2]" in msg, msg

    def test_shutdown_independent_of_disabled_warning(self):
        """Disabling warnings must not silently disable the hard abort."""
        from horovod_tpu.common.exceptions import HorovodInternalError

        ctrl = self._controller(warn=0.0, shut=1.0)
        ctrl._increment(_req(name="t", rank=1))
        self._age_everything(ctrl, by=2.0)
        with pytest.raises(HorovodInternalError):
            ctrl._check_stalls()

    def test_mask_path_cached_stall_shutdown_names_tensor(self):
        """A cache-bit announced by a subset of ranks ages past the
        shutdown deadline: the abort must name the CACHED tensor (via the
        coordinator cache template), not just a bit number."""
        from horovod_tpu.common.exceptions import HorovodInternalError
        from horovod_tpu.core.response_cache import cache_key

        ctrl = self._controller(warn=0.0, shut=5.0)
        bit, _ = ctrl._cache.maybe_insert(_req(name="cached/t", rank=0))
        ctrl._pending_masks[1] = 1 << bit  # rank 1 announced; 0,2 missing
        ctrl._mask_bit_since[bit] = 0.0
        self._age_everything(ctrl, by=6.0)
        with pytest.raises(HorovodInternalError) as ei:
            ctrl._check_stalls()
        msg = str(ei.value)
        assert "stall shutdown" in msg and "cached/t" in msg
        assert "[0, 2]" in msg, msg

    def test_mask_path_warning_converts_and_invalidates(self):
        """Below shutdown but past warning, a stalled cached bit converts
        its partial announcements into table tallies and evicts the cache
        entry so a post-recovery resubmission renegotiates from scratch."""
        ctrl = self._controller(warn=5.0, shut=0.0)
        bit, _ = ctrl._cache.maybe_insert(_req(name="cached/w", rank=0))
        ctrl._pending_masks[1] = 1 << bit
        ctrl._mask_bit_since[bit] = 0.0
        self._age_everything(ctrl, by=6.0)
        ctrl._check_stalls()
        # bit cleared from the mask path, tallied in the message table
        assert bit not in ctrl._mask_bit_since
        assert "cached/w" in ctrl._message_table
        assert ctrl._message_table["cached/w"].ranks == {1}
        # cache entry invalidated: the eviction is queued for broadcast
        assert bit in ctrl._cycle_evictions


def test_cache_steady_state_hits_and_correctness():
    """Same tensor allreduced across many steps: later steps ride the cache
    bit path and results stay exact."""
    out = run_distributed(2, """
from horovod_tpu.core.state import global_state

for step in range(6):
    val = np.full(8, float((rank + 1) * (step + 1)), np.float32)
    result = hvd.allreduce(val, op=hvd.Sum, name="grad.w")
    expected = (1 + 2) * (step + 1)
    assert np.allclose(np.asarray(result), expected), (step, result)

ctrl = global_state().controller
if rank != 0:
    assert ctrl.cache_hit_count > 0, "cache fast path never used"
    assert ctrl.cache_hit_count >= ctrl.cache_miss_count, (
        ctrl.cache_hit_count, ctrl.cache_miss_count)
print("CACHE_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"CACHE_OK {r}" in o


def test_adasum_two_rank_matches_formula():
    """VHDD with 2 ranks matches the closed-form Adasum operator computed
    on the FULL vectors: the per-level (dot, ||a||², ||b||²) triplets are
    allreduced across the reduction group before coefficients are formed
    (reference adasum.h:368 SumAllreduceWithComm), so slicing does not
    change the math."""
    out = run_distributed(2, """
a = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
b = np.array([2.0, 2.0, -1.0, 0.5], np.float32)
mine = a if rank == 0 else b
result = np.asarray(hvd.allreduce(mine, op=hvd.Adasum, name="adasum.t"))

def combine(x, y):
    dot = float(np.dot(x, y)); nx = float(np.dot(x, x)); ny = float(np.dot(y, y))
    cx = 1 - dot / (2 * nx) if nx > 0 else 1.0
    cy = 1 - dot / (2 * ny) if ny > 0 else 1.0
    return cx * x + cy * y

expected = combine(a, b)
assert np.allclose(result, expected, atol=1e-5), (result, expected)
print("ADASUM_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"ADASUM_OK {r}" in o


def test_adasum_four_rank_matches_formula():
    """4-rank VHDD: pairwise tree of full-vector combines — (r0⊕r1) ⊕
    (r2⊕r3) with global coefficients at both levels."""
    out = run_distributed(4, """
vecs = [np.array([1.0, 2.0, 3.0, 4.0], np.float32),
        np.array([2.0, 2.0, -1.0, 0.5], np.float32),
        np.array([-1.0, 0.5, 2.0, 1.0], np.float32),
        np.array([0.5, -2.0, 1.0, 3.0], np.float32)]
result = np.asarray(hvd.allreduce(vecs[rank], op=hvd.Adasum, name="adasum.q"))

def combine(x, y):
    x = x.astype(np.float64); y = y.astype(np.float64)
    dot = float(x @ y); nx = float(x @ x); ny = float(y @ y)
    cx = 1 - dot / (2 * nx) if nx > 0 else 1.0
    cy = 1 - dot / (2 * ny) if ny > 0 else 1.0
    return cx * x + cy * y

expected = combine(combine(vecs[0], vecs[1]), combine(vecs[2], vecs[3]))
assert np.allclose(result, expected, atol=1e-4), (result, expected)
print("ADASUM4_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"ADASUM4_OK {r}" in o


def test_adasum_zero_gradient_passthrough():
    """A zero gradient has coefficient 1.0 on the other side (reference
    adasum.h:385-391): adasum(0, g) == g, not g/2."""
    out = run_distributed(2, """
g = np.array([1.0, -2.0, 3.0], np.float32)
mine = np.zeros(3, np.float32) if rank == 0 else g
result = np.asarray(hvd.allreduce(mine, op=hvd.Adasum, name="adasum.z"))
assert np.allclose(result, g, atol=1e-5), result
print("ZERO_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"ZERO_OK {r}" in o


def test_adasum_identical_gradients_average():
    """Identical inputs are scale-halved (dot == ||a||²  → coefficient 1/2
    each): Adasum of equal gradients is their average."""
    out = run_distributed(2, """
val = np.full(6, 4.0, np.float32)
result = np.asarray(hvd.allreduce(val, op=hvd.Adasum, name="adasum.same"))
assert np.allclose(result, 4.0, atol=1e-5), result
print("SAME_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"SAME_OK {r}" in o


def test_autotune_end_to_end():
    """HOROVOD_AUTOTUNE tunes without breaking correctness; params move."""
    out = run_distributed(2, """
for step in range(30):
    v = np.full(64, float(rank + step), np.float32)
    r = hvd.allreduce(v, op=hvd.Sum, name="t")
    assert np.allclose(np.asarray(r), (0 + 1) + 2 * step), (step, r)
from horovod_tpu.core.state import global_state
st = global_state()
if rank == 0:
    assert st.parameter_manager is not None
    assert st.parameter_manager._samples_seen > 0, "tuner saw no samples"
print("TUNE_OK", rank, flush=True)
""", extra_env={"HOROVOD_AUTOTUNE": "1",
                "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "3"})
    for r, o in enumerate(out):
        assert f"TUNE_OK {r}" in o


def test_adasum_four_rank_identity():
    """adasum(a, a) == a at every VHDD level: 4 identical gradients pass
    through unchanged (exercises both distance rounds + allgather-back)."""
    out = run_distributed(4, """
val = np.arange(1, 9, dtype=np.float32)
result = np.asarray(hvd.allreduce(val, op=hvd.Adasum, name="adasum.id"))
assert np.allclose(result, val, atol=1e-5), (result, val)
print("ID_OK", rank, flush=True)
""")
    for r, o in enumerate(out):
        assert f"ID_OK {r}" in o


def test_adasum_odd_length_and_non_pow2_world():
    """5-element tensor pads through VHDD cleanly; 3-rank world falls back
    to the ring op (plain sum) instead of erroring."""
    out = run_distributed(2, """
a = np.array([1.0, 2.0, 3.0, 4.0, 5.0], np.float32)
result = np.asarray(hvd.allreduce(a, op=hvd.Adasum, name="adasum.odd"))
assert result.shape == (5,) and np.all(np.isfinite(result)), result
# identical inputs -> identity
assert np.allclose(result, a, atol=1e-5), result
print("ODD_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"ODD_OK {r}" in o

    out = run_distributed(3, """
v = np.ones(4, np.float32)
result = np.asarray(hvd.allreduce(v, op=hvd.Adasum, name="adasum.np2"))
# averaging ring fallback: identical gradients -> ~g, matching Adasum's
# identical-gradient behavior instead of a silent size-x sum
assert np.allclose(result, 1.0), result
print("NP2_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"NP2_OK {r}" in o
