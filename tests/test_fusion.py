"""Fusion: FIFO look-ahead response packing, allgather fusion, persistent
fusion buffers (reference ``controller.cc:859-998``,
``collective_operations.h:140-176``, ``fusion_buffer_manager.h``)."""

import numpy as np

from horovod_tpu.backend.cpu_ring import FusionBufferManager
from horovod_tpu.common.topology import ProcessTopology
from horovod_tpu.core.controller import Controller
from horovod_tpu.core.messages import DataType, Response, ResponseType

from .helpers import run_distributed


def _resp(rtype, name, sizes, dtype=DataType.FLOAT32, pre=1.0, post=1.0):
    return Response(response_type=rtype, tensor_names=[name],
                    tensor_type=dtype, tensor_sizes=list(sizes),
                    devices=[-1], prescale_factor=pre, postscale_factor=post)


def _controller(threshold=1 << 20):
    topo = ProcessTopology(rank=0, size=1, local_rank=0, local_size=1,
                           cross_rank=0, cross_size=1)
    return Controller(topo, None, fusion_threshold_bytes=threshold)


def test_lookahead_fuses_interleaved_dtypes():
    """f32, bf16, f32 → the two f32 responses fuse despite the interloper
    (VERDICT weak #7: previous-only merging was defeated by interleaving)."""
    c = _controller()
    out = c._fuse_responses([
        _resp(ResponseType.ALLREDUCE, "a", [10]),
        _resp(ResponseType.ALLREDUCE, "b", [10], dtype=DataType.BFLOAT16),
        _resp(ResponseType.ALLREDUCE, "c", [10]),
    ])
    assert len(out) == 2
    assert out[0].tensor_names == ["a", "c"]
    assert out[0].tensor_sizes == [10, 10]
    assert out[1].tensor_names == ["b"]


def test_lookahead_respects_threshold_and_scales():
    c = _controller(threshold=100)  # 25 f32 elements
    out = c._fuse_responses([
        _resp(ResponseType.ALLREDUCE, "a", [20]),
        _resp(ResponseType.ALLREDUCE, "b", [20]),   # would exceed 100B
        _resp(ResponseType.ALLREDUCE, "c", [5]),    # fits with a
        _resp(ResponseType.ALLREDUCE, "d", [5], post=0.5),  # scale differs
    ])
    names = [r.tensor_names for r in out]
    assert names == [["a", "c"], ["b", "d"]] or names == [["a", "c"], ["b"], ["d"]]
    # b and d must NOT fuse (mismatched postscale), even though both fit
    for r in out:
        if "b" in r.tensor_names:
            assert "d" not in r.tensor_names


def test_allgather_responses_fuse():
    c = _controller()
    out = c._fuse_responses([
        _resp(ResponseType.ALLGATHER, "x", [2, 3]),   # per-rank dim0s, size 2
        _resp(ResponseType.ALLGATHER, "y", [1, 1]),
    ])
    assert len(out) == 1
    assert out[0].tensor_names == ["x", "y"]
    assert out[0].tensor_sizes == [2, 3, 1, 1]


def test_broadcast_never_fuses():
    c = _controller()
    out = c._fuse_responses([
        _resp(ResponseType.BROADCAST, "p", [4]),
        _resp(ResponseType.BROADCAST, "q", [4]),
    ])
    assert len(out) == 2


def test_fusion_buffer_manager_reuses_storage():
    fbm = FusionBufferManager()
    a = fbm.get(np.dtype(np.float32), 100)
    b = fbm.get(np.dtype(np.float32), 50)
    assert b.base is a.base or b.base is a  # same arena
    big = fbm.get(np.dtype(np.float32), 200)  # grows
    assert big.size == 200
    other = fbm.get(np.dtype(np.int64), 10)   # separate per dtype
    assert other.dtype == np.int64


def test_fused_allgather_multiprocess():
    """Two variable-dim0 allgathers submitted together fuse into one
    response and both come back correct (block slicing by the per-tensor
    per-rank matrix)."""
    out = run_distributed(2, """
import horovod_tpu.frameworks.jax.ops as ops

# x: rank 0 contributes 1 row, rank 1 contributes 2 rows
x = np.full((rank + 1, 3), float(rank), np.float32)
# y: fixed shape, rank-dependent values
y = np.arange(4, dtype=np.float32) + 10 * rank
hx = ops.allgather_async(x, name="fx")
hy = ops.allgather_async(y, name="fy")
ox = np.asarray(ops.synchronize(hx))
oy = np.asarray(ops.synchronize(hy))
exp_x = np.concatenate([np.full((1, 3), 0.0), np.full((2, 3), 1.0)])
exp_y = np.concatenate([np.arange(4), np.arange(4) + 10]).astype(np.float32)
assert ox.shape == (3, 3) and np.allclose(ox, exp_x), ox
assert np.allclose(oy, exp_y), oy
print("FAG_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"FAG_OK {r}" in o


def test_persistent_buffer_outputs_survive_reuse():
    """Outputs of a fused response must not alias the persistent staging
    buffer: a later fused response reuses it."""
    out = run_distributed(2, """
import horovod_tpu.frameworks.jax.ops as ops

h1 = ops.allreduce_async(np.ones(1000, np.float32), name="p1", op=hvd.Sum)
h2 = ops.allreduce_async(np.full(1000, 2.0, np.float32), name="p2", op=hvd.Sum)
first_a = np.asarray(ops.synchronize(h1))
b = np.asarray(ops.synchronize(h2))
# second fused batch overwrites the staging arena with new values
h3 = ops.allreduce_async(np.full(1000, 7.0, np.float32), name="p3", op=hvd.Sum)
h4 = ops.allreduce_async(np.full(1000, 9.0, np.float32), name="p4", op=hvd.Sum)
ops.synchronize(h3); ops.synchronize(h4)
assert np.allclose(first_a, 2.0), first_a[:3]
assert np.allclose(b, 4.0), b[:3]
print("PBUF_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"PBUF_OK {r}" in o


def test_xla_fused_allgather_single_dispatch():
    """A fused (multi-entry) allgather response rides ONE device
    collective (VERDICT r2 #7: the per-entry dispatch loop contradicted
    the fusion the controller sets up)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from horovod_tpu.backend import xla as X
    from horovod_tpu.common.topology import ProcessTopology
    from horovod_tpu.core.messages import Response, ResponseType, DataType
    from horovod_tpu.core.tensor_queue import TensorTableEntry

    ctx = X.context()
    topo = ProcessTopology(rank=0, size=1, local_rank=0, local_size=1,
                           cross_rank=0, cross_size=1)
    ctx.initialize(topo)
    assert ctx.ready

    entries = [
        TensorTableEntry(tensor_name="a", tensor=jnp.arange(6, dtype=jnp.float32).reshape(3, 2)),
        TensorTableEntry(tensor_name="b", tensor=jnp.arange(4, dtype=jnp.float32).reshape(4, 1)),
    ]
    resp = Response(response_type=ResponseType.ALLGATHER,
                    tensor_names=["a", "b"],
                    tensor_type=DataType.FLOAT32,
                    tensor_sizes=[3, 4],  # per-rank dim0s, 1 rank
                    devices=[X.XLA_DEVICE_ID])
    op = X.XlaAllgather(topo)
    before = X.stats.get("allgather", 0)
    status = op.execute(resp, entries)
    assert status.pending and status.eager_complete
    assert X.stats.get("allgather", 0) == before + 1  # ONE dispatch
    assert entries[0].output.shape == (3, 2)
    assert entries[1].output.shape == (4, 1)
    import numpy as np
    assert np.allclose(np.asarray(entries[0].output),
                       np.arange(6).reshape(3, 2))
    assert np.allclose(np.asarray(entries[1].output),
                       np.arange(4).reshape(4, 1))


# ---------------------------------------------------------------------------
# readiness-ordered fusion (HOROVOD_FUSION_ORDER)
# ---------------------------------------------------------------------------


def _coordinator_np2(monkeypatch, order):
    from horovod_tpu.common import env as env_mod

    monkeypatch.setenv(env_mod.HOROVOD_FUSION_ORDER, order)
    topo = ProcessTopology(rank=0, size=2, local_rank=0, local_size=2,
                           cross_rank=0, cross_size=1)
    # mesh=None is fine: _gather_request_lists is patched per cycle; the
    # cache fast path is off so no compact frames are broadcast either.
    return Controller(topo, None, cache_capacity=0)


def _drive_two_cycles(monkeypatch, c):
    """Cycle 1: rank 0 announces "late_first" (incomplete — rank 1 silent).
    Cycle 2: rank 0 announces "early_second"; rank 1 announces BOTH, with
    "early_second" first — so arrival (completion-scan) order within cycle
    2 is [early_second, late_first], while readiness (first_seen) order is
    [late_first, early_second]."""
    from horovod_tpu.core.messages import Request, RequestList

    def req(name, rank):
        return Request(request_rank=rank, tensor_name=name,
                       tensor_shape=[8])

    monkeypatch.setattr(c, "_gather_request_lists",
                        lambda: iter([(1, RequestList(), False)]))
    monkeypatch.setattr(c, "_broadcast_response_payload",
                        lambda payload: None)
    rl1 = c._coordinator_round([req("late_first", 0)], False)
    assert not rl1.responses  # still waiting on rank 1

    monkeypatch.setattr(
        c, "_gather_request_lists",
        lambda: iter([(1, RequestList(requests=[
            req("early_second", 1), req("late_first", 1)]), False)]))
    rl2 = c._coordinator_round([req("early_second", 0)], False)
    return [n for r in rl2.responses for n in r.tensor_names]


def test_readiness_order_puts_oldest_negotiation_first(monkeypatch):
    from horovod_tpu.core import metrics

    c = _coordinator_np2(monkeypatch, "readiness")
    before = metrics.registry.get_counter("fusion_reorders_total")
    names = _drive_two_cycles(monkeypatch, c)
    assert names == ["late_first", "early_second"], names
    after = metrics.registry.get_counter("fusion_reorders_total")
    assert after == before + 1


def test_arrival_order_keeps_completion_scan_order(monkeypatch):
    c = _coordinator_np2(monkeypatch, "arrival")
    names = _drive_two_cycles(monkeypatch, c)
    assert names == ["early_second", "late_first"], names


def test_fusion_order_knob_validates(monkeypatch):
    from horovod_tpu.common import env as env_mod
    import pytest as _pytest

    monkeypatch.setenv(env_mod.HOROVOD_FUSION_ORDER, "fifo")
    topo = ProcessTopology(rank=0, size=1, local_rank=0, local_size=1,
                           cross_rank=0, cross_size=1)
    with _pytest.raises(ValueError, match="HOROVOD_FUSION_ORDER"):
        Controller(topo, None)
