"""WFBP overlap tests: microbatch-pipelined enqueue + in-program step.

Reference analog: WFBP hook scheduling in ``torch/optimizer.py:103-149``,
verified there by ``test/parallel/test_torch.py`` gradient-equivalence
cases.  Here: (a) overlap=True is bit-equivalent to accumulate-then-reduce
(linearity), (b) the compiled overlapped step trains identically to
single-process training on the concatenated batch (sync-DP equivalence),
(c) misuse raises.
"""


import numpy as np
import pytest

from .helpers import run_distributed


def _xla_env() -> dict:
    from .helpers import reserve_port

    port = reserve_port()
    return {
        "HOROVOD_DATA_PLANE": "xla",
        "HOROVOD_JAX_COORDINATOR": f"127.0.0.1:{port}",
    }


def test_overlap_requires_multiple_backward_passes():
    import optax

    from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer

    with pytest.raises(ValueError, match="backward_passes_per_step"):
        DistributedOptimizer(optax.sgd(0.1), overlap=True)
    with pytest.raises(ValueError, match="Adasum"):
        DistributedOptimizer(optax.sgd(0.1), op="adasum",
                             backward_passes_per_step=2, overlap=True)


def test_overlap_matches_accumulate_two_ranks():
    """overlap=True and the plain bpps path produce identical updates:
    allreduce is linear, so reduce-every-microbatch == reduce-the-sum."""
    out = run_distributed(2, """
import jax
import jax.numpy as jnp
import optax
from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer

params = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
          "b": jnp.ones(3, jnp.float32)}
# rank-dependent microbatch gradients
def g(mb):
    return {"w": jnp.full((2, 3), float(rank + 1 + mb)),
            "b": jnp.full(3, float(10 * rank + mb))}

results = {}
for overlap in (False, True):
    tx = optax.sgd(0.1, momentum=0.9)
    dopt = DistributedOptimizer(tx, backward_passes_per_step=3,
                                overlap=overlap)
    st = dopt.init(params)
    p = params
    for step in range(2):          # two full accumulation windows
        for mb in range(3):
            upd, st = dopt.update(g(mb), st, p)
            p = optax.apply_updates(p, upd)
    results[overlap] = p

for k in results[False]:
    a = np.asarray(results[False][k])
    b = np.asarray(results[True][k])
    assert np.allclose(a, b, atol=1e-6), (k, a, b)
print("OVERLAP_EQ_OK", rank, flush=True)
""", timeout=240)
    for r, o in enumerate(out):
        assert f"OVERLAP_EQ_OK {r}" in o


def test_overlapped_step_single_process():
    """np=1 smoke: the compiled overlapped step runs, loss decreases, and
    matches plain optax exactly (size-1 mesh, allreduce is identity)."""
    out = run_distributed(1, """
import jax
import jax.numpy as jnp
import optax
from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step

def loss_fn(params, batch):
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2)

rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(4, 2), jnp.float32)}
tx = optax.sgd(0.05)
batches = [{"x": jnp.asarray(rng.randn(8, 4), jnp.float32),
            "y": jnp.asarray(rng.randn(8, 2), jnp.float32)}
           for _ in range(5)]

step = make_overlapped_train_step(loss_fn, tx)
p, s = step.init(params, tx.init(params))
losses = []
for b in batches:
    p, s, loss = step(p, s, b)
    losses.append(float(np.asarray(loss)))
assert losses[-1] < losses[0], losses

# exact match vs plain optax
p2, s2 = params, tx.init(params)
fn = jax.jit(lambda p, s, b: (lambda l, g: (optax.apply_updates(
    p, tx.update(g, s, p)[0]), tx.update(g, s, p)[1], l))(
    *jax.value_and_grad(loss_fn)(p, b)))
for b in batches:
    p2, s2, _ = fn(p2, s2, b)
got = np.asarray(step.fetch(p)["w"])
exp = np.asarray(p2["w"])
assert np.allclose(got, exp, atol=1e-6), (got, exp)
print("WFBP_STEP_OK", rank, flush=True)
""", timeout=240)
    assert "WFBP_STEP_OK 0" in out[0]


def test_overlapped_step_has_aux():
    """Aux state (flax batch_stats shape) threads through the compiled
    step and matches a hand-rolled update."""
    out = run_distributed(1, """
import jax
import jax.numpy as jnp
import optax
from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step

def loss_fn(p, aux, b):
    pred = b["x"] @ p["w"]
    new_aux = {"ema": 0.9 * aux["ema"] + 0.1 * jnp.mean(pred)}
    return jnp.mean((pred - b["y"]) ** 2), new_aux

rng = np.random.RandomState(1)
params = {"w": jnp.asarray(rng.randn(3, 2), jnp.float32)}
aux = {"ema": jnp.zeros(())}
tx = optax.sgd(0.1)
step = make_overlapped_train_step(loss_fn, tx, has_aux=True)
p, s, a = step.init(params, tx.init(params), aux)
b = {"x": jnp.asarray(rng.randn(4, 3), jnp.float32),
     "y": jnp.asarray(rng.randn(4, 2), jnp.float32)}
for _ in range(3):
    p, s, a, loss = step(p, s, b, a)

# manual reference
p2, a2, s2 = params, aux, tx.init(params)
for _ in range(3):
    (l, a2), g = jax.value_and_grad(loss_fn, has_aux=True)(p2, a2, b)
    upd, s2 = tx.update(g, s2, p2)
    p2 = optax.apply_updates(p2, upd)
assert np.allclose(np.asarray(step.fetch(p)["w"]), np.asarray(p2["w"]),
                   atol=1e-6)
assert np.allclose(np.asarray(step.fetch(a)["ema"]),
                   np.asarray(a2["ema"]), atol=1e-6)
print("WFBP_AUX_OK", rank, flush=True)
""", timeout=240)
    assert "WFBP_AUX_OK 0" in out[0]


def test_overlapped_step_matches_big_batch_two_ranks():
    """Sync-DP equivalence: two ranks on half-batches through the
    overlapped step == one process on the full batch.  The in-program
    allreduce must therefore compute the exact global-mean gradient."""
    out = run_distributed(2, """
import jax
import jax.numpy as jnp
import optax
from horovod_tpu.backend.xla import context
from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step
assert context().ready, "XLA data plane required"

def loss_fn(params, batch):
    pred = jnp.tanh(batch["x"] @ params["w1"]) @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)

rng = np.random.RandomState(7)
params = {"w1": jnp.asarray(rng.randn(4, 8) * 0.3, jnp.float32),
          "w2": jnp.asarray(rng.randn(8, 2) * 0.3, jnp.float32)}
X = rng.randn(4, 6, 4).astype(np.float32)   # [steps, global_batch, d]
Y = rng.randn(4, 6, 2).astype(np.float32)

tx = optax.sgd(0.1, momentum=0.9)
step = make_overlapped_train_step(loss_fn, tx)
p, s = step.init(params, tx.init(params))
lo = rank * 3
for i in range(4):
    b = {"x": jnp.asarray(X[i, lo:lo + 3]), "y": jnp.asarray(Y[i, lo:lo + 3])}
    p, s, loss = step(p, s, b)
got = {k: np.asarray(v) for k, v in step.fetch(p).items()}

# single-process reference on the full batch
p2, s2 = params, tx.init(params)
vg = jax.jit(jax.value_and_grad(loss_fn))
for i in range(4):
    _, g = vg(p2, {"x": jnp.asarray(X[i]), "y": jnp.asarray(Y[i])})
    upd, s2 = tx.update(g, s2, p2)
    p2 = optax.apply_updates(p2, upd)
for k in got:
    exp = np.asarray(p2[k])
    assert np.allclose(got[k], exp, atol=1e-5), (k, got[k], exp)
print("WFBP_DP_OK", rank, flush=True)
""", timeout=300, extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"WFBP_DP_OK {r}" in o


def test_overlapped_step_signature_divergence_raises():
    """A rank tracing a different program shape must fail loudly up front
    (the negotiation-plane signature check), not hang in the collective."""
    out = run_distributed(2, """
import jax.numpy as jnp
import optax
from horovod_tpu.backend.xla import context
from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step
assert context().ready

def loss_fn(params, batch):
    return jnp.mean((batch["x"] @ params["w"]) ** 2)

w_cols = 2 if rank == 0 else 3        # divergent param shapes
params = {"w": jnp.ones((4, w_cols), jnp.float32)}
tx = optax.sgd(0.1)
step = make_overlapped_train_step(loss_fn, tx)
p, s = step.init(params, tx.init(params))
try:
    step(p, s, {"x": jnp.ones((2, 4), jnp.float32)})
except RuntimeError as e:
    assert "diverged" in str(e), e
    print("WFBP_SIG_OK", rank, flush=True)
else:
    print("WFBP_SIG_MISSED", rank, flush=True)
""", timeout=300, extra_env=_xla_env())
    for r, o in enumerate(out):
        assert f"WFBP_SIG_OK {r}" in o

@pytest.mark.smoke
def test_abandoned_window_drain_is_nonblocking(monkeypatch):
    """Evicting an abandoned overlap window must never block update()
    (ADVICE r4 medium): a handle that never completes is handed to the
    background drainer and force-discarded after its deadline — the
    training path returns immediately."""
    import time

    from horovod_tpu.frameworks.jax import ops, optimizer

    # A handle nobody will ever complete (the asymmetric-abandonment case).
    stuck = ops._handles.allocate()
    # And one already completed: the drainer must release it promptly.
    done = ops._handles.allocate()
    from horovod_tpu.core.tensor_queue import Status
    ops._handles.mark_done(done, Status.OK(), "result")

    t0 = time.monotonic()
    optimizer._drain_handles_async([stuck, done], timeout_s=1.5)
    assert time.monotonic() - t0 < 0.5, "drain hand-off must not block"

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with ops._handles._lock:
            gone = (stuck not in ops._handles._events
                    and done not in ops._handles._events)
        if gone:
            break
        time.sleep(0.2)
    with ops._handles._lock:
        assert stuck not in ops._handles._events, "stuck handle not discarded"
        assert done not in ops._handles._events, "done handle not released"
        assert stuck not in ops._handles._done
        assert done not in ops._handles._done

    # A callback that fires AFTER the discard must not resurrect the entry.
    ops._handles.mark_done(stuck, Status.OK(), "late")
    with ops._handles._lock:
        assert stuck not in ops._handles._done


@pytest.mark.smoke
def test_optimizer_instances_get_distinct_wire_names(monkeypatch):
    """Two DistributedOptimizer instances in one process must enqueue
    under distinct wire-name prefixes (ADVICE r4: identical names across
    instances break concurrent training states loudly)."""
    import jax.numpy as jnp
    import optax

    from horovod_tpu.frameworks.jax import ops, optimizer, wfbp

    recorded = []

    def fake_async(tensor, name=None, op=None, **kw):
        recorded.append(name)
        h = ops._handles.allocate()
        from horovod_tpu.core.tensor_queue import Status
        ops._handles.mark_done(h, Status.OK(), tensor)
        return h

    monkeypatch.setattr(wfbp.ops, "allreduce_async", fake_async)
    monkeypatch.setattr(optimizer.ops, "initialized", lambda: True)

    grads = {"w": jnp.ones((2, 2), jnp.float32)}
    names = {}
    for i in range(2):
        recorded.clear()
        d = optimizer.DistributedOptimizer(optax.sgd(0.1))
        st = d.init(grads)
        d.update(grads, st, grads)
        assert recorded, "no enqueue recorded"
        names[i] = set(recorded)
    assert names[0] and names[1]
    assert names[0].isdisjoint(names[1]), (names, "wire names collide "
                                           "across optimizer instances")


@pytest.mark.smoke
def test_timeout_scale_env_is_floor(monkeypatch):
    """HVD_TEST_TIMEOUT_SCALE is a FLOOR: a loaded bare host can scale
    past it (ADVICE r4 low — it used to be a fixed override)."""
    from . import helpers

    monkeypatch.setenv("HVD_TEST_TIMEOUT_SCALE", "3")
    monkeypatch.setattr(helpers.os, "getloadavg", lambda: (20.0, 0, 0))
    monkeypatch.setattr(helpers.os, "cpu_count", lambda: 2)
    assert helpers._timeout_scale() == 6.0  # load wins, capped at 6

    monkeypatch.setattr(helpers.os, "getloadavg", lambda: (0.0, 0, 0))
    assert helpers._timeout_scale() == 3.0  # floor wins on idle/containers

    monkeypatch.delenv("HVD_TEST_TIMEOUT_SCALE")
    assert helpers._timeout_scale() == 1.0
