"""Same-session A/B benchmark harness with a noise-aware verdict.

Generalizes ``allreduce_bench.py``'s interleaved-medians idiom (the box's
bench-noise discipline: ±20% run-to-run drift, so variants are sampled
A B, A B, ... and only medians compared) into a reusable gate:

1. run control and candidate configs INTERLEAVED for ``--repeats`` pairs,
2. report the median step time of each,
3. issue a verdict from a paired **sign test**: count the pairs where the
   candidate beat its same-pair control; under the no-difference null the
   count is Binomial(n, ½), and a two-sided p-value below ``--alpha``
   declares "improvement" or "regression" — anything else is
   "no significant difference".  Medians say *how big*, the sign test
   says *whether it's real*; a shared box's slow drift hits both arms of
   a pair equally, which is the whole point of interleaving.

With the defaults (6 pairs, α=0.05) a unanimous 6/6 sweep is the only
significant outcome (p = 2·(½)⁶ ≈ 0.031) — deliberately conservative for
a noisy box.

The workload is the eager-allreduce step (``allreduce_bench._measure``:
slowest-rank per-step seconds at a given payload × world size); control
and candidate differ only in environment overlays.

Usage::

    python benchmarks/ab_harness.py --label aa            # A/A null check
    python benchmarks/ab_harness.py --label crc-off \\
        --candidate HOROVOD_WIRE_CRC=0 \\
        --out benchmarks/results/ab_crc_off.json
    python benchmarks/ab_harness.py --label rank1-delay \\
        --candidate "HOROVOD_FAULT_SPEC=enqueue.collective:rank=1:action=delay_ms,5"

``ci/bench_gate.sh`` runs the A/A and an injected-slowdown case and
asserts the two verdicts; artifacts land in ``benchmarks/results/ab_*.json``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import sys
from typing import Callable, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def sign_test_p(wins: int, losses: int) -> float:
    """Two-sided paired sign-test p-value (ties already excluded): the
    probability, under Binomial(n, ½), of a split at least this lopsided."""
    n = wins + losses
    if n == 0:
        return 1.0
    k = min(wins, losses)
    tail = sum(math.comb(n, i) for i in range(k + 1)) / 2.0 ** n
    return min(1.0, 2.0 * tail)


def ab_compare(measure: Callable[[Optional[Dict[str, str]]], float],
               control_env: Optional[Dict[str, str]],
               candidate_env: Optional[Dict[str, str]],
               repeats: int = 6, alpha: float = 0.05) -> dict:
    """Interleaved paired comparison; ``measure(env)`` returns one step
    time in seconds.  Returns the verdict record (see module docstring)."""
    pairs: List[tuple] = []
    for _ in range(repeats):
        a = measure(control_env)
        b = measure(candidate_env)
        pairs.append((a, b))
    med_a = statistics.median(a for a, _ in pairs)
    med_b = statistics.median(b for _, b in pairs)
    wins = sum(1 for a, b in pairs if b < a)     # candidate faster
    losses = sum(1 for a, b in pairs if b > a)   # candidate slower
    p = sign_test_p(wins, losses)
    if p < alpha:
        verdict = "improvement" if wins > losses else "regression"
    else:
        verdict = "no significant difference"
    return {
        "metric": "ab_compare",
        "repeats": repeats,
        "alpha": alpha,
        "median_control_ms": round(med_a * 1e3, 3),
        "median_candidate_ms": round(med_b * 1e3, 3),
        "candidate_over_control": round(med_b / med_a, 3),
        "wins": wins,
        "losses": losses,
        "ties": repeats - wins - losses,
        "p_value": round(p, 5),
        "verdict": verdict,
        "samples_ms": {
            "control": [round(a * 1e3, 3) for a, _ in pairs],
            "candidate": [round(b * 1e3, 3) for _, b in pairs],
        },
    }


def _parse_env(items: List[str]) -> Dict[str, str]:
    env = {}
    for item in items:
        if "=" not in item:
            raise SystemExit(f"ab_harness: --control/--candidate entries "
                             f"must be KEY=VALUE, got {item!r}")
        k, v = item.split("=", 1)
        env[k] = v
    return env


def main() -> int:
    p = argparse.ArgumentParser(
        description="same-session interleaved A/B gate over the eager "
                    "allreduce step (docs/observability.md)")
    p.add_argument("--label", required=True,
                   help="short name for this comparison (artifact key)")
    p.add_argument("--control", nargs="*", default=[], metavar="K=V",
                   help="env overlay for the control arm (default: none)")
    p.add_argument("--candidate", nargs="*", default=[], metavar="K=V",
                   help="env overlay for the candidate arm")
    p.add_argument("--nbytes", type=int, default=1 << 22,
                   help="allreduce payload bytes (default: 4 MiB)")
    p.add_argument("--np", dest="np_", type=int, default=2)
    p.add_argument("--rounds", type=int, default=10,
                   help="allreduce rounds per sample")
    p.add_argument("--repeats", type=int, default=6,
                   help="interleaved A/B pairs (6 ⇒ only a unanimous "
                        "sweep is significant at the default alpha)")
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--out", default=None,
                   help="write the verdict record to this JSON file")
    args = p.parse_args()

    import allreduce_bench

    # allreduce_bench is imported from benchmarks/ (not run as __main__),
    # so its _worker would pickle BY REFERENCE — and the spawned workers
    # cannot import a module that only exists on this process's sys.path.
    # Ship it by value instead.
    try:
        import cloudpickle
        cloudpickle.register_pickle_by_value(allreduce_bench)
    except (ImportError, AttributeError):
        pass

    def measure(env):
        return allreduce_bench._measure(args.nbytes, args.np_, args.rounds,
                                        env)

    rec = ab_compare(measure, _parse_env(args.control) or None,
                     _parse_env(args.candidate) or None,
                     repeats=args.repeats, alpha=args.alpha)
    rec.update({
        "label": args.label,
        "control_env": _parse_env(args.control),
        "candidate_env": _parse_env(args.candidate),
        "payload_bytes": args.nbytes,
        "world_size": args.np_,
        "rounds": args.rounds,
        "host_cpus": os.cpu_count(),
    })
    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
