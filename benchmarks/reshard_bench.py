"""Churn-to-first-step A/B: live reshard vs. the legacy full-teardown
recovery, under SILENT preemption.

The churn model is a preempted VM, not a crashed process: the victim is
SIGSTOP'd (its lease stops renewing but its sockets stay OPEN and
silent) and its host is removed from the discovery pool — exactly what a
reclaimed TPU VM looks like from the survivors' side.  A plain SIGKILL
would close the victim's sockets and hand every survivor a prompt EOF,
which both recovery paths turn into a fast coordinated abort; the
regime the reshard tentpole exists for is the silent one, where the
legacy path has nothing to go on until the TCP progress deadline
expires while the reshard path aborts survivors' in-flight collectives
within one poll quantum of the driver's lease-expiry judgment.

Both arms run the SAME np=8 job (8 single-slot loopback hosts), the
SAME kill, the SAME lease timeout and progress deadline; the only
difference is ``HOROVOD_RESHARD``.  The metric is the longest gap
between consecutive committed batches on a surviving rank —
churn-to-first-step as training actually experiences it.  The committed
deadline here is 60 s to keep the bench runnable; the production
default is 600 s (``DEFAULT_TCP_PROGRESS_DEADLINE_SECS``), which only
widens the legacy arm's gap, so the ratio below is a floor.

    python benchmarks/reshard_bench.py \
        --out benchmarks/results/reshard_churn_np8.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HOSTS = ["localhost"] + [f"127.0.0.{i}" for i in range(2, 9)]
VICTIM_BATCH = 5  # SIGSTOP once the victim has committed this many

_TRAIN = """
import os
import time

import numpy as np

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.ObjectState(batch=0, params=np.zeros(4, np.float32))
print("WORKER_PID r%d %d %s" % (
    hvd.rank(), os.getpid(),
    os.environ.get("HOROVOD_HOSTNAME", "?")), flush=True)

@hvd.elastic.run
def train(state):
    while state.batch < 40:
        grad = hvd.allreduce(
            np.full(4, float(state.batch + 1), np.float32), name="g")
        state.params = state.params + np.asarray(grad)
        state.batch += 1
        state.commit()
        print("BATCH r%d %d t=%.6f" % (
            hvd.rank(), state.batch, time.monotonic()), flush=True)
        time.sleep(0.05)

train(state)
print("FINAL_PARAMS r%d %s" % (
    hvd.rank(), np.asarray(state.params).tobytes().hex()), flush=True)
hvd.shutdown()
"""


def _run_arm(workdir: str, reshard_enabled: bool, deadline_s: int,
             lease_s: float, timeout_s: int) -> dict:
    hosts_file = os.path.join(workdir, "hosts.txt")
    with open(hosts_file, "w") as f:
        f.write("".join(f"{h}:1\n" for h in HOSTS))
    disc = os.path.join(workdir, "discover.sh")
    with open(disc, "w") as f:
        f.write(f"#!/bin/sh\ncat {hosts_file}\n")
    os.chmod(disc, 0o755)
    train = os.path.join(workdir, "train.py")
    with open(train, "w") as f:
        f.write(_TRAIN)

    env = os.environ.copy()
    env.pop("HOROVOD_FAULT_SPEC", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_TRANSPORT": "tcp",
        "HOROVOD_TCP_PROGRESS_DEADLINE_SECS": str(deadline_s),
        "HOROVOD_LEASE_TIMEOUT_SECS": str(lease_s),
        "HOROVOD_RESHARD": "1" if reshard_enabled else "0",
        "HOROVOD_LOG_LEVEL": "info",
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", str(len(HOSTS)), "--min-np", "4",
         "--host-discovery-script", disc,
         sys.executable, train],
        cwd=REPO_ROOT, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)

    pids = {}        # rank -> (pid, hostname)
    batches = {}     # rank -> [(batch, t)]
    finals = {}      # rank -> params hex
    stdout_lines = []
    victim = {"stopped": False, "pid": None}
    lock = threading.Lock()

    def _on_line(line: str) -> None:
        stdout_lines.append(line)
        m = re.match(r"WORKER_PID r(\d+) (\d+) (\S+)", line)
        if m:
            with lock:
                pids[int(m.group(1))] = (int(m.group(2)), m.group(3))
            return
        m = re.match(r"BATCH r(\d+) (\d+) t=([0-9.]+)", line)
        if m:
            rank, batch, t = int(m.group(1)), int(m.group(2)), \
                float(m.group(3))
            with lock:
                batches.setdefault(rank, []).append((batch, t))
            # Silent preemption: freeze the victim (rank 3) once it has
            # committed VICTIM_BATCH batches, and take its host out of
            # the discovery pool in the same breath.
            if rank == 3 and batch >= VICTIM_BATCH \
                    and not victim["stopped"] and 3 in pids:
                victim["stopped"] = True
                victim["pid"], victim_host = pids[3]
                with open(hosts_file, "w") as f:
                    f.write("".join(f"{h}:1\n" for h in HOSTS
                                    if h != victim_host))
                os.kill(victim["pid"], signal.SIGSTOP)
            return
        m = re.match(r"FINAL_PARAMS r(\d+) ([0-9a-f]+)", line)
        if m:
            with lock:
                finals[int(m.group(1))] = m.group(2)

    def _pump() -> None:
        for line in proc.stdout:
            _on_line(line.rstrip("\n"))

    # stderr must drain concurrently too: the driver's info-level log is
    # chatty enough to fill the pipe and deadlock the launcher.
    stderr_lines = []

    def _pump_err() -> None:
        for line in proc.stderr:
            stderr_lines.append(line)

    pump = threading.Thread(target=_pump, daemon=True)
    pump.start()
    pump_err = threading.Thread(target=_pump_err, daemon=True)
    pump_err.start()

    # The frozen victim can never answer the driver's exit ping, so the
    # launcher would wait on it forever; reap it once every survivor has
    # printed final params (the measurement is already over by then).
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        with lock:
            done = len(finals) >= len(HOSTS) - 1
        if done or proc.poll() is not None:
            break
        time.sleep(0.25)
    if victim["pid"] is not None:
        try:
            os.kill(victim["pid"], signal.SIGKILL)
        except OSError:
            pass
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)
    pump.join(timeout=10)
    pump_err.join(timeout=10)
    stderr = "".join(stderr_lines)

    with lock:
        # The shrink re-ranks the new world 0..6, so rank 3 DOES appear
        # among the finals — it is a different (surviving) process; the
        # frozen victim never prints one.
        survivor_finals = dict(finals)
        rank0 = sorted(batches.get(0, []), key=lambda bt: bt[0])
    if len(survivor_finals) < len(HOSTS) - 1:
        raise RuntimeError(
            f"arm reshard={reshard_enabled}: only {len(survivor_finals)} "
            f"survivors finished (ranks {sorted(survivor_finals)})\n"
            f"{stderr[-3000:]}")
    if len(set(survivor_finals.values())) != 1:
        raise RuntimeError("survivors diverged")
    gaps = [(b1, t1 - t0) for (b0, t0), (b1, t1)
            in zip(rank0, rank0[1:])]
    churn_batch, churn_gap = max(gaps, key=lambda g: g[1])
    return {
        "reshard_enabled": reshard_enabled,
        "victim_stopped": victim["stopped"],
        "churn_to_first_step_s": round(churn_gap, 3),
        "resumed_at_batch": churn_batch,
        "rank0_batches": len(rank0),
        "survivors_final_param_hex": sorted(
            set(survivor_finals.values()))[0],
        "reshard_marker_published": "published with reshard marker"
                                    in stderr,
        "reshard_committed": "reshard committed at epoch" in stderr,
        "launcher_returncode": proc.returncode,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python benchmarks/reshard_bench.py")
    p.add_argument("--deadline", type=int, default=60,
                   help="TCP progress deadline (s) for BOTH arms; "
                        "production default is 600 — the committed 60 "
                        "understates the legacy arm's stall")
    p.add_argument("--lease", type=float, default=3.0)
    p.add_argument("--timeout", type=int, default=420,
                   help="per-arm wall clock bound (s)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    arms = {}
    for enabled in (True, False):
        name = "reshard" if enabled else "legacy_teardown"
        print(f"--- arm: {name} ---", flush=True)
        with tempfile.TemporaryDirectory() as wd:
            arms[name] = _run_arm(wd, enabled, args.deadline, args.lease,
                                  args.timeout)
        print(json.dumps(arms[name]), flush=True)

    if not arms["reshard"]["reshard_committed"]:
        raise RuntimeError("reshard arm never committed — the A/B "
                           "compared nothing")
    if arms["legacy_teardown"]["reshard_marker_published"]:
        raise RuntimeError("legacy arm published a reshard marker — the "
                           "kill-switch failed")
    if arms["reshard"]["survivors_final_param_hex"] != \
            arms["legacy_teardown"]["survivors_final_param_hex"]:
        raise RuntimeError("arms converged to different params")
    ratio = (arms["legacy_teardown"]["churn_to_first_step_s"]
             / max(1e-9, arms["reshard"]["churn_to_first_step_s"]))
    record = {
        "benchmark": "reshard_churn_np8",
        "np": len(HOSTS),
        "churn_model": "silent preemption: SIGSTOP victim + host removed "
                       "from discovery (sockets stay open; no EOF)",
        "tcp_progress_deadline_s": args.deadline,
        "production_default_deadline_s": 600,
        "lease_timeout_s": args.lease,
        "arms": arms,
        "improvement_ratio": round(ratio, 2),
    }
    print(json.dumps(record, indent=2), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(record) + "\n")
    return 0 if ratio >= 10.0 else 1


if __name__ == "__main__":
    sys.exit(main())
