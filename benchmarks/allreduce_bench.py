"""Eager allreduce micro-benchmark: bytes/sec across payload sizes and
world sizes (BASELINE.md metric #2 — allreduce scaling efficiency — had no
harness at all in round 1; reference recipe: ``docs/benchmarks.rst:16-64``).

Spawns real worker processes per world size (the same runtime path as
``hvdrun``), times a fixed number of eager ``hvd.allreduce`` rounds per
payload, and reports:

- ``busbw``: algorithm bandwidth ``2·(N−1)/N · bytes / time`` (the ring's
  wire traffic, comparable across world sizes — NCCL-tests convention),
  in GB/s and MB/s;
- ``scaling_efficiency``: busbw at N ranks / busbw at 2 ranks, per size.

Measurement discipline for this box (±20% run-to-run noise): every
reported time is the MEDIAN of ``--repeats`` samples, and when two
variants are compared (``--crc-sweep``: HOROVOD_WIRE_CRC on vs off;
``--segment-sweep``: HOROVOD_RING_SEGMENT_BYTES values) the samples are
INTERLEAVED — A B C, A B C, ... — so slow drift of the shared host hits
every variant equally instead of biasing whichever ran last.

Modes::

    python benchmarks/allreduce_bench.py                  # size × np grid
    python benchmarks/allreduce_bench.py --crc-sweep      # CRC on/off ratio
    python benchmarks/allreduce_bench.py --segment-sweep 65536 262144 ...
                                                          # pipeline knob sweep
    python benchmarks/allreduce_bench.py --compression-sweep
                                          # none/fp16/bf16 × CRC on/off

``--out FILE`` writes the result records as a JSON artifact (the segment
sweep's canonical home is ``benchmarks/results/ring_segment_sweep.json``).

On this CI image every rank is a localhost process over the TCP data
plane, so this measures the framework's own overhead curve (negotiation,
fusion, framing, the segment pipeline) rather than ICI — the TPU device
plane's collectives are XLA's own.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(size_bytes: int, rounds: int) -> float:
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = size_bytes // 4
    x = np.ones(n, np.float32) * (hvd.rank() + 1)
    # warmup: negotiation + cache line for this named tensor
    for i in range(3):
        hvd.allreduce(x, op=hvd.Sum, name=f"warm.{size_bytes}")
    hvd.barrier()
    t0 = time.perf_counter()
    for i in range(rounds):
        out = hvd.allreduce(x, op=hvd.Sum, name=f"bench.{size_bytes}")
    np.asarray(out)
    dt = time.perf_counter() - t0
    hvd.barrier()
    hvd.shutdown()
    return dt / rounds


def _measure(nbytes: int, np_: int, rounds: int, extra_env=None) -> float:
    """One sample: slowest-rank per-step seconds for (payload, world)."""
    import horovod_tpu.runner as runner

    use_env = {"JAX_PLATFORMS": "cpu"}
    if extra_env:
        use_env.update(extra_env)
    per_rank = runner.run(_worker, args=(nbytes, rounds),
                          np=np_, timeout=600, use_env=use_env)
    return max(per_rank)  # slowest rank bounds the collective


def _interleaved_medians(variants, repeats: int, nbytes: int, np_: int,
                         rounds: int):
    """Median step time per variant, sampled A B C, A B C, ... so host
    drift cannot bias one variant (the box's bench-noise discipline)."""
    samples = {key: [] for key, _ in variants}
    for _ in range(repeats):
        for key, env in variants:
            samples[key].append(_measure(nbytes, np_, rounds, env))
    return {key: statistics.median(vals) for key, vals in samples.items()}, \
        samples


def _record(nbytes: int, np_: int, step_s: float, base_busbw=None) -> dict:
    busbw = 2 * (np_ - 1) / np_ * nbytes / step_s
    rec = {
        "metric": "eager_allreduce_busbw",
        "payload_bytes": nbytes,
        "world_size": np_,
        "step_ms": round(step_s * 1e3, 3),
        "busbw_GBps": round(busbw / 1e9, 3),
        "busbw_MBps": round(busbw / 1e6, 1),
        "goodput_MBps": round(nbytes / step_s / 1e6, 1),
        # N workers timeshare this host's cores AND its loopback: when
        # world_size >> host_cpus the efficiency curve measures the box,
        # not the framework.
        "host_cpus": os.cpu_count(),
    }
    if base_busbw:
        rec["scaling_efficiency"] = round(busbw / base_busbw, 3)
    return rec


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="+",
                   default=[1 << 16, 1 << 20, 1 << 24, 1 << 26],
                   help="payload bytes per allreduce")
    p.add_argument("--world-sizes", type=int, nargs="+", default=[2, 4, 8])
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--repeats", type=int, default=3,
                   help="interleaved samples per config; medians reported")
    p.add_argument("--crc-sweep", action="store_true",
                   help="run every config with HOROVOD_WIRE_CRC on AND "
                        "off (interleaved) and report the overhead ratio")
    p.add_argument("--segment-sweep", type=int, nargs="*", default=None,
                   help="sweep HOROVOD_RING_SEGMENT_BYTES over these "
                        "values (interleaved) at --sizes[0] per world "
                        "size; 0 means chunk-sized (pipeline off)")
    p.add_argument("--metrics-sweep", action="store_true",
                   help="run --sizes[0] with HOROVOD_METRICS on AND off "
                        "(interleaved) and report the overhead ratio — "
                        "the observability plane's ±10%% guard "
                        "(docs/observability.md)")
    p.add_argument("--compression-sweep", action="store_true",
                   help="sweep HOROVOD_WIRE_COMPRESSION none/fp16/bf16 × "
                        "HOROVOD_WIRE_CRC on/off (interleaved) and report "
                        "per-variant step time + speedup vs uncompressed "
                        "(canonical artifact: "
                        "benchmarks/results/ring_compression_r9.json)")
    p.add_argument("--transport-sweep", action="store_true",
                   help="sweep HOROVOD_TRANSPORT shm/tcp/auto "
                        "(interleaved) per config and report per-variant "
                        "step time + shm speedup over loopback TCP "
                        "(canonical artifact: "
                        "benchmarks/results/ring_transport_sweep_r11.json)")
    p.add_argument("--out", type=str, default=None,
                   help="write result records to this JSON file")
    args = p.parse_args()

    results = []

    if args.segment_sweep is not None:
        seg_values = args.segment_sweep or [
            1 << 14, 1 << 16, 1 << 18, 1 << 20, 0]
        nbytes = args.sizes[0]
        for np_ in args.world_sizes:
            variants = []
            for seg in seg_values:
                # 0 → a segment at least the whole chunk: pipeline off.
                eff = seg if seg > 0 else max(nbytes, 1)
                variants.append(
                    (seg, {"HOROVOD_RING_SEGMENT_BYTES": str(eff)}))
            medians, samples = _interleaved_medians(
                variants, args.repeats, nbytes, np_, args.rounds)
            for seg, _ in variants:
                rec = _record(nbytes, np_, medians[seg])
                rec.update({
                    "metric": "ring_segment_sweep",
                    "segment_bytes": seg,
                    "samples_ms": [round(s * 1e3, 3)
                                   for s in samples[seg]],
                    "repeats": args.repeats,
                })
                results.append(rec)
                print(json.dumps(rec), flush=True)
    elif args.metrics_sweep:
        nbytes = args.sizes[0]
        for np_ in args.world_sizes:
            variants = [("on", {"HOROVOD_METRICS": "1"}),
                        ("off", {"HOROVOD_METRICS": "0"})]
            medians, samples = _interleaved_medians(
                variants, args.repeats, nbytes, np_, args.rounds)
            rec = _record(nbytes, np_, medians["on"])
            rec.update({
                "metric": "eager_allreduce_metrics_overhead",
                "step_ms_metrics_on": round(medians["on"] * 1e3, 3),
                "step_ms_metrics_off": round(medians["off"] * 1e3, 3),
                "metrics_on_off_ratio": round(
                    medians["on"] / medians["off"], 3),
                "samples_ms": {k: [round(s * 1e3, 3) for s in v]
                               for k, v in samples.items()},
                "repeats": args.repeats,
            })
            results.append(rec)
            print(json.dumps(rec), flush=True)
    elif args.compression_sweep:
        try:
            import ml_dtypes  # noqa: F401
            comp_modes = ["none", "fp16", "bf16"]
        except ImportError:
            comp_modes = ["none", "fp16"]
        # Lossy codecs ride the same sweep: on a loopback box the win is
        # bytes, not wall-clock (the A/B harness gives the verdict); the
        # sweep records both so the scaling model can project wire-bound
        # topologies from measured numbers.
        comp_modes += ["int8", "onebit", "topk10"]
        for nbytes in args.sizes:
            for np_ in args.world_sizes:
                variants = [
                    (f"{mode}/crc-{crc}",
                     {"HOROVOD_WIRE_COMPRESSION": mode,
                      "HOROVOD_WIRE_CRC": "1" if crc == "on" else "0"})
                    for mode in comp_modes
                    for crc in ("on", "off")
                ]
                medians, samples = _interleaved_medians(
                    variants, args.repeats, nbytes, np_, args.rounds)
                base = medians["none/crc-on"]
                for key, _ in variants:
                    mode, crc = key.split("/crc-")
                    rec = _record(nbytes, np_, medians[key])
                    rec.update({
                        "metric": "ring_compression_sweep",
                        "compression": mode,
                        "wire_crc": crc,
                        "speedup_vs_none_crc_on": round(
                            base / medians[key], 3),
                        "samples_ms": [round(s * 1e3, 3)
                                       for s in samples[key]],
                        "repeats": args.repeats,
                    })
                    results.append(rec)
                    print(json.dumps(rec), flush=True)
    elif args.transport_sweep:
        for nbytes in args.sizes:
            for np_ in args.world_sizes:
                variants = [("shm", {"HOROVOD_TRANSPORT": "shm"}),
                            ("tcp", {"HOROVOD_TRANSPORT": "tcp"}),
                            ("auto", {"HOROVOD_TRANSPORT": "auto"})]
                medians, samples = _interleaved_medians(
                    variants, args.repeats, nbytes, np_, args.rounds)
                rec = _record(nbytes, np_, medians["shm"])
                rec.update({
                    "metric": "ring_transport_sweep",
                    "step_ms_shm": round(medians["shm"] * 1e3, 3),
                    "step_ms_tcp": round(medians["tcp"] * 1e3, 3),
                    "step_ms_auto": round(medians["auto"] * 1e3, 3),
                    "shm_speedup_vs_tcp": round(
                        medians["tcp"] / medians["shm"], 3),
                    "samples_ms": {k: [round(s * 1e3, 3) for s in v]
                                   for k, v in samples.items()},
                    "repeats": args.repeats,
                })
                results.append(rec)
                print(json.dumps(rec), flush=True)
    elif args.crc_sweep:
        for nbytes in args.sizes:
            for np_ in args.world_sizes:
                variants = [("on", {"HOROVOD_WIRE_CRC": "1"}),
                            ("off", {"HOROVOD_WIRE_CRC": "0"})]
                medians, samples = _interleaved_medians(
                    variants, args.repeats, nbytes, np_, args.rounds)
                rec = _record(nbytes, np_, medians["on"])
                rec.update({
                    "metric": "eager_allreduce_crc_overhead",
                    "step_ms_crc_on": round(medians["on"] * 1e3, 3),
                    "step_ms_crc_off": round(medians["off"] * 1e3, 3),
                    "crc_on_off_ratio": round(
                        medians["on"] / medians["off"], 3),
                    "samples_ms": {k: [round(s * 1e3, 3) for s in v]
                                   for k, v in samples.items()},
                    "repeats": args.repeats,
                })
                results.append(rec)
                print(json.dumps(rec), flush=True)
    else:
        for nbytes in args.sizes:
            base_busbw = None
            for np_ in args.world_sizes:
                medians, samples = _interleaved_medians(
                    [("t", None)], args.repeats, nbytes, np_, args.rounds)
                rec = _record(nbytes, np_, medians["t"], base_busbw)
                rec["samples_ms"] = [round(s * 1e3, 3)
                                     for s in samples["t"]]
                if base_busbw is None:
                    base_busbw = 2 * (np_ - 1) / np_ * nbytes / medians["t"]
                    rec["scaling_efficiency"] = 1.0
                results.append(rec)
                print(json.dumps(rec), flush=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
