"""Eager allreduce micro-benchmark: bytes/sec across payload sizes and
world sizes (BASELINE.md metric #2 — allreduce scaling efficiency — had no
harness at all in round 1; reference recipe: ``docs/benchmarks.rst:16-64``).

Spawns real worker processes per world size (the same runtime path as
``hvdrun``), times a fixed number of eager ``hvd.allreduce`` rounds per
payload, and reports:

- ``busbw``: algorithm bandwidth ``2·(N−1)/N · bytes / time`` (the ring's
  wire traffic, comparable across world sizes — NCCL-tests convention);
- ``scaling_efficiency``: busbw at N ranks / busbw at 2 ranks, per size.

On this CI image every rank is a localhost process over the TCP data
plane, so this measures the framework's own overhead curve (negotiation,
fusion, framing) rather than ICI — the TPU device plane's collectives are
XLA's own.  Run: ``python benchmarks/allreduce_bench.py [--sizes ...]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(size_bytes: int, rounds: int) -> float:
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = size_bytes // 4
    x = np.ones(n, np.float32) * (hvd.rank() + 1)
    # warmup: negotiation + cache line for this named tensor
    for i in range(3):
        hvd.allreduce(x, op=hvd.Sum, name=f"warm.{size_bytes}")
    hvd.barrier()
    t0 = time.perf_counter()
    for i in range(rounds):
        out = hvd.allreduce(x, op=hvd.Sum, name=f"bench.{size_bytes}")
    np.asarray(out)
    dt = time.perf_counter() - t0
    hvd.barrier()
    hvd.shutdown()
    return dt / rounds


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="+",
                   default=[1 << 16, 1 << 20, 1 << 24, 1 << 26],
                   help="payload bytes per allreduce")
    p.add_argument("--world-sizes", type=int, nargs="+", default=[2, 4, 8])
    p.add_argument("--rounds", type=int, default=10)
    args = p.parse_args()

    import horovod_tpu.runner as runner

    results = []
    for nbytes in args.sizes:
        base_busbw = None
        for np_ in args.world_sizes:
            per_rank = runner.run(_worker, args=(nbytes, args.rounds),
                                  np=np_, timeout=600,
                                  use_env={"JAX_PLATFORMS": "cpu"})
            step_s = max(per_rank)  # slowest rank bounds the collective
            busbw = 2 * (np_ - 1) / np_ * nbytes / step_s
            if base_busbw is None:
                base_busbw = busbw
            rec = {
                "metric": "eager_allreduce_busbw",
                "payload_bytes": nbytes,
                "world_size": np_,
                "step_ms": round(step_s * 1e3, 3),
                "busbw_GBps": round(busbw / 1e9, 3),
                "scaling_efficiency": round(busbw / base_busbw, 3),
                # N workers timeshare this host's cores AND its loopback:
                # when world_size >> host_cpus the efficiency curve
                # measures the box, not the framework.
                "host_cpus": os.cpu_count(),
            }
            results.append(rec)
            print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
