"""Control-plane micro-benchmark: negotiation latency vs world size.

VERDICT round 1 (weak #3): the star control plane's "adequate to hundreds
of ranks" claim was unmeasured.  This measures it: per-allreduce latency
of a TINY payload (latency ≈ pure negotiation + framing cost, the
ResponseCache steady state) across world sizes, plus the cold
(cache-miss) first round.

Run: ``python benchmarks/controller_bench.py [--world-sizes 2 4 8 16]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(rounds: int) -> dict:
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(4, np.float32)
    t0 = time.perf_counter()
    hvd.allreduce(x, op=hvd.Sum, name="cold")
    cold_ms = (time.perf_counter() - t0) * 1e3

    for _ in range(3):  # reach the cache fast path
        hvd.allreduce(x, op=hvd.Sum, name="hot")
    hvd.barrier()
    t0 = time.perf_counter()
    for _ in range(rounds):
        hvd.allreduce(x, op=hvd.Sum, name="hot")
    hot_ms = (time.perf_counter() - t0) / rounds * 1e3
    hvd.barrier()
    hvd.shutdown()
    return {"cold_ms": cold_ms, "hot_ms": hot_ms}


def _measure_hop_cost(msg_bytes: int, rounds: int = 200) -> float:
    """One TcpMesh message hop over loopback (send syscall + framing +
    recv), in ms — the t_msg parameter of the topology model."""
    import threading
    import time

    from horovod_tpu.transport.store import MemoryStore
    from horovod_tpu.transport.tcp import TcpMesh

    payload = bytes(msg_bytes)
    store = MemoryStore()
    meshes: dict = {}

    def build(rank):
        meshes[rank] = TcpMesh(rank, 2, store, scope="hopbench",
                               bind_addr="127.0.0.1",
                               advertise_addr="127.0.0.1", timeout=30)

    threads = [threading.Thread(target=build, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    stop = threading.Event()

    def echo():
        while not stop.is_set():
            try:
                meshes[1].send(0, meshes[1].recv(0))
            except Exception:  # noqa: BLE001 — mesh closed
                return

    echo_t = threading.Thread(target=echo, daemon=True)
    echo_t.start()
    # warmup
    for _ in range(10):
        meshes[0].send(1, payload)
        meshes[0].recv(1)
    t0 = time.perf_counter()
    for _ in range(rounds):
        meshes[0].send(1, payload)
        meshes[0].recv(1)
    rtt_ms = (time.perf_counter() - t0) / rounds * 1e3
    stop.set()
    for m in meshes.values():
        m.close()
    return rtt_ms / 2  # one hop = half the echo round trip


def _coordinator_cpu_ms(world: int, tensors: int, topology: str) -> dict:
    """Hot-cycle coordinator CPU at `world` ranks under `topology`,
    via the controller_sim harness (real controller code, canned wire)."""
    os.environ["HOROVOD_CONTROLLER_TOPOLOGY"] = topology
    try:
        import controller_sim

        case = controller_sim.run_case(world, tensors, cycles=30)
        return {"hot_ms": case["hot_cycle_ms_p50"],
                "cold_ms": case["cold_cycle_ms"]}
    finally:
        os.environ.pop("HOROVOD_CONTROLLER_TOPOLOGY", None)


def compare_topologies(world_sizes, tensors: int) -> list:
    """Star vs binomial tree: measured coordinator CPU (real controller
    code) + measured per-hop wire cost, composed into a cycle-wall model.

    The per-cycle wall difference is the coordinator's SERIAL message
    loop: star pays (P-1) hops on gather + (P-1) on broadcast; the tree
    pays ceil(log2 P) levels each way (relays run concurrently across
    the tree, so depth — not node count — is the wall term).  256 real
    processes cannot run on this host, so wall numbers for large P are
    the model; CPU numbers are real measurements of the real code.
    """
    import math

    from horovod_tpu.core.controller import TREE_TOPOLOGY_THRESHOLD

    hop_small_ms = _measure_hop_cost(512)       # RequestList-sized
    hop_resp_ms = _measure_hop_cost(4096)       # fused ResponseList-sized
    out = []
    for world in world_sizes:
        if world <= 2:
            # Controller forces the star at size <= 2 (a 2-rank tree IS
            # the star); a "tree" row here would just be star noise.
            out.append({"world_size": world,
                        "skipped": "tree degenerates to star"})
            continue
        depth = max(1, math.ceil(math.log2(world)))
        star_cpu = _coordinator_cpu_ms(world, tensors, "star")
        tree_cpu = _coordinator_cpu_ms(world, tensors, "tree")
        star_wall = star_cpu["hot_ms"] + (world - 1) * (hop_small_ms
                                                        + hop_resp_ms)
        tree_wall = tree_cpu["hot_ms"] + depth * (hop_small_ms
                                                  + hop_resp_ms)
        out.append({
            "metric": "controller_topology_cycle_wall",
            "world_size": world,
            "star": {"coord_cpu_hot_ms": star_cpu["hot_ms"],
                     "modeled_wall_ms": round(star_wall, 3)},
            "tree": {"coord_cpu_hot_ms": tree_cpu["hot_ms"],
                     "modeled_wall_ms": round(tree_wall, 3),
                     "depth": depth},
            "hop_ms": {"request": round(hop_small_ms, 4),
                       "response": round(hop_resp_ms, 4)},
            "winner": "tree" if tree_wall < star_wall else "star",
            "auto_threshold": TREE_TOPOLOGY_THRESHOLD,
            "note": "coord CPU measured on real controller code; wall "
                    "composes it with measured loopback hop cost "
                    "(real N-process runs infeasible beyond ~16 ranks "
                    "on this host)",
        })
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--world-sizes", type=int, nargs="+",
                   default=[2, 4, 8, 16])
    p.add_argument("--rounds", type=int, default=50)
    p.add_argument("--topology", default=None,
                   choices=["star", "tree"],
                   help="force the controller fan-out for the real-process "
                        "runs")
    p.add_argument("--compare-topologies", action="store_true",
                   help="star-vs-tree coordinator CPU + modeled cycle "
                        "wall at --world-sizes (feasible at 64/256: no "
                        "real worker processes)")
    p.add_argument("--out", default=None, help="also append JSON lines here")
    args = p.parse_args()

    records = []
    if args.compare_topologies:
        records = compare_topologies(args.world_sizes, tensors=50)
        for rec in records:
            print(json.dumps(rec), flush=True)
    else:
        import horovod_tpu.runner as runner

        env = {"JAX_PLATFORMS": "cpu"}
        if args.topology:
            env["HOROVOD_CONTROLLER_TOPOLOGY"] = args.topology
        for np_ in args.world_sizes:
            # Mesh bring-up of N jax runtimes flakes on small CI hosts
            # (accept timeouts under load) — retry via the suite's shared
            # infra-signature gate (tests/helpers.py), not a divergent
            # copy of it.
            from tests.helpers import infra_retryable, retry_backoff

            for attempt in range(3):
                try:
                    per_rank = runner.run(_worker, args=(args.rounds,),
                                          np=np_, timeout=600, use_env=env)
                    break
                except Exception as e:  # noqa: BLE001
                    if attempt == 2 or not infra_retryable(e):
                        raise
                    retry_backoff(attempt + 1)
            rec = {
                "metric": "negotiation_latency",
                "world_size": np_,
                "topology": args.topology or "auto",
                "hot_path_ms": round(max(r["hot_ms"] for r in per_rank), 3),
                "cold_path_ms": round(max(r["cold_ms"] for r in per_rank),
                                      3),
                # N workers timeshare this host's cores: when world_size >>
                # host_cpus the numbers measure the box, not the protocol.
                "host_cpus": os.cpu_count(),
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
