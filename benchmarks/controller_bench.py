"""Control-plane micro-benchmark: negotiation latency vs world size.

VERDICT round 1 (weak #3): the star control plane's "adequate to hundreds
of ranks" claim was unmeasured.  This measures it: per-allreduce latency
of a TINY payload (latency ≈ pure negotiation + framing cost, the
ResponseCache steady state) across world sizes, plus the cold
(cache-miss) first round.

Run: ``python benchmarks/controller_bench.py [--world-sizes 2 4 8 16]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(rounds: int) -> dict:
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(4, np.float32)
    t0 = time.perf_counter()
    hvd.allreduce(x, op=hvd.Sum, name="cold")
    cold_ms = (time.perf_counter() - t0) * 1e3

    for _ in range(3):  # reach the cache fast path
        hvd.allreduce(x, op=hvd.Sum, name="hot")
    hvd.barrier()
    t0 = time.perf_counter()
    for _ in range(rounds):
        hvd.allreduce(x, op=hvd.Sum, name="hot")
    hot_ms = (time.perf_counter() - t0) / rounds * 1e3
    hvd.barrier()
    hvd.shutdown()
    return {"cold_ms": cold_ms, "hot_ms": hot_ms}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--world-sizes", type=int, nargs="+",
                   default=[2, 4, 8, 16])
    p.add_argument("--rounds", type=int, default=50)
    args = p.parse_args()

    import horovod_tpu.runner as runner

    for np_ in args.world_sizes:
        per_rank = runner.run(_worker, args=(args.rounds,), np=np_,
                              timeout=600,
                              use_env={"JAX_PLATFORMS": "cpu"})
        rec = {
            "metric": "negotiation_latency",
            "world_size": np_,
            "hot_path_ms": round(max(r["hot_ms"] for r in per_rank), 3),
            "cold_path_ms": round(max(r["cold_ms"] for r in per_rank), 3),
            # N workers timeshare this host's cores: when world_size >>
            # host_cpus the numbers measure the box, not the protocol.
            "host_cpus": os.cpu_count(),
        }
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
