"""Multi-process eager dispatch-chain benchmark (VERDICT r3 missing #6).

The r3 eager-vs-jit number was measured at np=1, where ``XlaAllreduce``
takes the ``local_allreduce`` shortcut — the np>1 chain (fuse →
``make_array_from_single_device_arrays`` → global-mesh jit → unfuse) had
appeared in no perf number.  This harness runs UNDER THE LAUNCHER on the
virtual CPU mesh and measures, per process:

- **jit**: local train step, no communication (the per-chip compute
  baseline);
- **eager**: the same step with grads through ``DistributedOptimizer``
  (full negotiate → fuse → global-mesh collective → unfuse chain);
- **eager_overlap**: ``DistributedOptimizer(overlap=True,
  backward_passes_per_step=2)`` — the WFBP microbatch pipeline;
- **wfbp_step**: the in-program overlapped step
  (``make_overlapped_train_step`` — forward+backward+allreduce+update in
  one XLA program);
- **dispatch probe**: enqueue→synchronize wall time of a single fused
  allreduce at several payload sizes; the small-payload time is almost
  pure per-dispatch overhead (negotiation cycle + fuse + global-array
  assembly + jit launch + unfuse), the scaling-model input the r3 model
  had to assume.

Run (CPU mesh, one device per process):

    JAX_PLATFORMS=cpu python -m horovod_tpu.runner.launch -np 8 \
        --data-plane xla python benchmarks/eager_np_bench.py \
        --out benchmarks/results/eager_np8_cpu.json

Rank 0 writes the JSON artifact.
"""

from __future__ import annotations

import argparse
import json
import time


def _bench(fn, warmup: int, iters: int, after_warmup=None) -> float:
    """Mean seconds per call; ``after_warmup`` runs between the warmup and
    the timed region (e.g. resetting profile accumulators)."""
    for _ in range(warmup):
        fn()
    if after_warmup is not None:
        after_warmup()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--hidden", type=int, default=512)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--out", default=None)
    parser.add_argument("--profile", action="store_true", default=False,
                        help="include the per-phase dispatch-chain "
                             "breakdown and controller fast-path counters")
    args = parser.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # The axon sitecustomize re-pins the platform via jax.config at
        # import time; env alone does not stick (see tests/helpers.py).
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer
    from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # -- model: plain MLP pytree, ~1M params at defaults ----------------
    rng = np.random.RandomState(0)
    dims = [args.hidden] * (args.layers + 1)
    params = {f"w{i}": jnp.asarray(rng.randn(dims[i], dims[i + 1]) * 0.05,
                                   jnp.float32)
              for i in range(args.layers)}
    grad_bytes = sum(int(np.prod(v.shape)) * 4 for v in params.values())
    x = jnp.asarray(rng.randn(args.batch_size, args.hidden), jnp.float32)
    y = jnp.asarray(rng.randn(args.batch_size, args.hidden), jnp.float32)

    def loss_fn(p, batch):
        h = batch["x"]
        for i in range(args.layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - batch["y"]) ** 2)

    batch = {"x": x, "y": y}
    tx = optax.sgd(0.01, momentum=0.9)

    # -- jit baseline: local step, zero comm ----------------------------
    @jax.jit
    def jit_step(p, s, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        upd, s = tx.update(grads, s, p)
        return optax.apply_updates(p, upd), s, loss

    box = [params, tx.init(params)]

    def run_jit():
        p, s, loss = jit_step(box[0], box[1], batch)
        box[0], box[1] = p, s
        jax.block_until_ready(loss)

    jit_dt = _bench(run_jit, args.warmup, args.iters)

    vg = jax.jit(jax.value_and_grad(loss_fn))
    apply_updates = jax.jit(optax.apply_updates)

    def eager_flavor(dopt, n_calls=1):
        st = [params, dopt.init(params)]

        def run():
            for _ in range(n_calls):
                loss, grads = vg(st[0], batch)
                upd, st[1] = dopt.update(grads, st[1], st[0])
                st[0] = apply_updates(st[0], upd)
            jax.block_until_ready(st[0])
        return run

    # -- eager: negotiate+fuse+collective every step --------------------
    from horovod_tpu.core.timeline import phase_stats, wire_stats

    # phase_stats/wire_stats reset after warmup so the breakdown covers
    # the steady-state (cache-warm) timed region only.
    def _reset_stats():
        phase_stats.reset()
        wire_stats.reset()

    eager_dt = _bench(eager_flavor(DistributedOptimizer(tx)),
                      args.warmup, args.iters,
                      after_warmup=_reset_stats)
    phase_breakdown = phase_stats.snapshot()
    wire_counters = wire_stats.snapshot()

    # -- eager overlap: WFBP microbatch pipeline (2 backwards/step) ------
    # n_calls=2 → one full accumulation window per run; per-backward time
    # is dt/2, comparable against the non-overlap bpps=2 flavor.
    ov_dt = _bench(
        eager_flavor(DistributedOptimizer(
            tx, backward_passes_per_step=2, overlap=True), n_calls=2),
        args.warmup, args.iters) / 2
    acc_dt = _bench(
        eager_flavor(DistributedOptimizer(
            tx, backward_passes_per_step=2), n_calls=2),
        args.warmup, args.iters) / 2

    # -- in-program overlapped step -------------------------------------
    step = make_overlapped_train_step(loss_fn, tx)
    gp, gs = step.init(params, tx.init(params))
    wf = [gp, gs]

    def run_wfbp():
        p, s, loss = step(wf[0], wf[1], batch)
        wf[0], wf[1] = p, s
        jax.block_until_ready(loss)

    wfbp_dt = _bench(run_wfbp, args.warmup, args.iters)

    # -- dispatch probe: per-op cost of the full async chain ------------
    probe = {}
    for elems in (256, 65_536, 1_048_576):
        buf = jnp.asarray(rng.randn(elems), jnp.float32)

        def run_probe():
            hvd.synchronize(hvd.allreduce_async(
                buf, op=hvd.Sum, name=f"probe.{elems}"))

        probe[elems] = round(_bench(run_probe, args.warmup,
                                    args.iters) * 1e3, 3)

    from horovod_tpu.backend import xla as xla_backend
    from horovod_tpu.core.state import global_state

    ctrl = global_state().controller
    result = {
        "metric": "eager_np_dispatch_chain",
        "world_size": size,
        "grad_bytes": grad_bytes,
        "platform": jax.devices()[0].platform,
        "jit_step_ms": round(jit_dt * 1e3, 3),
        "eager_step_ms": round(eager_dt * 1e3, 3),
        "eager_gap_pct": round((eager_dt - jit_dt) / jit_dt * 100, 2),
        "eager_overlap_per_backward_ms": round(ov_dt * 1e3, 3),
        "eager_accum_per_backward_ms": round(acc_dt * 1e3, 3),
        "overlap_speedup_pct": round((acc_dt - ov_dt) / acc_dt * 100, 2),
        "wfbp_step_ms": round(wfbp_dt * 1e3, 3),
        "wfbp_gap_vs_jit_pct": round((wfbp_dt - jit_dt) / jit_dt * 100, 2),
        "dispatch_probe_ms": probe,
        "per_dispatch_overhead_ms": probe[256],
        "xla_dispatch_stats": dict(xla_backend.stats),
        # Steady-state fast-path engagement over the whole run: cycles
        # negotiated with mask frames only (zero Request/Response
        # payloads) vs Requests ever serialized by this rank.
        "fast_cycles": ctrl.fast_cycle_count if ctrl else 0,
        "requests_serialized": ctrl.serialized_request_count if ctrl else 0,
        "cache_hits": ctrl.cache_hit_count if ctrl else 0,
    }
    if args.profile:
        result["phase_breakdown_ms"] = phase_breakdown
        # Data-plane counters (core/timeline.py wire_stats): payload bytes
        # the transport moved and heap materializations in the host data
        # plane during the steady-state eager region.
        result["wire_counters"] = wire_counters
    hvd.shutdown()
    if rank == 0:
        line = json.dumps(result)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")


if __name__ == "__main__":
    main()
