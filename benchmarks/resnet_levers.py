"""ResNet-50 BN-statistics levers, measured (VERDICT r3 next #3).

The r3 profile showed 46.6% of device time in ``convert_reduce_fusion`` —
BatchNorm statistics (fwd moments + bwd reductions) reading bf16
activations into fp32 reductions — and defended 31% MFU with a roofline
whose byte count was admittedly overcounted.  This harness measures the
levers instead of arguing:

- **baseline** — fp32 BN reductions, one-pass variance (the shipped
  config);
- **bf16_stats** — ``force_float32_reductions=False``: statistics
  reduce in bf16 (XLA picks the accumulator).  Numerics check: loss
  trajectory + running-stat drift vs baseline over the same batches;
- **two_pass_var** — ``use_fast_variance=False``: textbook two-pass
  variance, expected slower (one more full activation read) — measured
  to bound how much the one-pass trick is already buying;
- **XLA flag experiments** (run via subprocess so the flag reaches
  backend init): ``--xla_tpu_scoped_vmem_limit_kib=65536`` (deeper
  fusion headroom).

Each config: compile, warmup, timed steps on the attached chip →
images/sec + MFU.  Output: one JSON object; commit to
``benchmarks/results/resnet_levers_v5e.json`` and transcribe the table
into ``docs/perf_r4.md``.

Run: ``python benchmarks/resnet_levers.py [--iters 20]``
Single-config child mode (used for flag experiments):
``python benchmarks/resnet_levers.py --single baseline``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

PEAK_V5E = 197e12
FLOPS_FALLBACK = 3 * 2 * 4.09e9  # per image; bench.py convention


def run_config(name: str, iters: int, warmup: int, batch_size: int,
               check_numerics: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import ResNet50
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    on_tpu = jax.devices()[0].platform == "tpu"
    bs = batch_size if on_tpu else 8
    img = 224 if on_tpu else 64
    iters = iters if on_tpu else 3

    overrides = {
        "baseline": {},
        "bf16_stats": {"bn_f32_stats": False},
        "two_pass_var": {"bn_fast_variance": False},
        # The structural lever (r4's "one option left"): BN statistics
        # fused into the 1x1 convs' pallas epilogue — eliminates the
        # stats re-read of those activations entirely
        # (horovod_tpu/kernels/conv_bn_stats.py).
        "fused_conv1x1_bn": {"fuse_conv1x1_bn": True},
    }[name]  # unknown names must raise, not silently measure baseline

    mesh = build_mesh(MeshSpec(data=-1))
    n_dev = len(jax.devices())
    if overrides.get("fuse_conv1x1_bn") and n_dev > 1:
        overrides["fused_bn_mesh"] = mesh  # shard_map flavor
    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                     **overrides)
    tx = optax.sgd(0.01, momentum=0.9)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(bs, img, img, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(bs,)), jnp.int32)

    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=True)
    batch = shard_batch(mesh, {"x": x, "y": y})
    compiled = step.lower(state, batch).compile()
    try:
        flops = compiled.cost_analysis()["flops"]
    except Exception:  # noqa: BLE001
        flops = FLOPS_FALLBACK * bs

    losses = []
    for _ in range(max(1, warmup)):  # >=1: compile outside the timed loop
        state, loss = compiled(state, batch)
    losses.append(float(loss))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = compiled(state, batch)
    losses.append(float(loss))
    dt = (time.perf_counter() - t0) / iters

    out = {
        "config": name,
        "batch_size": bs,
        "step_ms": round(dt * 1e3, 3),
        "images_per_sec": round(bs / dt, 2),
        "mfu": round(flops / dt / PEAK_V5E, 4) if on_tpu else None,
        "final_loss": losses[-1],
        "finite": bool(np.isfinite(losses[-1])),
    }
    if check_numerics:
        # Running-stat drift vs what fp32 stats produce on one batch: an
        # absolute BN-mean comparison after `warmup+iters` identical
        # steps.  (Cheap proxy; convergence claims need real training.)
        means = jax.tree_util.tree_leaves(state.batch_stats)
        out["stat_abs_max"] = float(max(
            jnp.max(jnp.abs(m)) for m in means))
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--single", default=None,
                        help="run ONE config and print its JSON (child "
                             "mode for flag experiments)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    # Bounded backend probe BEFORE this process touches jax: a wedged
    # chip must yield a structured record, not an infinite hang (the
    # exact defense bench.py grew after round 4 — reuse it).
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import bench as _bench

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        probe = _bench._probe_accelerator(
            timeout_s=float(os.environ.get("HVD_BENCH_PROBE_TIMEOUT_S",
                                           "120")),
            retries=int(os.environ.get("HVD_BENCH_PROBE_RETRIES", "3")))
        if not probe["ok"]:
            line = json.dumps({"metric": "resnet50_bn_levers",
                               "error": "tpu_unavailable", "probe": probe})
            print(line)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(line + "\n")
            return 0

    if args.single:
        print(json.dumps(run_config(args.single, args.iters, args.warmup,
                                    args.batch_size, True)))
        return 0

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    results = {}
    configs = ["baseline", "bf16_stats", "two_pass_var"]
    if on_tpu:
        # fused lever: TPU-only — interpret mode on CPU would run dozens
        # of interpreted pallas grids per grad step.  Multi-device runs
        # use the shard_map flavor (psum'd statistics).
        configs.append("fused_conv1x1_bn")
    else:
        results["fused_conv1x1_bn"] = {
            "skipped": "TPU-only (pallas kernel; no CPU interpret timing)"}
    for name in configs:
        results[name] = run_config(name, args.iters, args.warmup,
                                   args.batch_size, True)
        print(name, "->", results[name], file=sys.stderr)

    # Flag experiments in child processes (XLA_FLAGS latch at backend init)
    here = os.path.abspath(__file__)
    for flag_name, flags in (
            ("vmem64m", "--xla_tpu_scoped_vmem_limit_kib=65536"),):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
        try:
            proc = subprocess.run(
                [sys.executable, here, "--single", "baseline",
                 "--iters", str(args.iters), "--warmup", str(args.warmup),
                 "--batch-size", str(args.batch_size)],
                env=env, capture_output=True, text=True, timeout=560)
            line = proc.stdout.strip().splitlines()[-1] if \
                proc.stdout.strip() else ""
            results[flag_name] = json.loads(line) if line.startswith("{") \
                else {"error": proc.stderr[-500:]}
        except Exception as e:  # noqa: BLE001
            results[flag_name] = {"error": str(e)}
        results[flag_name]["xla_flags"] = flags
        print(flag_name, "->", results[flag_name], file=sys.stderr)

    payload = {"metric": "resnet50_bn_levers", "results": results}
    line = json.dumps(payload)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
