"""TF graph-mode collective cost: per-tensor py_function vs batched.

VERDICT r3 missing #4 / next #7: graph-mode collectives paid one
``tf.py_function`` per tensor — measured ~2.6× over eager for a single
1M-float allreduce (docs/benchmarks.md).  The fix batches the whole
gradient list through ONE py_function per step
(``_batched_allreduce``).  This harness quantifies all three flavors on a
realistic gradient list:

- **eager**: per-step batched allreduce, eager TF (the baseline);
- **graph_batched**: the same list under ``@tf.function`` through the
  batched path (the product path after the fix);
- **graph_per_tensor**: one public ``hvd.allreduce`` per tensor under
  ``@tf.function`` (the pre-fix behavior, kept measurable via the public
  op).

Run: ``python benchmarks/tf_graph_bench.py [--out path.json]``
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tensors", type=int, default=50)
    parser.add_argument("--elems", type=int, default=20_000)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    import numpy as np
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd
    from horovod_tpu.frameworks.tensorflow import (
        Compression,
        _allreduce_grads,
    )

    hvd.init()
    rng = np.random.RandomState(0)
    grads = [tf.constant(rng.randn(args.elems).astype(np.float32))
             for _ in range(args.tensors)]

    def bench(fn):
        for _ in range(args.warmup):
            fn()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn()
        np.asarray(out[-1])  # materialize
        return (time.perf_counter() - t0) / args.iters * 1e3  # ms

    # eager batched (baseline)
    eager_ms = bench(lambda: _allreduce_grads(
        grads, Compression.none, hvd.Average, 1.0, 1.0))

    # graph batched (the product path)
    @tf.function
    def graph_batched():
        return _allreduce_grads(grads, Compression.none, hvd.Average,
                                1.0, 1.0)

    graph_batched_ms = bench(graph_batched)

    # graph per-tensor (pre-fix behavior)
    @tf.function
    def graph_per_tensor():
        return [hvd.allreduce(g, name=f"pt.{i}")
                for i, g in enumerate(grads)]

    graph_pt_ms = bench(graph_per_tensor)

    result = {
        "metric": "tf_graph_collective_cost",
        "tensors": args.tensors,
        "elems_each": args.elems,
        "world_size": hvd.size(),
        "eager_ms_per_step": round(eager_ms, 3),
        "graph_batched_ms_per_step": round(graph_batched_ms, 3),
        "graph_per_tensor_ms_per_step": round(graph_pt_ms, 3),
        "batched_vs_eager": round(graph_batched_ms / eager_ms, 3),
        "per_tensor_vs_eager": round(graph_pt_ms / eager_ms, 3),
    }
    hvd.shutdown()
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
