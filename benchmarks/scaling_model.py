"""Analytic scaling-efficiency projection from measured single-chip inputs.

BASELINE metric #2 (allreduce scaling efficiency, 8→256 chips) cannot be
measured on this rig (one chip); this model projects it from quantities
that WERE measured, with every assumption explicit in the output:

- single-chip step time and gradient bytes: measured
  (`benchmarks/results/eager_vs_jit_v5e.json`, profile artifacts);
- ring-allreduce wire cost ``2·(N−1)/N · bytes / busbw`` with the busbw an
  explicit parameter (default 90 GB/s effective per chip on the v5e 2-D
  torus — a conservative fraction of the 1600 Gbit/s ICI spec);
- controller cycle overhead from the coordinator simulation
  (`benchmarks/results/controller_sim.json` hot-path p50);
- two overlap regimes: the jit/SPMD plane (XLA overlaps the psum with
  backward: exposed comm = max(0, t_comm − overlap window, taken as the
  backward ≈ 2/3 of the step)) and the eager plane (static tree fusion
  fires after backward: comm fully exposed + one cycle).

This is a MODEL, labeled as such — the driver's multi-chip dry run checks
the sharded code compiles/executes; real 8–256-chip numbers need a pod.

Run: ``python benchmarks/scaling_model.py
[--out benchmarks/results/scaling_model.json]``
"""

from __future__ import annotations

import argparse
import json
import sys


MODELS = {
    # name: (measured single-chip step ms [jit], grad bytes)
    "resnet50_bs128": (50.1, 25_557_032 * 4),
    "bert_large_bs8": (121.4, 334_000_000 * 4),
}


def project(step_ms: float, grad_bytes: int, n: int, busbw_gbs: float,
            cycle_ms: float) -> dict:
    t_comm = 2 * (n - 1) / n * grad_bytes / (busbw_gbs * 1e9) * 1e3  # ms
    backward_ms = step_ms * 2 / 3
    jit_exposed = max(0.0, t_comm - backward_ms)
    eager_exposed = t_comm + cycle_ms
    return {
        "chips": n,
        "allreduce_ms": round(t_comm, 3),
        "jit_efficiency": round(step_ms / (step_ms + jit_exposed), 4),
        "eager_efficiency": round(step_ms / (step_ms + eager_exposed), 4),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--busbw-gbs", type=float, default=90.0,
                   help="effective per-chip allreduce busbw (v5e ICI)")
    p.add_argument("--chips", type=int, nargs="+",
                   default=[8, 16, 64, 256])
    p.add_argument("--out", default=None)
    args = p.parse_args()

    # hot-path coordinator cycle p50 from the committed simulation
    # (benchmarks/results/controller_sim.json), by N
    cycle = {8: 0.66, 16: 0.75, 64: 1.14, 256: 2.14}

    out = {
        "model": "analytic ring-allreduce projection (see module docstring)",
        "assumptions": {
            "busbw_gbs": args.busbw_gbs,
            "overlap_window": "2/3 of step (backward) for the jit plane; "
                              "none for the eager plane",
            "controller_cycle_ms": cycle,
        },
        "projections": {},
    }
    for name, (step_ms, grad_bytes) in MODELS.items():
        out["projections"][name] = [
            project(step_ms, grad_bytes, n, args.busbw_gbs,
                    cycle.get(n, 2.0))
            for n in args.chips
        ]
    line = json.dumps(out, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
