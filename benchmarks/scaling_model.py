"""Analytic scaling-efficiency projection from measured single-chip inputs.

BASELINE metric #2 (allreduce scaling efficiency, 8→256 chips) cannot be
measured on this rig (one chip); this model projects it from quantities
that WERE measured, with every assumption explicit in the output:

- single-chip step time and gradient bytes: measured
  (`benchmarks/results/eager_vs_jit_v5e.json`, profile artifacts);
- ring-allreduce wire cost ``2·(N−1)/N · bytes / busbw`` with the busbw an
  explicit parameter (default 90 GB/s effective per chip on the v5e 2-D
  torus — a conservative fraction of the 1600 Gbit/s ICI spec);
- controller hot-path cycle from the coordinator simulation
  (`benchmarks/results/controller_sim.json` p50);
- per-dispatch host overhead of the np>1 eager chain: MEASURED on the
  virtual CPU mesh (VERDICT r3 missing #6).  The np=2 artifact
  (`benchmarks/results/eager_np2_cpu.json`, one rank per host core — the
  closest proxy for process-per-chip) is the preferred input; the np=8
  artifact is kept as a 4×-oversubscription stress point, not a model
  input;
- three planes:
  * **jit / SPMD**: XLA overlaps the psum with backward
    (exposed = max(0, t_comm − backward), backward ≈ 2/3 of step);
  * **eager (post-backward tree fusion)**: comm fully exposed + one
    negotiation cycle — the r3 product path;
  * **eager + WFBP** (`make_overlapped_train_step`): gradient allreduce
    compiled INTO the step program; XLA's latency-hiding scheduler
    overlaps it with backward exactly like the jit plane (a TPU core runs
    one program at a time, so this in-program schedule is the only
    physical way to overlap — `horovod_tpu/frameworks/jax/wfbp.py`).
    Steady state needs no per-step negotiation (one-time signature
    check); exposed = max(0, t_comm − backward) + per-step host dispatch
    (measured, see `wfbp_gap` inputs).

This is a MODEL, labeled as such — the driver's multi-chip dry run checks
the sharded code compiles/executes; real 8–256-chip numbers need a pod.

Run: ``python benchmarks/scaling_model.py
[--out benchmarks/results/scaling_model.json]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys


MODELS = {
    # name: (measured single-chip step ms [jit], grad bytes)
    "resnet50_bs128": (50.1, 25_557_032 * 4),
    "bert_large_bs8": (121.4, 334_000_000 * 4),
}

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")


def _load_json(name):
    path = os.path.join(RESULTS_DIR, name)
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return None


def _codec_factor(name: str):
    """Wire-byte divisor for a HOROVOD_WIRE_COMPRESSION value, derived
    from the codec's own ``wire_nbytes`` at the default ring segment on
    f32 — the same arithmetic the transport uses to frame, so the model
    input cannot drift from the implementation.  Returns None for an
    unknown codec name."""
    if name in ("fp16", "bf16"):
        return 2.0
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    import numpy as np

    from horovod_tpu.backend import compression as comp_mod

    if name == "int8":
        comp = comp_mod.Int8Compressor()
    elif name == "onebit":
        comp = comp_mod.OneBitCompressor()
    else:
        m = comp_mod._TOPK_RE.match(name)
        if m is None or not 1 <= int(m.group(1)) <= 100:
            return None
        comp = comp_mod.TopKCompressor(int(m.group(1)))
    dtype = np.dtype(np.float32)
    from horovod_tpu.common.env import DEFAULT_RING_SEGMENT_BYTES
    n = DEFAULT_RING_SEGMENT_BYTES // dtype.itemsize
    return n * dtype.itemsize / comp.wire_nbytes(n, dtype)


def project(step_ms: float, grad_bytes: int, n: int, busbw_gbs: float,
            cycle_ms: float, dispatch_ms: float,
            wfbp_overhead_ms: float, compression_factor: float = 1.0,
            local_size: int = 1, intra_busbw_gbs: float = 0.0) -> dict:
    # Cast-on-the-wire compression (docs/data_plane.md) divides the bytes
    # crossing the wire — fp16/bf16 on f32 grads is factor 2 — while the
    # cast itself runs at memory bandwidth, far above wire busbw, so the
    # model folds it entirely into t_comm.
    wire_bytes = grad_bytes / compression_factor
    if local_size > 1 and n % local_size == 0 and n > local_size:
        # Hierarchical cut (docs/data_plane.md "Transports"): the
        # intra-host phase rides shm at intra_busbw (reduce-scatter +
        # allgather over the full payload inside each host), the
        # cross-host phase moves only 1/local_size of the payload per
        # chip over the inter-host fabric.
        hosts = n // local_size
        t_intra = (2 * (local_size - 1) / local_size * wire_bytes
                   / (intra_busbw_gbs * 1e9) * 1e3)
        t_cross = (2 * (hosts - 1) / hosts * (wire_bytes / local_size)
                   / (busbw_gbs * 1e9) * 1e3)
        t_comm = t_intra + t_cross
    else:
        t_comm = 2 * (n - 1) / n * wire_bytes / (busbw_gbs * 1e9) * 1e3
    backward_ms = step_ms * 2 / 3
    jit_exposed = max(0.0, t_comm - backward_ms)
    # dispatch_ms (measured probe) already contains one full negotiation
    # round at small N; cycle_ms models how that round grows with N — take
    # the max rather than summing both (they are the same cost, not
    # additive).
    eager_exposed = t_comm + max(cycle_ms, dispatch_ms)
    wfbp_exposed = max(0.0, t_comm - backward_ms) + wfbp_overhead_ms
    return {
        "chips": n,
        "allreduce_ms": round(t_comm, 3),
        "jit_efficiency": round(step_ms / (step_ms + jit_exposed), 4),
        "eager_efficiency": round(step_ms / (step_ms + eager_exposed), 4),
        "eager_wfbp_efficiency": round(
            step_ms / (step_ms + wfbp_exposed), 4),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--busbw-gbs", type=float, default=90.0,
                   help="effective per-chip allreduce busbw (v5e ICI)")
    p.add_argument("--chips", type=int, nargs="+",
                   default=[8, 16, 64, 256])
    p.add_argument("--compression-factor", type=float, default=1.0,
                   help="wire-byte divisor from HOROVOD_WIRE_COMPRESSION "
                        "(2.0 for fp16/bf16 on f32 grads, 1.0 = raw)")
    p.add_argument("--codec", default=None,
                   help="derive --compression-factor from a codec's "
                        "wire_nbytes ratio on f32 at the default ring "
                        "segment (any HOROVOD_WIRE_COMPRESSION value: "
                        "fp16|bf16|int8|onebit|topk<K>) instead of "
                        "hand-computing it")
    p.add_argument("--local-size", type=int, default=1,
                   help="chips per host: >1 switches to the hierarchical "
                        "cut — intra-host phase at --intra-busbw-gbs "
                        "(shm data plane), cross-host bytes divided by "
                        "local size")
    p.add_argument("--intra-busbw-gbs", type=float, default=400.0,
                   help="effective intra-host allreduce busbw for the "
                        "shm transport (memory-bandwidth bound; see "
                        "benchmarks/results/ring_transport_sweep_r11."
                        "json for this box's measured shm-vs-tcp ratio)")
    p.add_argument("--out", default=None)
    args = p.parse_args()
    if args.codec is not None:
        if args.compression_factor != 1.0:
            p.error("--codec derives the factor; don't also pass "
                    "--compression-factor")
        factor = _codec_factor(args.codec)
        if factor is None:
            p.error(f"unknown --codec {args.codec!r} (expected "
                    "fp16|bf16|int8|onebit|topk<K>, K in [1, 100])")
        args.compression_factor = factor
    if args.compression_factor <= 0:
        p.error("--compression-factor must be positive")
    if args.local_size < 1:
        p.error("--local-size must be >= 1")
    if args.intra_busbw_gbs <= 0:
        p.error("--intra-busbw-gbs must be positive")

    # hot-path coordinator cycle p50 from the committed simulation
    # (benchmarks/results/controller_sim.json), by N
    cycle = {8: 0.66, 16: 0.75, 64: 1.14, 256: 2.14}

    # Per-dispatch host overhead of the np>1 chain (VERDICT r3 missing
    # #6): measured on the virtual CPU mesh.  Prefer the np=2 artifact —
    # one rank per host core, the closest proxy for TPU's
    # process-per-chip layout; the np=8 artifact (8 ranks on 2 cores, 4×
    # oversubscribed) is kept as the contention stress point, not a model
    # input.  min over probe sizes: scheduler jitter dominates single
    # probes on a busy host.
    np8 = _load_json("eager_np8_cpu.json")
    np2 = _load_json("eager_np2_cpu.json")
    src = np2 or np8
    if src is not None:
        dispatch_ms = min(float(v)
                          for v in src["dispatch_probe_ms"].values())
        dispatch_src = (f"measured: eager_np{src['world_size']}_cpu.json "
                        "min(dispatch_probe_ms) — full enqueue→negotiate→"
                        "fuse→global-mesh-collective→unfuse chain, CPU "
                        "upper bound (includes the CPU gloo collective "
                        "itself)")
    else:
        dispatch_ms = 2.0
        dispatch_src = "assumed (no np>1 artifact)"

    # Per-step host overhead of the compiled WFBP step: measured on the
    # real chip when eager_vs_jit_v5e.json carries wfbp_step_ms; else the
    # np=8 CPU artifact's wfbp-vs-jit delta; else assumed.
    v5e = _load_json("eager_vs_jit_v5e.json")
    if v5e is not None and "wfbp_step_ms" in v5e:
        wfbp_ms = max(0.0, float(v5e["wfbp_step_ms"])
                      - float(v5e["jit_step_ms"]))
        wfbp_src = ("measured: eager_vs_jit_v5e.json wfbp_step_ms − "
                    "jit_step_ms (single v5e chip)")
    elif np8 is not None:
        wfbp_ms = max(0.0, float(np8["wfbp_step_ms"])
                      - float(np8["jit_step_ms"]))
        wfbp_src = ("measured: eager_np8_cpu.json wfbp−jit delta (CPU "
                    "mesh upper bound; includes the actual CPU-collective "
                    "time XLA cannot overlap on one host)")
    else:
        wfbp_ms = 1.0
        wfbp_src = "assumed (no artifact)"

    out = {
        "model": "analytic ring-allreduce projection (see module docstring)",
        "assumptions": {
            "busbw_gbs": args.busbw_gbs,
            "compression_factor": round(args.compression_factor, 4),
            "compression_codec": args.codec,
            "local_size": args.local_size,
            "intra_busbw_gbs": (args.intra_busbw_gbs
                                if args.local_size > 1 else None),
            "overlap_window": "2/3 of step (backward) for the jit and "
                              "eager-WFBP planes; none for the "
                              "post-backward eager plane",
            "controller_cycle_ms": cycle,
            "per_dispatch_ms": {"value": dispatch_ms,
                                "provenance": dispatch_src},
            "wfbp_step_overhead_ms": {"value": wfbp_ms,
                                      "provenance": wfbp_src},
        },
        "projections": {},
    }
    for name, (step_ms, grad_bytes) in MODELS.items():
        out["projections"][name] = [
            project(step_ms, grad_bytes, n, args.busbw_gbs,
                    cycle.get(n, 2.0), dispatch_ms, wfbp_ms,
                    args.compression_factor, args.local_size,
                    args.intra_busbw_gbs)
            for n in args.chips
        ]
    line = json.dumps(out, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
