"""Eager product-path benchmark: jit step vs DistributedOptimizer step.

The framework's core promise (SURVEY §7.4) is that the Horovod-style eager
path — gradients enqueued as named tensors, negotiated by the background
controller, fused, dispatched through the pre-compiled bucketed XLA
collectives (`backend/xla.py`), results awaited via handles — costs ~nothing
next to a pure-jit step.  This harness measures exactly that on whatever
accelerator is attached, with the SAME model/batch/dtype as `bench.py`:

- **jit**: one compiled train step, gradient sync folded in as a psum
  (the configuration `bench.py` reports).
- **eager**: the same jit'd forward/backward, but the gradient pytree flows
  through ``hvd.DistributedOptimizer`` (full enqueue → negotiate → fuse →
  device collective → unfuse → synchronize per step).  Run under
  ``hvd.init()`` so the runtime is live; at np=1 the negotiation is local
  but every other overhead source (host round-trips, fuse/unfuse dispatch,
  handle waits, cycle latency) is real and measured.

Output: one JSON object with both throughputs and the gap.  The driver's
acceptance bar (VERDICT r2 #1) is gap ≤ ~10%.

Run: ``python benchmarks/eager_bench.py [--batch-size N] [--iters N]``
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--image-size", type=int, default=None)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--iters", type=int, default=30)
    parser.add_argument("--out", default=None,
                        help="also write the JSON result to this path")
    parser.add_argument("--profile", action="store_true", default=False,
                        help="include the per-phase dispatch-chain "
                             "breakdown (negotiate/fuse/collective/unfuse/"
                             "wait) for the eager timed region")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.frameworks.jax.optimizer import DistributedOptimizer
    from horovod_tpu.models import ResNet50
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    on_tpu = jax.devices()[0].platform == "tpu"
    batch_size = args.batch_size or (128 if on_tpu else 8)
    image_size = args.image_size or (224 if on_tpu else 64)
    warmup, iters = args.warmup, (args.iters if on_tpu else 5)

    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    tx = optax.sgd(0.01, momentum=0.9)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(batch_size,)), jnp.int32)

    # ---- jit flavor (bench.py configuration) --------------------------
    mesh = build_mesh(MeshSpec(data=-1))
    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=True)
    batch = shard_batch(mesh, {"x": x, "y": y})
    compiled = step.lower(state, batch).compile()

    for _ in range(warmup):
        state, loss = compiled(state, batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = compiled(state, batch)
    float(loss)
    jit_dt = (time.perf_counter() - t0) / iters
    del state

    # ---- eager flavor (the product path) ------------------------------
    hvd.init()

    estate = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                                init_kwargs={"train": True})
    dopt = DistributedOptimizer(tx)
    params, batch_stats = estate.params, estate.batch_stats
    opt_state = dopt.init(params)

    @jax.jit
    def grad_step(params, batch_stats):
        def loss_fn(p):
            out, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x,
                train=True, mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(y, 1000)
            return optax.softmax_cross_entropy(out, one_hot).mean(), updates
        (loss, updates), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, grads, updates["batch_stats"]

    apply_updates = jax.jit(optax.apply_updates)

    def eager_step():
        nonlocal params, batch_stats, opt_state
        loss, grads, batch_stats = grad_step(params, batch_stats)
        updates, opt_state = dopt.update(grads, opt_state, params)
        params = apply_updates(params, updates)
        return loss

    from horovod_tpu.core.timeline import phase_stats, wire_stats

    for _ in range(warmup):
        loss = eager_step()
    float(loss)
    phase_stats.reset()  # profile the steady-state timed region only
    wire_stats.reset()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = eager_step()
    final_loss = float(loss)
    eager_dt = (time.perf_counter() - t0) / iters
    phase_breakdown = phase_stats.snapshot()
    wire_counters = wire_stats.snapshot()
    assert np.isfinite(final_loss)

    # ---- wfbp flavor: forward+backward+allreduce+update, ONE program --
    # (the in-program WFBP overlap — horovod_tpu.frameworks.jax.wfbp)
    from horovod_tpu.frameworks.jax.wfbp import make_overlapped_train_step

    def wfbp_loss(p, bstats, b):
        out, updates = model.apply(
            {"params": p, "batch_stats": bstats}, b["x"],
            train=True, mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(b["y"], 1000)
        loss = optax.softmax_cross_entropy(out, one_hot).mean()
        return loss, updates["batch_stats"]

    wstate = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                                init_kwargs={"train": True})
    wstep = make_overlapped_train_step(wfbp_loss, tx, has_aux=True)
    wp, ws, wa = wstep.init(wstate.params, tx.init(wstate.params),
                            wstate.batch_stats)
    wbatch = {"x": x, "y": y}

    for _ in range(max(1, warmup)):  # >=1: compile outside the timed loop
        wp, ws, wa, wloss = wstep(wp, ws, wbatch, wa)
    float(np.asarray(wloss))
    t0 = time.perf_counter()
    for _ in range(iters):
        wp, ws, wa, wloss = wstep(wp, ws, wbatch, wa)
    float(np.asarray(wloss))
    wfbp_dt = (time.perf_counter() - t0) / iters

    from horovod_tpu.backend import xla as xla_backend
    result = {
        "metric": "eager_vs_jit_resnet50",
        "batch_size": batch_size,
        "image_size": image_size,
        "iters": iters,
        "world_size": hvd.size(),
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "jit_images_per_sec": round(batch_size / jit_dt, 2),
        "eager_images_per_sec": round(batch_size / eager_dt, 2),
        "jit_step_ms": round(jit_dt * 1e3, 3),
        "eager_step_ms": round(eager_dt * 1e3, 3),
        "eager_overhead_ms": round((eager_dt - jit_dt) * 1e3, 3),
        "gap_pct": round((eager_dt - jit_dt) / jit_dt * 100, 2),
        "wfbp_step_ms": round(wfbp_dt * 1e3, 3),
        "wfbp_gap_pct": round((wfbp_dt - jit_dt) / jit_dt * 100, 2),
        "xla_dispatch_stats": dict(xla_backend.stats),
    }
    if args.profile:
        # Where the eager step's overhead budget goes, per phase, over the
        # timed region (totals across all iters; mean per occurrence).
        result["phase_breakdown_ms"] = phase_breakdown
        # Data-plane counters (core/timeline.py wire_stats): payload bytes
        # the transport moved and heap materializations in the host data
        # plane during the steady-state timed region.
        result["wire_counters"] = wire_counters
    hvd.shutdown()
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
