"""BERT-large pretraining-step benchmark: tokens/sec + MFU on one chip.

BASELINE.md's scaling target names BERT-large alongside ResNet-50; this
is the transformer-side companion of ``bench.py`` (same MFU methodology:
XLA cost-model FLOPs over the chip's bf16 peak).  Transformers are
matmul-dominated, so this is the number that shows how close the model
stack gets to the MXU's ceiling — convnets (ResNet) are capped far lower
by small-channel convs and batch-norm memory traffic.

Run: ``python benchmarks/bert_bench.py [--batch-size 8 --seq-len 512]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _PEAK_FLOPS, _peak_for  # noqa: E402  (shared tables)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize blocks (less HBM traffic, more "
                        "FLOPs — wins when the step is memory-bound)")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models.transformer import (
        Transformer,
        bert_large_config,
        tiny_config,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    bs = args.batch_size or (8 if on_tpu else 2)
    seq = args.seq_len or (512 if on_tpu else 32)
    cfg = bert_large_config(max_len=seq, causal=False,
                            remat=args.remat) if on_tpu \
        else tiny_config(max_len=seq, causal=False, remat=args.remat)
    model = Transformer(cfg)
    tx = optax.adamw(1e-4)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (bs, seq)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    opt_state = tx.init(params)

    def loss_fn(params, toks):
        logits = model.apply({"params": params}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, toks).mean()

    def step(params, opt_state, toks):
        loss, grads = jax.value_and_grad(loss_fn)(params, toks)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    compiled = jax.jit(step, donate_argnums=(0, 1)).lower(
        params, opt_state, tokens).compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops_per_step = float(ca["flops"])
        src = "xla_cost_analysis"
    except Exception:  # noqa: BLE001
        # 6 * params * tokens approximation (fwd+bwd), params ~334M
        flops_per_step = 6 * 334e6 * bs * seq
        src = "analytic"

    for _ in range(3):
        params, opt_state, loss = compiled(params, opt_state, tokens)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        params, opt_state, loss = compiled(params, opt_state, tokens)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)

    flops_per_sec = flops_per_step * args.iters / dt
    peak = _peak_for(jax.devices()[0]) if on_tpu else None
    print(json.dumps({
        "metric": "bert_large_tokens_per_sec_per_chip" if on_tpu
        else "tiny_transformer_tokens_per_sec",
        "value": round(bs * seq * args.iters / dt, 1),
        "unit": "tokens/sec/chip",
        "mfu": round(flops_per_sec / peak, 4) if peak else 0.0,
        "tflops_per_sec_per_chip": round(flops_per_sec / 1e12, 2),
        "flops_source": src,
        "batch_size": bs,
        "seq_len": seq,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
