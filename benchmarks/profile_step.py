"""Capture + summarize a device profile of the headline train steps.

Produces the per-op-class breakdown VERDICT r2 asked for: captures a
``jax.profiler.trace`` around N steady-state steps of the ResNet-50 (or
BERT) benchmark config, then parses the chrome-trace into device-time
shares by fused-op class and prints a roofline table (XLA cost-model bytes
vs HBM bandwidth, FLOPs vs MXU peak).

Run on the chip:  ``python benchmarks/profile_step.py [--model bert]
[--out benchmarks/results/resnet50_profile_v5e.json]``
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _peak_for  # noqa: E402

# v5e HBM bandwidth, public spec sheet (GB/s).
_HBM_BW = {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9, "v5p": 2765e9}


def _bw_for(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, bw in _HBM_BW.items():
        if key in kind:
            return bw
    return None


def parse_trace(trace_dir: str, steps: int) -> dict:
    """Device-time by op class from the chrome trace (pid of the TPU
    device lane; outer jit spans and per-step lanes excluded)."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    data = json.load(gzip.open(sorted(paths)[-1]))
    events = data["traceEvents"]
    device_pids = {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "device" in e["args"].get("name", "").lower()
    }
    groups: dict = collections.defaultdict(float)
    leaf_total = 0.0
    for e in events:
        if e.get("ph") == "X" and e["pid"] in device_pids:
            name = e.get("name", "")
            if name.startswith("jit_") or name.isdigit():
                continue  # outer span / per-step lane, not a kernel
            dur = e.get("dur", 0)
            leaf_total += dur
            groups[re.sub(r"[.\d]+$", "", name)] += dur
    out = {
        "device_ms_per_step": round(leaf_total / steps / 1e3, 3),
        "classes": {
            k: {"ms_per_step": round(v / steps / 1e3, 3),
                "share": round(v / leaf_total, 4)}
            for k, v in sorted(groups.items(), key=lambda kv: -kv[1])
            if v / leaf_total > 0.004
        },
    }
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet", choices=["resnet", "bert"])
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    on_tpu = jax.devices()[0].platform == "tpu"

    if args.model == "resnet":
        from horovod_tpu.models import ResNet50
        from horovod_tpu.models.training import (
            create_train_state,
            make_sharded_train_step,
        )
        from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

        bs = args.batch_size or (128 if on_tpu else 8)
        size = 224 if on_tpu else 64
        mesh = build_mesh(MeshSpec(data=-1))
        model = ResNet50(num_classes=1000,
                         dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        tx = optax.sgd(0.01, momentum=0.9)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(bs, size, size, 3), jnp.float32)
        y = jnp.asarray(rng.randint(0, 1000, (bs,)), jnp.int32)
        batch = shard_batch(mesh, {"x": x, "y": y})
        state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                                   mesh=mesh, init_kwargs={"train": True})
        step = make_sharded_train_step(model, tx, mesh,
                                       has_batch_stats=True, donate=True)
        compiled = step.lower(state, batch).compile()
        carry = (state,)

        def run_once(carry):
            state, = carry
            state, loss = compiled(state, batch)
            return (state,), loss
    else:
        from horovod_tpu.models.transformer import (
            Transformer,
            bert_large_config,
            tiny_config,
        )

        bs = args.batch_size or (8 if on_tpu else 2)
        seq = 512 if on_tpu else 32
        cfg = bert_large_config(max_len=seq, causal=False) if on_tpu \
            else tiny_config(max_len=seq, causal=False)
        model = Transformer(cfg)
        tx = optax.adamw(1e-4)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (bs, seq)),
                             jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        opt_state = tx.init(params)

        def loss_fn(params, toks):
            logits = model.apply({"params": params}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, toks).mean()

        def stepf(params, opt_state, toks):
            loss, grads = jax.value_and_grad(loss_fn)(params, toks)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        compiled = jax.jit(stepf, donate_argnums=(0, 1)).lower(
            params, opt_state, tokens).compile()
        carry = (params, opt_state)

        def run_once(carry):
            params, opt_state = carry
            params, opt_state, loss = compiled(params, opt_state, tokens)
            return (params, opt_state), loss

    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = float(ca.get("flops", 0))
    byts = float(ca.get("bytes accessed", 0))

    for _ in range(3):
        carry, loss = run_once(carry)
    float(loss)

    tmp = tempfile.mkdtemp(prefix="hvdprof-")
    with jax.profiler.trace(tmp):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            carry, loss = run_once(carry)
        float(loss)
        dt = (time.perf_counter() - t0) / args.steps

    report = parse_trace(tmp, args.steps)
    dev = jax.devices()[0]
    peak = _peak_for(dev) if on_tpu else None
    bw = _bw_for(dev) if on_tpu else None
    report.update({
        "model": args.model,
        "batch_size": bs,
        "device": getattr(dev, "device_kind", "cpu"),
        "measured_ms_per_step": round(dt * 1e3, 3),
        "cost_model_flops_per_step": flops,
        "cost_model_bytes_per_step": byts,
        "roofline": {
            "compute_floor_ms": round(flops / peak * 1e3, 2) if peak else None,
            "memory_floor_ms": round(byts / bw * 1e3, 2) if bw else None,
            "bound": (("memory" if byts / bw > flops / peak else "compute")
                      if (peak and bw) else None),
            "mfu": round(flops / dt / peak, 4) if peak else None,
        },
    })
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
