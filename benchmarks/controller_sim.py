"""Coordinator-cost simulation: REAL controller code, modeled wire.

VERDICT r2 #10: `controller_bench.py`'s numbers on a 2-core CI host
measure core timesharing, not the protocol.  This harness removes the
host from the equation: it drives the REAL `Controller._coordinator_round`
(parse, IncrementTensorCount, ConstructResponse, FuseResponses, cache
bookkeeping, serialize) against an in-memory mesh pre-loaded with each
worker's actual serialized `RequestList`, and times the coordinator's CPU
per cycle as world size scales — the part of the star protocol that grows
with N and cannot overlap anything.

Wire time is modeled separately and additively (it overlaps across
workers): workers transmit concurrently, the kernel buffers, and the
coordinator's sequential `recv`s read buffered data, so cycle wall ≈
worker flight (1 RTT) + coordinator CPU + response broadcast serialization.

Outputs one JSON line per (world_size, scenario):
  - cold: every worker submits full Requests for T tensors (first cycle)
  - hot:  every worker submits T cache bits (steady-state fast path)

Run: ``python benchmarks/controller_sim.py [--world-sizes 8 16 64 256]
[--tensors 50] [--out benchmarks/results/controller_sim.json]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.common.topology import ProcessTopology  # noqa: E402
from horovod_tpu.core.controller import Controller  # noqa: E402
from horovod_tpu.core.messages import (  # noqa: E402
    DataType,
    Request,
    RequestList,
    RequestType,
)


class RecordingMesh:
    """In-memory mesh: `recv(w)` pops the next canned payload for w;
    `send(w, b)` accounts bytes.  No sockets, no sleeps — the coordinator
    CPU is the only cost left."""

    def __init__(self):
        self.inbox = {}
        self.sent_bytes = 0
        self.sends = 0

    def preload(self, worker: int, payload: bytes) -> None:
        self.inbox.setdefault(worker, []).append(payload)

    def recv(self, worker: int) -> bytes:
        return self.inbox[worker].pop(0)

    def send(self, worker: int, payload: bytes) -> None:
        self.sent_bytes += len(payload)
        self.sends += 1


def requests_for(t: int, rank: int):
    return [Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                    tensor_name=f"grad.{i}", tensor_type=DataType.FLOAT32,
                    tensor_shape=[1024, 1024], device=0)
            for i in range(t)]


def _subtree(rank: int, size: int):
    """All ranks in rank's binomial subtree (itself included)."""
    from horovod_tpu.core.controller import tree_children

    out = [rank]
    for c in tree_children(rank, size):
        out.extend(_subtree(c, size))
    return out


def _preload(mesh, ctrl, world: int, payloads: dict) -> None:
    """Feed per-worker payloads to the coordinator in the shape its
    fan-out topology expects: direct messages for the star, per-child
    subtree bundles for the tree (what interior ranks would relay)."""
    if ctrl.fanout_topology == "tree":
        from horovod_tpu.core.controller import _encode_bundle, tree_children

        for child in tree_children(0, world):
            mesh.preload(child, _encode_bundle(
                [(r, payloads[r]) for r in _subtree(child, world)]))
    else:
        for w, p in payloads.items():
            mesh.preload(w, p)


def run_case(world: int, tensors: int, cycles: int) -> dict:
    topo = ProcessTopology(rank=0, size=world, local_rank=0,
                           local_size=world, cross_rank=0, cross_size=1)
    mesh = RecordingMesh()
    ctrl = Controller(topo, mesh)

    # ---- cold cycle: full Requests from every worker ----
    cold_payload = {
        w: RequestList(requests=requests_for(tensors, w)).to_bytes()
        for w in range(1, world)
    }
    _preload(mesh, ctrl, world, cold_payload)
    t0 = time.perf_counter()
    rlist = ctrl.compute_response_list(requests_for(tensors, 0))
    cold_ms = (time.perf_counter() - t0) * 1e3
    assert rlist.responses, "cold cycle negotiated nothing"
    n_responses = len(rlist.responses)

    # Bits assigned this cycle — workers would mirror them; replay the
    # coordinator's own assignment order as each worker's hit list.
    bits = [a[0] if isinstance(a, (list, tuple)) else a
            for a in rlist.cache_assignments]

    # ---- hot cycles: every worker sends the dense bit MASK, exactly the
    # wire real workers produce in _worker_round ----
    mask = 0
    for b in bits:
        mask |= 1 << b
    mask_bytes = mask.to_bytes((mask.bit_length() + 7) // 8, "little")
    reps = []
    hot_payload = RequestList(requests=[], cache_mask=mask_bytes).to_bytes()
    for _ in range(cycles):
        _preload(mesh, ctrl, world,
                 {w: hot_payload for w in range(1, world)})
        t0 = time.perf_counter()
        rl = ctrl.compute_response_list(requests_for(tensors, 0))
        reps.append((time.perf_counter() - t0) * 1e3)
        assert len(rl.responses) == n_responses
    reps.sort()
    gather_bytes = sum(len(p) for p in cold_payload.values())
    return {
        "metric": "coordinator_cycle_cost",
        "world_size": world,
        "fanout_topology": ctrl.fanout_topology,
        "tensors": tensors,
        "fused_responses": n_responses,
        "cold_cycle_ms": round(cold_ms, 3),
        "hot_cycle_ms_p50": round(reps[len(reps) // 2], 3),
        "hot_cycle_ms_p99": round(reps[int(len(reps) * 0.99)], 3),
        "cold_gather_bytes": gather_bytes,
        "response_bcast_bytes": mesh.sent_bytes // max(mesh.sends, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--world-sizes", type=int, nargs="+",
                   default=[8, 16, 64, 256])
    p.add_argument("--tensors", type=int, default=50)
    p.add_argument("--cycles", type=int, default=200)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    lines = []
    for world in args.world_sizes:
        rec = run_case(world, args.tensors, args.cycles)
        line = json.dumps(rec)
        print(line, flush=True)
        lines.append(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write("\n".join(lines) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
