"""Coordinator-cost simulation: REAL controller code, modeled wire.

VERDICT r2 #10: `controller_bench.py`'s numbers on a 2-core CI host
measure core timesharing, not the protocol.  This harness removes the
host from the equation: it drives the REAL `Controller._coordinator_round`
(parse, IncrementTensorCount, ConstructResponse, FuseResponses, cache
bookkeeping, serialize) against an in-memory mesh pre-loaded with each
worker's actual serialized `RequestList`, and times the coordinator's CPU
per cycle as world size scales — the part of the star protocol that grows
with N and cannot overlap anything.

Wire time is modeled separately and additively (it overlaps across
workers): workers transmit concurrently, the kernel buffers, and the
coordinator's sequential `recv`s read buffered data, so cycle wall ≈
worker flight (1 RTT) + coordinator CPU + response broadcast serialization.

Outputs one JSON line per (world_size, scenario):
  - cold: every worker submits full Requests for T tensors (first cycle)
  - hot:  every worker submits T cache bits (steady-state fast path)

Run: ``python benchmarks/controller_sim.py [--world-sizes 8 16 64 256]
[--tensors 50] [--out benchmarks/results/controller_sim.json]``

``--churn`` switches to the CONTROL-plane cost model (ROADMAP item 4
seed): a real journaled rendezvous server, driven over the real
HTTPStoreClient with the op mix one membership-churn event costs the
elastic driver at world size N — full lease scan (keys + N gets), slot
table republish (N puts), and a full round of lease renewals (N puts) —
plus what durability adds: journal bytes on disk, compaction
generations, and cold-restart replay time.  Each record also carries an
``attribution`` block (unless ``--no-trace``): the server+driver traces
are merged and run through ``hvd-control-path`` so the JSON says how the
per-event wall time splits across store lock wait / journal fsync / HTTP
round-trips.  Baseline artifact:
``python benchmarks/controller_sim.py --churn --world-sizes 64
--out benchmarks/results/controller_churn_np64.json``; the metrics
overhead guard (gate: <= 1.05x) is ``--churn --overhead-out
benchmarks/results/server_metrics_overhead_r14.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.common.topology import ProcessTopology  # noqa: E402
from horovod_tpu.core.controller import Controller  # noqa: E402
from horovod_tpu.core.messages import (  # noqa: E402
    DataType,
    Request,
    RequestList,
    RequestType,
)


class RecordingMesh:
    """In-memory mesh: `recv(w)` pops the next canned payload for w;
    `send(w, b)` accounts bytes.  No sockets, no sleeps — the coordinator
    CPU is the only cost left."""

    def __init__(self):
        self.inbox = {}
        self.sent_bytes = 0
        self.sends = 0

    def preload(self, worker: int, payload: bytes) -> None:
        self.inbox.setdefault(worker, []).append(payload)

    def recv(self, worker: int) -> bytes:
        return self.inbox[worker].pop(0)

    def send(self, worker: int, payload: bytes) -> None:
        self.sent_bytes += len(payload)
        self.sends += 1


def requests_for(t: int, rank: int):
    return [Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                    tensor_name=f"grad.{i}", tensor_type=DataType.FLOAT32,
                    tensor_shape=[1024, 1024], device=0)
            for i in range(t)]


def _subtree(rank: int, size: int):
    """All ranks in rank's binomial subtree (itself included)."""
    from horovod_tpu.core.controller import tree_children

    out = [rank]
    for c in tree_children(rank, size):
        out.extend(_subtree(c, size))
    return out


def _preload(mesh, ctrl, world: int, payloads: dict) -> None:
    """Feed per-worker payloads to the coordinator in the shape its
    fan-out topology expects: direct messages for the star, per-child
    subtree bundles for the tree (what interior ranks would relay)."""
    if ctrl.fanout_topology == "tree":
        from horovod_tpu.core.controller import _encode_bundle, tree_children

        for child in tree_children(0, world):
            mesh.preload(child, _encode_bundle(
                [(r, payloads[r]) for r in _subtree(child, world)]))
    else:
        for w, p in payloads.items():
            mesh.preload(w, p)


def run_case(world: int, tensors: int, cycles: int) -> dict:
    topo = ProcessTopology(rank=0, size=world, local_rank=0,
                           local_size=world, cross_rank=0, cross_size=1)
    mesh = RecordingMesh()
    ctrl = Controller(topo, mesh)

    # ---- cold cycle: full Requests from every worker ----
    cold_payload = {
        w: RequestList(requests=requests_for(tensors, w)).to_bytes()
        for w in range(1, world)
    }
    _preload(mesh, ctrl, world, cold_payload)
    t0 = time.perf_counter()
    rlist = ctrl.compute_response_list(requests_for(tensors, 0))
    cold_ms = (time.perf_counter() - t0) * 1e3
    assert rlist.responses, "cold cycle negotiated nothing"
    n_responses = len(rlist.responses)

    # Bits assigned this cycle — workers would mirror them; replay the
    # coordinator's own assignment order as each worker's hit list.
    bits = [a[0] if isinstance(a, (list, tuple)) else a
            for a in rlist.cache_assignments]

    # ---- hot cycles: every worker sends the dense bit MASK, exactly the
    # wire real workers produce in _worker_round ----
    mask = 0
    for b in bits:
        mask |= 1 << b
    mask_bytes = mask.to_bytes((mask.bit_length() + 7) // 8, "little")
    reps = []
    hot_payload = RequestList(requests=[], cache_mask=mask_bytes).to_bytes()
    for _ in range(cycles):
        _preload(mesh, ctrl, world,
                 {w: hot_payload for w in range(1, world)})
        t0 = time.perf_counter()
        rl = ctrl.compute_response_list(requests_for(tensors, 0))
        reps.append((time.perf_counter() - t0) * 1e3)
        assert len(rl.responses) == n_responses
    reps.sort()
    gather_bytes = sum(len(p) for p in cold_payload.values())
    return {
        "metric": "coordinator_cycle_cost",
        "world_size": world,
        "fanout_topology": ctrl.fanout_topology,
        "tensors": tensors,
        "fused_responses": n_responses,
        "cold_cycle_ms": round(cold_ms, 3),
        "hot_cycle_ms_p50": round(reps[len(reps) // 2], 3),
        "hot_cycle_ms_p99": round(reps[int(len(reps) * 0.99)], 3),
        "cold_gather_bytes": gather_bytes,
        "response_bcast_bytes": mesh.sent_bytes // max(mesh.sends, 1),
    }


def _percentile(sorted_ms, frac):
    return round(sorted_ms[min(int(len(sorted_ms) * frac),
                               len(sorted_ms) - 1)], 3)


def run_churn_case(world: int, events: int, trace: bool = True,
                   batched: bool = False) -> dict:
    """One membership-churn baseline at world size N, end to end through
    the journaled rendezvous server (started in-process, driven over
    HTTP like a real driver would).

    With ``trace=True`` (the default) the server writes its control-plane
    timeline (RV_* handler spans, RV_LOCK_WAIT, JR_* journal spans), the
    sim drives the client under a driver-pid timeline (RVC_* round-trips
    plus one CHURN_EVENT window per event), and the merged traces are fed
    through ``hvd-control-path`` in-process — the record then carries an
    ``attribution`` block saying where each event's wall time went.

    ``batched=True`` issues the op mix the way the post-ISSUE-15 driver
    does — everything through ``client.batch`` (lease scan = one frame of
    N gets, republish = one frame of N+1 puts, renewals = one frame of N
    puts) — so the SAME call sites measure both protocols: with
    ``HOROVOD_RENDEZVOUS_BATCH=0`` in the environment the client (and
    server) fall back to per-op round-trips, which is exactly the control
    arm the A/B mode uses."""
    import shutil
    import tempfile

    from horovod_tpu.runner.rendezvous import RendezvousServer
    from horovod_tpu.transport.store import LEASE_SCOPE, HTTPStoreClient

    jdir = tempfile.mkdtemp(prefix="hvd-churn-")
    tdir = tempfile.mkdtemp(prefix="hvd-churn-trace-") if trace else None
    server_trace = os.path.join(tdir, "server.json") if trace else None
    server = RendezvousServer("127.0.0.1", journal_dir=jdir,
                              trace_path=server_trace)
    port = server.start()
    client = HTTPStoreClient("127.0.0.1", port)
    tl = None
    if trace:
        from horovod_tpu.core.timeline import DRIVER_TRACE_PID, Timeline

        # Plays the driver's role: activates so the client's RVC_* spans
        # have a sink; offset 0 — same host as the server (clock base).
        tl = Timeline(os.path.join(tdir, "driver.json"),
                      rank=DRIVER_TRACE_PID, clock_offset_ns=0,
                      process_name="churn driver (sim)")
    identities = [f"host{r:03d}:0" for r in range(world)]

    def _slot(rank: int, identity: str, epoch: int) -> bytes:
        return json.dumps({
            "hostname": identity.split(":")[0], "rank": rank,
            "local_rank": 0, "cross_rank": rank, "size": world,
            "local_size": 1, "cross_size": world, "epoch": epoch,
        }).encode()

    def _lease(rank: int, epoch: int, renewal: int) -> bytes:
        return json.dumps({"rank": rank, "epoch": epoch,
                           "renewals": renewal}).encode()

    if batched:
        # The post-ISSUE-15 driver's shape: one /batch frame per phase
        # (see ElasticDriver._tick_store_reads / _rendezvous_epoch).
        def publish_table(epoch: int) -> None:
            client.batch(
                [("set", "rank_and_size", identity,
                  _slot(rank, identity, epoch))
                 for rank, identity in enumerate(identities)]
                + [("set", "driver", "epoch", str(epoch).encode())])

        def renew_leases(epoch: int, renewal: int) -> None:
            client.batch([("set", LEASE_SCOPE, identity,
                           _lease(rank, epoch, renewal))
                          for rank, identity in enumerate(identities)])

        def lease_scan() -> None:
            client.batch([("get", LEASE_SCOPE, identity)
                          for identity in identities])
    else:
        def publish_table(epoch: int) -> None:
            for rank, identity in enumerate(identities):
                client.set("rank_and_size", identity,
                           _slot(rank, identity, epoch))
            client.set("driver", "epoch", str(epoch).encode())

        def renew_leases(epoch: int, renewal: int) -> None:
            for rank, identity in enumerate(identities):
                client.set(LEASE_SCOPE, identity,
                           _lease(rank, epoch, renewal))

        def lease_scan() -> None:
            for identity in client.keys(LEASE_SCOPE):
                client.get(LEASE_SCOPE, identity)

    t0 = time.perf_counter()
    publish_table(0)
    renew_leases(0, 0)
    bringup_ms = (time.perf_counter() - t0) * 1e3

    event_ms, scan_ms, republish_ms = [], [], []
    for event in range(events):
        # One churn event = what one epoch advance costs the driver:
        # scan every lease, republish the whole table, absorb a renewal
        # round at the new epoch.  Deterministic — no randomness.
        t0_ns = time.monotonic_ns() if tl is not None else 0
        t0 = time.perf_counter()
        lease_scan()
        t1 = time.perf_counter()
        publish_table(event + 1)
        t2 = time.perf_counter()
        renew_leases(event + 1, event + 1)
        t3 = time.perf_counter()
        scan_ms.append((t1 - t0) * 1e3)
        republish_ms.append((t2 - t1) * 1e3)
        event_ms.append((t3 - t0) * 1e3)
        if tl is not None:
            tl.span_since("driver", "CHURN_EVENT", t0_ns,
                          {"cause": "sim", "epoch": event + 1})
    if tl is not None:
        tl.close()
    server.stop()

    attribution = None
    if trace:
        from horovod_tpu.tools.control_path import analyze
        from horovod_tpu.tools.trace_merge import load_trace, merge

        doc = analyze(merge([load_trace(server_trace),
                             load_trace(os.path.join(tdir, "driver.json"))]))
        attribution = {
            "coverage": doc["coverage"],
            "phase_share": doc["phase_share"],
            "phase_ms_per_event": {
                p: round(v / 1e3 / max(events, 1), 3)
                for p, v in doc["phase_totals_us"].items()},
        }
        shutil.rmtree(tdir, ignore_errors=True)

    journal_bytes = sum(
        os.path.getsize(os.path.join(jdir, f)) for f in os.listdir(jdir))
    generations = sorted(f for f in os.listdir(jdir)
                         if f.startswith("journal-"))

    # Cold-restart cost: the survivability price a supervisor pays.
    from horovod_tpu.transport.store import DurableMemoryStore

    t0 = time.perf_counter()
    replayed = DurableMemoryStore(jdir)
    replay_ms = (time.perf_counter() - t0) * 1e3
    replayed_keys = len(replayed.keys(LEASE_SCOPE)) + \
        len(replayed.keys("rank_and_size"))
    replayed.close()
    shutil.rmtree(jdir, ignore_errors=True)

    event_ms.sort(), scan_ms.sort(), republish_ms.sort()
    rec = {
        "metric": "controller_churn",
        "world_size": world,
        "events": events,
        "batched": batched,
        "bringup_ms": round(bringup_ms, 3),
        "event_ms_p50": _percentile(event_ms, 0.5),
        "event_ms_p99": _percentile(event_ms, 0.99),
        "lease_scan_ms_p50": _percentile(scan_ms, 0.5),
        "republish_ms_p50": _percentile(republish_ms, 0.5),
        "journal_bytes": journal_bytes,
        "journal_generation": int(generations[-1].split("-")[1])
        if generations else 0,
        "replay_ms": round(replay_ms, 3),
        "replayed_keys": replayed_keys,
    }
    if attribution is not None:
        rec["attribution"] = attribution
    return rec


def run_churn_ab(world: int, events: int, repeats: int) -> dict:
    """Interleaved batched-vs-per-op A/B at world size N through
    ``ab_harness.ab_compare`` (paired sign test): both arms run the SAME
    batched-style call sites; the control arm holds
    ``HOROVOD_RENDEZVOUS_BATCH=0`` so server and client degrade to the
    old per-op protocol.  The PR gate is verdict == "improvement" with
    the batched arm >= 2x faster per churn event."""
    from ab_harness import ab_compare

    def measure(env) -> float:
        saved = {}
        for k, v in (env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            rec = run_churn_case(world, events, trace=False, batched=True)
            return rec["event_ms_p50"] / 1e3
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old

    doc = ab_compare(measure,
                     control_env={"HOROVOD_RENDEZVOUS_BATCH": "0"},
                     candidate_env={"HOROVOD_RENDEZVOUS_BATCH": "1"},
                     repeats=repeats)
    doc.update({
        "metric": "controller_churn_batched_ab",
        "world_size": world,
        "events": events,
        "label": "rendezvous-batch",
        "speedup": round(doc["median_control_ms"]
                         / max(doc["median_candidate_ms"], 1e-9), 2),
    })
    return doc


def run_churn_overhead(world: int, events: int, rounds: int) -> dict:
    """Interleaved A/B guard for the server-side metrics instrumentation:
    alternate metrics-on / metrics-off churn rounds (tracing off in BOTH
    arms — this isolates the always-on metrics cost, not the opt-in
    timeline cost) and compare medians.  Interleaving makes the arms see
    the same thermal/cache drift; the PR gate is ratio <= 1.05."""
    from horovod_tpu.core import metrics

    samples = {"on": [], "off": []}
    try:
        for _ in range(rounds):
            for mode in ("on", "off"):
                metrics.configure(mode == "on")
                rec = run_churn_case(world, events, trace=False)
                samples[mode].append(rec["event_ms_p50"])
    finally:
        metrics.configure(None)  # back to env-driven policy
    med = {m: sorted(v)[len(v) // 2] for m, v in samples.items()}
    return {
        "metric": "server_metrics_overhead",
        "world_size": world,
        "events": events,
        "rounds": rounds,
        "event_ms_p50_on": med["on"],
        "event_ms_p50_off": med["off"],
        "ratio": round(med["on"] / med["off"], 4),
        "samples_ms": samples,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--world-sizes", type=int, nargs="+",
                   default=[8, 16, 64, 256])
    p.add_argument("--tensors", type=int, default=50)
    p.add_argument("--cycles", type=int, default=200)
    p.add_argument("--churn", action="store_true",
                   help="membership-churn cost against a real journaled "
                        "rendezvous server instead of the coordinator sim")
    p.add_argument("--events", type=int, default=20,
                   help="churn events per world size (--churn only)")
    p.add_argument("--batched", action="store_true",
                   help="drive the churn op mix through /batch "
                        "transactions, one frame per phase, like the "
                        "post-batching driver (--churn only)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip trace capture + attribution (--churn only)")
    p.add_argument("--ab-out", default=None, metavar="PATH",
                   help="run the interleaved batched-vs-per-op A/B "
                        "(ab_harness paired sign test) at the first "
                        "world size and write the verdict record here "
                        "(--churn only)")
    p.add_argument("--ab-repeats", type=int, default=6)
    p.add_argument("--overhead-out", default=None, metavar="PATH",
                   help="instead of the churn sweep, run the interleaved "
                        "metrics on/off A/B at the first world size and "
                        "write the overhead record here (--churn only)")
    p.add_argument("--overhead-rounds", type=int, default=5)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    if args.churn and args.ab_out:
        rec = run_churn_ab(args.world_sizes[0], args.events,
                           args.ab_repeats)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(args.ab_out, "w") as f:
            f.write(line + "\n")
        return 0

    if args.churn and args.overhead_out:
        rec = run_churn_overhead(args.world_sizes[0], args.events,
                                 args.overhead_rounds)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(args.overhead_out, "w") as f:
            f.write(line + "\n")
        return 0

    lines = []
    for world in args.world_sizes:
        if args.churn:
            rec = run_churn_case(world, args.events,
                                 trace=not args.no_trace,
                                 batched=args.batched)
        else:
            rec = run_case(world, args.tensors, args.cycles)
        line = json.dumps(rec)
        print(line, flush=True)
        lines.append(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write("\n".join(lines) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
