"""Headline benchmark: ResNet-50 synthetic training throughput + MFU.

Mirror of the reference's synthetic benchmark
(`examples/tensorflow2/tensorflow2_synthetic_benchmark.py`: ResNet-50,
synthetic ImageNet-shaped batches, warmup then timed iterations, reports
images/sec).  Runs on whatever accelerator is attached (the driver gives
one TPU chip); falls back to CPU with a tiny config so the script always
produces its JSON line.

``vs_baseline`` is **MFU** — measured FLOPs/sec divided by the chip's peak
(VERDICT round 1: the old denominator was a 2016 Pascal GPU figure, a
vanity comparison).  FLOPs/step come from XLA's own cost model
(``compiled.cost_analysis()['flops']``, multiply-add = 2 ops, the same
convention as the peak numbers), with an analytic ResNet-50 fallback.
The reference's published numbers remain in BASELINE.md for context.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import time

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e device_kind is "TPU v5 lite"
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,
}

# Analytic fallback: ResNet-50 forward ~4.09 GMACs at 224x224 = 8.2 GFLOPs
# (MAC=2); training ~3x forward.
_RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9


def _peak_for(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import ResNet50
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    on_tpu = jax.devices()[0].platform == "tpu"
    batch_size = 128 if on_tpu else 8
    image_size = 224 if on_tpu else 64
    warmup, iters = 5, 30 if on_tpu else 5

    mesh = build_mesh(MeshSpec(data=-1))
    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    tx = optax.sgd(0.01, momentum=0.9)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(batch_size,)), jnp.int32)
    batch = shard_batch(mesh, {"x": x, "y": y})

    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=True)

    # AOT-compile once: the same executable serves the timed loop AND the
    # FLOPs measurement (no second trace/compile).
    compiled = step.lower(state, batch).compile()
    n_dev = len(jax.devices())
    # Everything below is PER-DEVICE: cost_analysis describes the
    # SPMD-partitioned per-device module already, while the analytic
    # count covers the global batch and must be divided down.
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops_per_step_dev = float(ca["flops"])
        flops_source = "xla_cost_analysis"
    except Exception:  # noqa: BLE001 — backend without cost model
        flops_per_step_dev = _RESNET50_TRAIN_FLOPS_PER_IMG * batch_size \
            * (image_size / 224) ** 2 / n_dev
        flops_source = "analytic"

    # Sync points use device_get of the step's loss, not block_until_ready:
    # the attached TPU backend can report buffers ready before remote
    # execution finishes, but a host transfer of the final loss cannot
    # complete early — it transitively waits on every chained step.
    for _ in range(warmup):
        state, loss = compiled(state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = compiled(state, batch)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    img_per_sec = batch_size * iters / dt / n_dev
    flops_per_sec = flops_per_step_dev * iters / dt
    peak = _peak_for(jax.devices()[0]) if on_tpu else None
    mfu = round(flops_per_sec / peak, 4) if peak else 0.0

    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": mfu,
        "mfu": mfu,
        "tflops_per_sec_per_chip": round(flops_per_sec / 1e12, 2),
        "flops_per_step_per_device": flops_per_step_dev,
        "flops_source": flops_source,
        "batch_size": batch_size,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
    }))


if __name__ == "__main__":
    main()
