"""Headline benchmark: ResNet-50 synthetic training throughput + MFU.

Mirror of the reference's synthetic benchmark
(`examples/tensorflow2/tensorflow2_synthetic_benchmark.py`: ResNet-50,
synthetic ImageNet-shaped batches, warmup then timed iterations, reports
images/sec).  Runs on whatever accelerator is attached (the driver gives
one TPU chip); falls back to CPU with a tiny config so the script always
produces its JSON line.

``vs_baseline`` is **MFU** — measured FLOPs/sec divided by the chip's peak
(VERDICT round 1: the old denominator was a 2016 Pascal GPU figure, a
vanity comparison).  FLOPs/step come from XLA's own cost model
(``compiled.cost_analysis()['flops']``, multiply-add = 2 ops, the same
convention as the peak numbers), with an analytic ResNet-50 fallback.
The reference's published numbers remain in BASELINE.md for context.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e device_kind is "TPU v5 lite"
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,
}

# Analytic fallback: ResNet-50 forward ~4.09 GMACs at 224x224 = 8.2 GFLOPs
# (MAC=2); training ~3x forward.
_RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9

def _control_block() -> dict:
    """Same-session control: a fixed host workload (f32 512×512 matmul
    chain) timed right next to the headline number.  BENCH numbers on this
    shared box must only be compared against a same-session control
    (ROADMAP cross-cutting note) — the ratio headline/control is
    comparable across rounds even when the box itself speeds up or slows
    down; raw cross-round comparisons are not.  Median of 3 to shed
    scheduler noise; ~100 ms total."""
    import numpy as np

    a0 = np.random.RandomState(1).rand(512, 512).astype(np.float32)
    reps, times = 20, []
    for _ in range(3):
        a = a0.copy()
        t0 = time.perf_counter()
        for _ in range(reps):
            a = a @ a0
            a /= np.abs(a).max() + 1.0  # keep values finite
        times.append(time.perf_counter() - t0)
    med = sorted(times)[1]
    return {
        "workload": "host_matmul_f32_512x512",
        "reps": reps,
        "median_s": round(med, 5),
        "gflops": round(2 * 512 ** 3 * reps / med / 1e9, 2),
        "host_cpus": os.cpu_count(),
    }


# The output contract is ONE JSON line, even when the watchdog thread and
# the main thread race to report (success-vs-hang, error-vs-hang): every
# record goes through _emit, first writer wins.
_emit_lock = threading.Lock()
_emitted = False


def _emit(line: str) -> bool:
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        print(line, flush=True)
        _emitted = True
        return True


def _peak_for(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


# Round 4 lost its BENCH artifact to a wedged TPU: jax.devices() either hung
# or raised UNAVAILABLE in-process, producing rc=1 with no parseable JSON.
# The accelerator probe therefore runs in a *bounded subprocess* first — the
# parent never touches the accelerator backend until a child proved it
# responsive — and total failure degrades to the CPU mini-bench with a
# structured "error": "tpu_unavailable" field instead of a crash.
_PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "print('HVD_PROBE_OK', d[0].platform, len(d), flush=True)"
)


def _probe_accelerator(timeout_s: float = 120.0, retries: int = 3,
                       retry_delay_s: float = 15.0,
                       probe_src: str | None = None) -> dict:
    """Check that backend init completes within a bound, in a subprocess.

    Returns {"ok": True, "platform": ...} or
    {"ok": False, "attempts": [...]} where each attempt records how init
    failed (timeout vs error + message tail).  Never raises.
    """
    attempts: list[dict] = []
    for i in range(retries):
        if i:
            time.sleep(retry_delay_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe_src or _PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
                env=os.environ.copy())
        except subprocess.TimeoutExpired:
            attempts.append({"outcome": "timeout", "timeout_s": timeout_s})
            continue
        out = proc.stdout.strip().splitlines()
        marker = [ln for ln in out if ln.startswith("HVD_PROBE_OK")]
        if proc.returncode == 0 and marker:
            _, platform, n = marker[-1].split()
            return {"ok": True, "platform": platform, "n_devices": int(n),
                    "attempts": attempts}
        attempts.append({
            "outcome": "error", "returncode": proc.returncode,
            "stderr_tail": proc.stderr[-500:],
        })
    return {"ok": False, "attempts": attempts}


def main() -> None:
    # Bounded accelerator probe BEFORE this process imports jax: a wedged
    # chip must degrade to the CPU mini-bench + structured error, not rc=1.
    error = None
    probe: dict = {}
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        want_cpu = True
        probe = {"ok": True, "platform": "cpu", "skipped": True}
    else:
        # Fail FAST to the honest CPU headline: r05 burned 6+ minutes on
        # 3×120 s probe timeouts before ever starting the CPU bench (the
        # wedge never healed within the retry window — it never does on
        # this box).  One bounded attempt decides; operators on flaky
        # real TPUs can raise both knobs.
        probe = _probe_accelerator(
            timeout_s=float(os.environ.get("HVD_BENCH_PROBE_TIMEOUT_S",
                                           "60")),
            retries=int(os.environ.get("HVD_BENCH_PROBE_RETRIES", "1")))
        want_cpu = not probe["ok"]
        if want_cpu:
            error = "tpu_unavailable"
            os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if want_cpu:
        # The axon sitecustomize re-pins the platform at import time; the
        # config update (not just the env var) makes the CPU pin stick —
        # needed on the probe-failure AND the explicit-env path alike.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import ResNet50
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    on_tpu = jax.devices()[0].platform == "tpu"
    batch_size = int(os.environ.get("HVD_BENCH_BATCH",
                                    128 if on_tpu else 8))
    image_size = int(os.environ.get("HVD_BENCH_IMAGE",
                                    224 if on_tpu else 64))
    warmup = int(os.environ.get("HVD_BENCH_WARMUP", 5))
    iters = int(os.environ.get("HVD_BENCH_ITERS", 30 if on_tpu else 5))
    # The data-parallel mesh spans every visible device (a leaked
    # XLA_FLAGS=--xla_force_host_platform_device_count can make that >1
    # even on the CPU fallback); the global batch must divide across it.
    n_dev = len(jax.devices())
    batch_size = -(-batch_size // n_dev) * n_dev

    mesh = build_mesh(MeshSpec(data=-1))
    # Opt-in pallas conv1x1+BN-stat fusion (kernels/conv_bn_stats.py);
    # flip the default only on a measured win (benchmarks/resnet_levers.py
    # "fused_conv1x1_bn" lever).  TPU-only: CPU would interpret the
    # kernel.  Multi-device runs go through the shard_map flavor
    # (psum'd statistics) via fused_bn_mesh.
    fused_bn = on_tpu and os.environ.get("HVD_BENCH_FUSED_BN") == "1"
    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                     fuse_conv1x1_bn=fused_bn,
                     fused_bn_mesh=mesh if fused_bn and n_dev > 1
                     else None)
    tx = optax.sgd(0.01, momentum=0.9)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(batch_size,)), jnp.int32)
    batch = shard_batch(mesh, {"x": x, "y": y})

    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=True)

    # AOT-compile once: the same executable serves the timed loop AND the
    # FLOPs measurement (no second trace/compile).
    compiled = step.lower(state, batch).compile()
    # Everything below is PER-DEVICE: cost_analysis describes the
    # SPMD-partitioned per-device module already, while the analytic
    # count covers the global batch and must be divided down.
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops_per_step_dev = float(ca["flops"])
        flops_source = "xla_cost_analysis"
    except Exception:  # noqa: BLE001 — backend without cost model
        flops_per_step_dev = _RESNET50_TRAIN_FLOPS_PER_IMG * batch_size \
            * (image_size / 224) ** 2 / n_dev
        flops_source = "analytic"

    # Sync points use device_get of the step's loss, not block_until_ready:
    # the attached TPU backend can report buffers ready before remote
    # execution finishes, but a host transfer of the final loss cannot
    # complete early — it transitively waits on every chained step.
    for _ in range(warmup):
        state, loss = compiled(state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = compiled(state, batch)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    img_per_sec = batch_size * iters / dt / n_dev
    flops_per_sec = flops_per_step_dev * iters / dt
    peak = _peak_for(jax.devices()[0]) if on_tpu else None
    mfu = round(flops_per_sec / peak, 4) if peak else 0.0

    record = {
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": mfu,
        "mfu": mfu,
        "tflops_per_sec_per_chip": round(flops_per_sec / 1e12, 2),
        "flops_per_step_per_device": flops_per_step_dev,
        "flops_source": flops_source,
        "batch_size": batch_size,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
        "control": _control_block(),
    }
    if error:
        record["error"] = error
        record["probe"] = probe
    _emit(json.dumps(record))


def _error_record(error: str, detail: str) -> str:
    return json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": error,
        "exception": detail[:1500],
    })


def _run_guarded() -> None:
    """Run main() under a watchdog; any failure still prints ONE JSON line.

    The watchdog is a *thread* that prints the error record and
    ``os._exit(0)``s — a signal-based alarm could not fire while the main
    thread is blocked inside a non-interruptible XLA/PJRT C call, which is
    exactly how a chip wedging mid-compile or mid-step manifests.  The
    except covers in-process errors.  Both degrade to a structured record
    with an ``error`` field rather than rc=1/rc=124.
    """
    import traceback

    watchdog_s = float(os.environ.get("HVD_BENCH_WATCHDOG_S", "1800"))
    finished = threading.Event()

    def _watchdog():
        if not finished.wait(watchdog_s):
            _emit(_error_record(
                "tpu_hang",
                f"bench watchdog fired after {watchdog_s:.0f}s — the main "
                "thread is likely blocked inside a wedged device call"))
            os._exit(0)

    if watchdog_s > 0:
        threading.Thread(target=_watchdog, name="bench-watchdog",
                         daemon=True).start()
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — must still emit the record
        _emit(_error_record(
            "bench_failed",
            f"{type(e).__name__}: {e}\n"
            + traceback.format_exc()[-1200:]))
        sys.exit(0)
    finally:
        finished.set()


if __name__ == "__main__":
    _run_guarded()
