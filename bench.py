"""Headline benchmark: ResNet-50 synthetic training throughput (images/sec).

Mirror of the reference's synthetic benchmark
(`examples/tensorflow2/tensorflow2_synthetic_benchmark.py`: ResNet-50,
synthetic ImageNet-shaped batches, warmup then timed iterations, reports
images/sec).  Runs on whatever accelerator is attached (the driver gives one
TPU chip); falls back to CPU with a tiny config so the script always
produces its JSON line.

``vs_baseline``: the only absolute throughput the reference publishes is
`docs/benchmarks.rst:32-43` — 1656.82 images/sec on 16 Pascal GPUs
(ResNet-101 bs=64) = 103.55 images/sec/GPU.  BASELINE.md's per-chip metric
is measured against that per-device figure.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

REFERENCE_PER_DEVICE_IMG_PER_SEC = 1656.82 / 16  # docs/benchmarks.rst:32-43


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import ResNet50
    from horovod_tpu.models.training import (
        create_train_state,
        make_sharded_train_step,
    )
    from horovod_tpu.parallel import MeshSpec, build_mesh, shard_batch

    on_tpu = jax.devices()[0].platform == "tpu"
    batch_size = 128 if on_tpu else 8
    image_size = 224 if on_tpu else 64
    warmup, iters = 5, 30 if on_tpu else 5

    mesh = build_mesh(MeshSpec(data=-1))
    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    tx = optax.sgd(0.01, momentum=0.9)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(batch_size,)), jnp.int32)
    batch = shard_batch(mesh, {"x": x, "y": y})

    state = create_train_state(model, jax.random.PRNGKey(0), x, tx,
                               mesh=mesh, init_kwargs={"train": True})
    step = make_sharded_train_step(model, tx, mesh, has_batch_stats=True,
                                   donate=True)

    # Sync points use device_get of the step's loss, not block_until_ready:
    # the attached TPU backend can report buffers ready before remote
    # execution finishes, but a host transfer of the final loss cannot
    # complete early — it transitively waits on every chained step.
    for _ in range(warmup):
        state, loss = step(state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, batch)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    img_per_sec = batch_size * iters / dt
    n_dev = len(jax.devices())
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(img_per_sec / n_dev, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / n_dev /
                             REFERENCE_PER_DEVICE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
