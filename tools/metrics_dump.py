#!/usr/bin/env python3
"""Checkout-friendly shim: ``tools/metrics_dump.py`` runs
``horovod_tpu.tools.metrics_dump`` without installing the package."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.tools.metrics_dump import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
