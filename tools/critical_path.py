#!/usr/bin/env python3
"""Checkout-friendly shim: ``tools/critical_path.py <traces...>`` runs
``horovod_tpu.tools.critical_path`` without installing the package."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.tools.critical_path import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
