#!/usr/bin/env python3
"""Checkout-friendly shim: ``tools/control_path.py <traces...>`` runs
``horovod_tpu.tools.control_path`` without installing the package."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.tools.control_path import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
