"""`import horovod_tpu.mxnet as hvd` — reference-parity alias for the
MXNet binding (reference exposes `horovod.mxnet`)."""

from .frameworks.mxnet import *  # noqa: F401,F403
from .frameworks.mxnet import __all__  # noqa: F401
