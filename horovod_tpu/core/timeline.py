"""Chrome-tracing timeline — per-tensor lanes of negotiation + execution.

Role of the reference's ``horovod/common/timeline.cc:1-509`` /
``timeline.h:106-126``: a catapult-format JSON trace where each tensor gets
its own lane (tid), showing ``NEGOTIATE_*`` (how long ranks waited on each
other, with per-rank ready ticks) followed by the operation with nested
activities.  The reference feeds records through a boost lockfree spsc queue
drained by a writer thread so the background loop never blocks on disk; we
use a ``SimpleQueue`` + writer thread for the same property.

View the output in ``chrome://tracing`` / Perfetto.  Runtime toggles via
``hvd.start_timeline()/stop_timeline()`` (reference ``operations.cc:780-806``)
or the ``HOROVOD_TIMELINE`` env knob.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict, List, Optional

_WRITER_SENTINEL = None


class Timeline:
    def __init__(self, path: str, mark_cycles: bool = False):
        self._path = path
        self._mark_cycles = mark_cycles
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._tids: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._start = time.monotonic_ns()
        self._closed = False
        self._file = open(path, "w", buffering=1024 * 1024)
        self._file.write("[\n")
        self._first = True
        self._writer = threading.Thread(
            target=self._writer_loop, name="horovod-timeline", daemon=True)
        self._writer.start()
        self._emit({"name": "process_name", "ph": "M", "pid": 0,
                    "args": {"name": "horovod_tpu background loop"}})

    # -- producers (background/controller thread; never block) -------------

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start) / 1e3

    def _tid(self, tensor_name: str) -> int:
        with self._lock:
            tid = self._tids.get(tensor_name)
            if tid is None:
                tid = len(self._tids) + 1
                self._tids[tensor_name] = tid
                self._emit({"name": "thread_name", "ph": "M", "pid": 0,
                            "tid": tid, "args": {"name": tensor_name}})
        return tid

    def _emit(self, record: dict) -> None:
        if not self._closed:
            self._queue.put(record)

    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        self._emit({"name": f"NEGOTIATE_{op_name}", "ph": "B", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        """Per-rank readiness tick inside the negotiation phase
        (reference ``NegotiateRankReady``, ``timeline.h:113``)."""
        self._emit({"name": str(rank), "ph": "i", "s": "t", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def negotiate_end(self, tensor_name: str) -> None:
        self._emit({"name": "", "ph": "E", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def op_start(self, response, entries) -> None:
        name = response.response_type.name
        ts = self._ts_us()
        for e in entries:
            self._emit({"name": name, "ph": "B", "pid": 0,
                        "tid": self._tid(e.tensor_name), "ts": ts})

    def op_end(self, response, entries) -> None:
        ts = self._ts_us()
        for e in entries:
            self._emit({"name": "", "ph": "E", "pid": 0,
                        "tid": self._tid(e.tensor_name), "ts": ts})

    def activity(self, tensor_name: str, activity: str, begin: bool) -> None:
        """Nested activity markers (MEMCPY_IN_FUSION_BUFFER, ... —
        reference macro list ``common.h:31-62``)."""
        rec = {"name": activity if begin else "", "ph": "B" if begin else "E",
               "pid": 0, "tid": self._tid(tensor_name), "ts": self._ts_us()}
        self._emit(rec)

    def mark_cycle(self) -> None:
        if self._mark_cycles:
            self._emit({"name": "CYCLE", "ph": "i", "s": "g", "pid": 0,
                        "tid": 0, "ts": self._ts_us()})

    # -- writer thread ------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            rec = self._queue.get()
            if rec is _WRITER_SENTINEL:
                break
            try:
                if not self._first:
                    self._file.write(",\n")
                self._first = False
                self._file.write(json.dumps(rec))
            except ValueError:  # file closed under us
                break

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(_WRITER_SENTINEL)
        self._writer.join(timeout=10)
        if self._writer.is_alive():
            # Writer still draining a deep backlog: do not write the epilogue
            # or close the file under it — a truncated-but-valid-prefix trace
            # beats an interleaved corrupt one.
            return
        self._file.write("\n]\n")
        self._file.close()


# ---------------------------------------------------------------------------
# per-phase dispatch-chain accounting
# ---------------------------------------------------------------------------


class PhaseStats:
    """Always-on wall-time accumulator over the eager dispatch chain's
    phases: ``negotiate`` (controller round, busy cycles only), ``fuse``
    (staging the fused buffer onto the mesh), ``collective`` (host cost of
    dispatching the device collective), ``unfuse`` (slicing results back to
    per-entry outputs), ``wait`` (framework-thread handle synchronization).

    This is the aggregate companion to the Chrome-trace timeline: the trace
    answers "what happened when", this answers "where does a dispatch's
    millisecond budget go" cheaply enough to leave enabled (a few monotonic
    reads + one dict update per phase per response).  Surfaced by
    ``benchmarks/eager_bench.py --profile`` / ``eager_np_bench.py
    --profile`` and snapshot-able from tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[str, List[float]] = {}

    def add(self, phase: str, seconds: float) -> None:
        with self._lock:
            slot = self._acc.get(phase)
            if slot is None:
                self._acc[phase] = [seconds, 1]
            else:
                slot[0] += seconds
                slot[1] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                phase: {
                    "total_ms": round(total * 1e3, 3),
                    "count": int(count),
                    "mean_ms": round(total / count * 1e3, 4),
                }
                for phase, (total, count) in self._acc.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()


#: Process-global instance — the background loop, the XLA backend, and the
#: framework-side handle waits all record into this.
phase_stats = PhaseStats()


class CounterStats:
    """Monotonic event counters for the host data plane.

    The companion to :class:`PhaseStats` for quantities that are counts,
    not durations:

    - ``bytes_on_wire``: DATA payload bytes the TCP transport actually
      framed (sender side) or delivered (receiver side).  Each data frame
      is counted once per endpoint, so a process's number is its own
      traffic; control frames (coordinated abort) are excluded on both
      sides — they are teardown traffic, and counting them on only one
      side would break sender/receiver symmetry.
    - ``heap_copies``: payload materializations in the host data plane
      (``backend/cpu_ring.py`` / ``backend/adasum.py``) — every site that
      still copies tensor bytes onto the heap (fuse staging, unfuse
      ``copy=True``, output assembly) increments it.  The zero-copy
      invariant the test suite asserts: a steady-state ring *step*
      contributes **zero** (reduction reads staged segments in place;
      nothing is ever ``tobytes()``'d or ``frombuffer``-copied).

    Cheap enough to leave always-on (one dict update under a lock per
    event; the transport batches per frame, not per syscall)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def add(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


#: Process-global data-plane counters (bytes_on_wire, heap_copies);
#: surfaced by the benches' ``--profile`` output next to ``phase_stats``.
wire_stats = CounterStats()
