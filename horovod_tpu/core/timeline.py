"""Chrome-tracing timeline — per-tensor lanes of negotiation + execution.

Role of the reference's ``horovod/common/timeline.cc:1-509`` /
``timeline.h:106-126``: a catapult-format JSON trace where each tensor gets
its own lane (tid), showing ``NEGOTIATE_*`` (how long ranks waited on each
other, with per-rank ready ticks) followed by the operation with nested
activities.  The reference feeds records through a boost lockfree spsc queue
drained by a writer thread so the background loop never blocks on disk; we
use a ``SimpleQueue`` + writer thread for the same property.

Cross-rank story (the Dapper-shaped half, docs/observability.md): every
rank writes its own trace with ``pid = rank`` (rank 0 at the configured
``HOROVOD_TIMELINE`` path, rank r at ``<path>.rank<r>``), every span is
tagged with its negotiation **cycle id** (the lockstep round counter,
identical on every rank), and a ``clock_sync`` metadata record carries the
wall-clock base plus an offset-to-the-rendezvous-server estimate
(:func:`estimate_server_clock_offset_ns`, Cristian-style against the
server's ``GET /clock``).  ``tools/trace_merge.py`` uses those to align
the per-rank files into ONE Chrome/Perfetto view where every rank's lanes
for the same collective line up.

View the output in ``chrome://tracing`` / Perfetto.  Runtime toggles via
``hvd.start_timeline()/stop_timeline()`` (reference ``operations.cc:780-806``)
or the ``HOROVOD_TIMELINE`` env knob.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict, List, Optional

from ..common import env as env_mod
from . import metrics

_WRITER_SENTINEL = None

#: Name of the per-trace metadata record trace_merge aligns clocks on.
CLOCK_SYNC_EVENT = "clock_sync"

#: Per-tensor lifecycle spans (submitted → negotiated → fused → wire →
#: reduced → callback) on every rank, consumed by
#: ``tools/critical_path.py``.  Toggle-gated so the instrumented hot
#: paths stay at one module-attribute read when off or when no timeline
#: is active.
LIFECYCLE_ENABLED = env_mod.get_bool(env_mod.HOROVOD_TIMELINE_LIFECYCLE, True)

#: Control-plane spans (``RV_*`` on the server trace, ``RVC_*`` client
#: round-trips, ``DRV_*``/``CHURN_EVENT`` on the driver trace), consumed
#: by ``tools/control_path.py``.  Same gating discipline as
#: ``LIFECYCLE_ENABLED``.
CONTROL_PLANE_ENABLED = env_mod.get_bool(
    env_mod.HOROVOD_TIMELINE_CONTROL_PLANE, True)

#: Reserved trace pids for the control-plane processes.  Workers own the
#: non-negative pids (pid = rank); the rendezvous server and the elastic
#: driver get sentinel lanes so a merged trace keeps them distinct from
#: every possible rank.
SERVER_TRACE_PID = -1
DRIVER_TRACE_PID = -2

#: The process's live Timeline, set by the constructor and cleared by
#: ``close()``: instrumentation sites that can't reach the global state
#: object (tensor queue, ring backend) emit lifecycle records through the
#: module-level helpers below instead of threading the instance through
#: every call chain.
ACTIVE: Optional["Timeline"] = None


def lifecycle_begin(tensor_name: str, stage: str,
                    cycle: Optional[int] = None) -> None:
    tl = ACTIVE
    if tl is not None and LIFECYCLE_ENABLED:
        tl.lifecycle(tensor_name, stage, begin=True, cycle=cycle)


def lifecycle_end(tensor_name: str, stage: str) -> None:
    tl = ACTIVE
    if tl is not None and LIFECYCLE_ENABLED:
        tl.lifecycle(tensor_name, stage, begin=False)


def lifecycle_instant(tensor_name: str, stage: str,
                      cycle: Optional[int] = None) -> None:
    tl = ACTIVE
    if tl is not None and LIFECYCLE_ENABLED:
        tl.lifecycle_mark(tensor_name, stage, cycle=cycle)


def control_active() -> bool:
    """True when a control-plane span emitted now would land somewhere.
    Instrumentation sites sample ``time.monotonic_ns()`` only when this
    holds, so the off path stays at two module-attribute reads."""
    return ACTIVE is not None and CONTROL_PLANE_ENABLED


def control_span_since(lane: str, name: str, t0_mono_ns: int,
                       **args) -> None:
    """Retroactive control-plane span on the active timeline: covers
    ``[t0_mono_ns, now]`` (caller sampled ``time.monotonic_ns()`` before
    the work).  No-op when no timeline is active or the knob is off."""
    tl = ACTIVE
    if tl is not None and CONTROL_PLANE_ENABLED:
        tl.span_since(lane, name, t0_mono_ns, args or None)


def control_instant(lane: str, name: str, **args) -> None:
    tl = ACTIVE
    if tl is not None and CONTROL_PLANE_ENABLED:
        tl.instant(lane, name, args or None)


def rank_trace_path(path: str, rank: int) -> str:
    """Per-rank trace file layout: rank 0 owns the configured path
    (back-compat with single-file consumers), rank r writes
    ``<path>.rank<r>``."""
    return path if rank == 0 else f"{path}.rank{rank}"


def estimate_server_clock_offset_ns(samples: int = 3) -> Optional[int]:
    """Estimate this host's wall-clock offset to the rendezvous server
    (``local_wall - server_wall``, ns) via the server's ``GET /clock``:
    Cristian's algorithm, keeping the minimum-RTT sample.  Every rank
    measures against the SAME server clock, so cross-rank skew is the
    difference of these estimates.  Returns None when no rendezvous is
    configured or unreachable — trace_merge then assumes synced clocks."""
    import urllib.request

    addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        return None
    best = None  # (rtt_ns, offset_ns)
    try:
        for _ in range(samples):
            t0 = time.time_ns()
            with urllib.request.urlopen(
                    f"http://{addr}:{port}/clock", timeout=2.0) as resp:
                server_ns = int(resp.read())
            t1 = time.time_ns()
            cand = (t1 - t0, (t0 + t1) // 2 - server_ns)
            if best is None or cand[0] < best[0]:
                best = cand
    except (OSError, ValueError):
        return None if best is None else best[1]
    return best[1]


class Timeline:
    def __init__(self, path: str, mark_cycles: bool = False, rank: int = 0,
                 clock_offset_ns: Optional[int] = None,
                 activate: bool = True,
                 process_name: Optional[str] = None):
        self._path = path
        self._mark_cycles = mark_cycles
        self._pid = rank
        self._cycle = 0
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._tids: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._start = time.monotonic_ns()
        # Sampled back-to-back with _start: ts=0 on this trace's axis is
        # this wall-clock instant (trace_merge's alignment anchor).
        self._wall_base_ns = time.time_ns()
        self._closed = False
        self._file = open(path, "w", buffering=1024 * 1024)
        self._file.write("[\n")
        self._first = True
        self._writer = threading.Thread(
            target=self._writer_loop, name="horovod-timeline", daemon=True)
        self._writer.start()
        self._emit({"name": "process_name", "ph": "M", "pid": self._pid,
                    "args": {"name": process_name
                             or f"horovod_tpu rank {rank}"}})
        self._emit({"name": CLOCK_SYNC_EVENT, "ph": "M", "pid": self._pid,
                    "args": {"wall_base_ns": self._wall_base_ns,
                             "server_offset_ns": clock_offset_ns,
                             "rank": rank}})
        # Secondary timelines (the rendezvous server's trace lives inside
        # the launcher process next to the workers') opt out of owning the
        # module-level ACTIVE slot.
        if activate:
            global ACTIVE
            ACTIVE = self

    # -- producers (background/controller thread; never block) -------------

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start) / 1e3

    def set_cycle(self, cycle: int) -> None:
        """Current negotiation cycle id — the background loop advances it
        each round.  Rounds are lockstep across ranks (the TCP recv pairs
        them), so the same id names the same global round everywhere;
        spans tagged with it line up across merged per-rank traces."""
        self._cycle = cycle

    def _tid(self, tensor_name: str) -> int:
        with self._lock:
            tid = self._tids.get(tensor_name)
            if tid is None:
                tid = len(self._tids) + 1
                self._tids[tensor_name] = tid
                self._emit({"name": "thread_name", "ph": "M",
                            "pid": self._pid, "tid": tid,
                            "args": {"name": tensor_name}})
        return tid

    def _emit(self, record: dict) -> None:
        if not self._closed:
            self._queue.put(record)

    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        self._emit({"name": f"NEGOTIATE_{op_name}", "ph": "B",
                    "pid": self._pid, "tid": self._tid(tensor_name),
                    "ts": self._ts_us(), "args": {"cycle": self._cycle}})

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        """Per-rank readiness tick inside the negotiation phase
        (reference ``NegotiateRankReady``, ``timeline.h:113``)."""
        self._emit({"name": str(rank), "ph": "i", "s": "t", "pid": self._pid,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def negotiate_end(self, tensor_name: str) -> None:
        self._emit({"name": "", "ph": "E", "pid": self._pid,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def op_start(self, response, entries) -> None:
        name = response.response_type.name
        ts = self._ts_us()
        # Pipelined device dispatches run while the NEXT cycle negotiates;
        # the response carries the cycle it was negotiated in so the tag
        # stays right regardless of which thread executes it.
        cycle = getattr(response, "_cycle", self._cycle)
        for e in entries:
            self._emit({"name": name, "ph": "B", "pid": self._pid,
                        "tid": self._tid(e.tensor_name), "ts": ts,
                        "args": {"cycle": cycle}})

    def op_end(self, response, entries) -> None:
        ts = self._ts_us()
        for e in entries:
            self._emit({"name": "", "ph": "E", "pid": self._pid,
                        "tid": self._tid(e.tensor_name), "ts": ts})

    def activity(self, tensor_name: str, activity: str, begin: bool) -> None:
        """Nested activity markers (MEMCPY_IN_FUSION_BUFFER, ... —
        reference macro list ``common.h:31-62``)."""
        rec = {"name": activity if begin else "", "ph": "B" if begin else "E",
               "pid": self._pid, "tid": self._tid(tensor_name),
               "ts": self._ts_us()}
        self._emit(rec)

    def lifecycle(self, tensor_name: str, stage: str, begin: bool,
                  cycle: Optional[int] = None) -> None:
        """Cycle-tagged lifecycle span on the tensor's lane (``LC_*`` —
        submitted/fuse/wire/reduce/callback; docs/observability.md lists
        the schema).  Unlike :meth:`activity`, B records carry
        ``args.cycle`` so ``tools/critical_path.py`` can group a tensor's
        spans into per-step chains across ranks."""
        rec = {"name": stage if begin else "", "ph": "B" if begin else "E",
               "pid": self._pid, "tid": self._tid(tensor_name),
               "ts": self._ts_us()}
        if begin:
            rec["args"] = {"cycle": self._cycle if cycle is None else cycle}
        self._emit(rec)

    def lifecycle_mark(self, tensor_name: str, stage: str,
                       cycle: Optional[int] = None) -> None:
        """Instant lifecycle marker (e.g. ``LC_NEGOTIATED`` with the cycle
        the response was agreed in)."""
        self._emit({"name": stage, "ph": "i", "s": "t", "pid": self._pid,
                    "tid": self._tid(tensor_name), "ts": self._ts_us(),
                    "args": {"cycle": self._cycle if cycle is None
                             else cycle}})

    def span_since(self, lane: str, name: str, t0_mono_ns: int,
                   args: Optional[dict] = None) -> None:
        """Complete ("X") control-plane span on a named lane, covering
        ``[t0_mono_ns, now]``.  Complete events are atomic — concurrent
        handler threads can land overlapping spans on one lane without
        the B/E mis-nesting a shared stack would suffer."""
        b_us = (t0_mono_ns - self._start) / 1e3
        rec = {"name": name, "ph": "X", "pid": self._pid,
               "tid": self._tid(lane), "ts": b_us,
               "dur": self._ts_us() - b_us}
        if args:
            rec["args"] = dict(args)
        self._emit(rec)

    def instant(self, lane: str, name: str,
                args: Optional[dict] = None) -> None:
        """Instant marker on a named lane (control-plane events like
        ``EPOCH_TRANSITION``)."""
        rec = {"name": name, "ph": "i", "s": "t", "pid": self._pid,
               "tid": self._tid(lane), "ts": self._ts_us()}
        if args:
            rec["args"] = dict(args)
        self._emit(rec)

    def mark_cycle(self) -> None:
        if self._mark_cycles:
            self._emit({"name": "CYCLE", "ph": "i", "s": "g",
                        "pid": self._pid, "tid": 0, "ts": self._ts_us(),
                        "args": {"cycle": self._cycle}})

    # -- writer thread ------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            rec = self._queue.get()
            if rec is _WRITER_SENTINEL:
                break
            try:
                if not self._first:
                    self._file.write(",\n")
                self._first = False
                self._file.write(json.dumps(rec))
            except ValueError:  # file closed under us
                break

    def close(self) -> None:
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = None
        if self._closed:
            return
        self._closed = True
        self._queue.put(_WRITER_SENTINEL)
        self._writer.join(timeout=10)
        if self._writer.is_alive():
            # Writer still draining a deep backlog: do not write the epilogue
            # or close the file under it — a truncated-but-valid-prefix trace
            # beats an interleaved corrupt one.
            return
        self._file.write("\n]\n")
        self._file.close()


# ---------------------------------------------------------------------------
# per-phase dispatch-chain accounting
# ---------------------------------------------------------------------------


class PhaseStats:
    """Always-on wall-time accumulator over the eager dispatch chain's
    phases: ``negotiate`` (controller round, busy cycles only), ``fuse``
    (staging the fused buffer onto the mesh), ``collective`` (host cost of
    dispatching the device collective), ``unfuse`` (slicing results back to
    per-entry outputs), ``wait`` (framework-thread handle synchronization).

    This is the aggregate companion to the Chrome-trace timeline: the trace
    answers "what happened when", this answers "where does a dispatch's
    millisecond budget go" cheaply enough to leave enabled (a few monotonic
    reads + one dict update per phase per response).  Surfaced by
    ``benchmarks/eager_bench.py --profile`` / ``eager_np_bench.py
    --profile``, snapshot-able from tests, and registered as a view in the
    metrics registry (``phase_seconds_total``/``phase_ops_total``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[str, List[float]] = {}

    def add(self, phase: str, seconds: float) -> None:
        with self._lock:
            slot = self._acc.get(phase)
            if slot is None:
                self._acc[phase] = [seconds, 1]
            else:
                slot[0] += seconds
                slot[1] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                phase: {
                    "total_ms": round(total * 1e3, 3),
                    "count": int(count),
                    "mean_ms": round(total / count * 1e3, 4),
                }
                for phase, (total, count) in self._acc.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()


#: Process-global instance — the background loop, the XLA backend, and the
#: framework-side handle waits all record into this.
phase_stats = PhaseStats()


class CounterStats:
    """Monotonic event counters for the host data plane.

    The companion to :class:`PhaseStats` for quantities that are counts,
    not durations:

    - ``bytes_on_wire``: DATA payload bytes the TCP transport actually
      framed (sender side) or delivered (receiver side).  Each data frame
      is counted once per endpoint, so a process's number is its own
      traffic; control frames (coordinated abort) are excluded on both
      sides — they are teardown traffic, and counting them on only one
      side would break sender/receiver symmetry.
    - ``heap_copies``: payload materializations in the host data plane
      (``backend/cpu_ring.py`` / ``backend/adasum.py``) — every site that
      still copies tensor bytes onto the heap (fuse staging, unfuse
      ``copy=True``, output assembly) increments it.  The zero-copy
      invariant the test suite asserts: a steady-state ring *step*
      contributes **zero** (reduction reads staged segments in place;
      nothing is ever ``tobytes()``'d or ``frombuffer``-copied).

    Cheap enough to leave always-on (one dict update under a lock per
    event; the transport batches per frame, not per syscall).  Registered
    as a metrics-registry view (``wire_*_total``).

    ``seed`` names are present at 0 from construction (and after
    ``reset``): a counter family that scrapes/dashboards depend on must
    not vanish just because nothing incremented it — under
    ``HOROVOD_TRANSPORT=auto`` on one host, ALL data frames ride shm and
    ``bytes_on_wire`` legitimately never ticks."""

    def __init__(self, seed=()):
        self._lock = threading.Lock()
        self._seed = tuple(seed)
        self._counts: Dict[str, int] = {name: 0 for name in self._seed}

    def add(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts = {name: 0 for name in self._seed}


#: Process-global data-plane counters (bytes_on_wire, heap_copies);
#: surfaced by the benches' ``--profile`` output next to ``phase_stats``.
wire_stats = CounterStats(seed=("bytes_on_wire", "heap_copies"))


# -- registry views: fold the pre-existing accumulators into every
#    metrics snapshot (docs/observability.md) -------------------------------


def _phase_stats_view() -> dict:
    counters: Dict[str, float] = {}
    for phase, d in phase_stats.snapshot().items():
        counters[metrics.flat("phase_seconds_total", phase=phase)] = \
            d["total_ms"] / 1e3
        counters[metrics.flat("phase_ops_total", phase=phase)] = d["count"]
    return {"counters": counters}


def _wire_stats_view() -> dict:
    return {"counters": {
        f"wire_{name}_total": value
        for name, value in wire_stats.snapshot().items()}}


metrics.registry.register_view("phase_stats", _phase_stats_view)
metrics.registry.register_view("wire_stats", _wire_stats_view)
