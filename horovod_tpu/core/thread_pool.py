"""Fixed-size worker pool.

Role of the reference's ``common/thread_pool.h:45`` / ``thread_pool.cc:67``:
a generic closure-executing pool, used there for the per-stream GPU
finalizer threads (``operations.cc:421``).  Here it backs the XLA
finalizer (``HOROVOD_NUM_FINALIZER_THREADS`` is the
``HOROVOD_NUM_NCCL_STREAMS`` analog: more threads let multiple in-flight
fused batches complete concurrently instead of serializing behind one
``block_until_ready``).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional


class ThreadPool:
    def __init__(self, num_threads: int, name: str = "hvd-pool"):
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = \
            queue.Queue()
        self._threads: List[threading.Thread] = []
        self._shutdown = False
        for i in range(max(1, num_threads)):
            t = threading.Thread(target=self._loop, name=f"{name}-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _loop(self) -> None:
        while True:
            task = self._queue.get()
            if task is None:
                return
            task()  # tasks are pre-wrapped; they must not raise

    def execute(self, fn: Callable[[], None]) -> None:
        if self._shutdown:
            raise RuntimeError("ThreadPool is shut down")
        self._queue.put(fn)

    def shutdown(self, timeout: Optional[float] = None) -> None:
        """Drain: queued tasks run to completion, then workers exit."""
        if self._shutdown:
            return
        self._shutdown = True
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=timeout)
