"""Response cache — the steady-state negotiation fast path.

Reference: ``response_cache.h:104-167`` / ``response_cache.cc`` +
``CoordinateCacheAndState`` (``controller.cc:826-851``): after a tensor has
been negotiated once, later cycles replace its full Request message with a
single bit in a bitvector, synced by two bitwise allreduces; training
steady-state (same tensors every step) negotiates at bitvector cost.

Our control plane is a star (coordinator-authoritative), which permits a
simpler, race-free design with the same wire win:

- the **coordinator** owns the cache: it assigns a bit to each eligible
  single-tensor Response it constructs, broadcasting (bit, request
  template) assignments and evictions inside the ResponseList;
- **workers** mirror only {key → bit}; when a pending Request matches a
  mirrored key they send the bit instead of the Request;
- the coordinator rehydrates a bit hit into the stored template (with the
  hitting rank patched in), so tallying and validation are unchanged;
- eviction is LRU at the coordinator (HOROVOD_CACHE_CAPACITY, reference
  default 1024); evicted bits are tombstoned for a few cycles so hits
  already in flight still resolve.

Eligible ops: ALLREDUCE / ADASUM / BROADCAST — fixed per-rank metadata.
ALLGATHER/ALLTOALL have per-rank shapes/splits that must travel every
cycle, so caching them would not shrink the wire.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from .messages import Request, RequestType

CACHEABLE = (RequestType.ALLREDUCE, RequestType.ADASUM, RequestType.BROADCAST)
_TOMBSTONE_CYCLES = 4


def cache_key(req: Request) -> Tuple:
    return (req.tensor_name, int(req.request_type), int(req.tensor_type),
            tuple(req.tensor_shape), req.root_rank, req.device,
            req.prescale_factor, req.postscale_factor)


class CoordinatorCache:
    """Rank-0 side: bit assignment, LRU, tombstones."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, capacity)
        self._by_bit: "OrderedDict[int, Tuple[Tuple, Request]]" = OrderedDict()
        self._by_key: Dict[Tuple, int] = {}
        self._by_name: Dict[str, int] = {}
        self._tombstones: Dict[int, Tuple[Request, int]] = {}
        self._next_bit = 0
        # Recycled ids (evicted + tombstone expired): keeps the dense
        # bitmask wire format bounded by ~capacity bits instead of growing
        # with the total number of assignments ever made.  Safe because the
        # round trip is synchronous (a hit is consumed the same cycle it is
        # sent) and the controller converts lingering pending bits to table
        # tallies when their entry is evicted.
        self._free_bits: List[int] = []

    def lookup(self, key: Tuple) -> Optional[int]:
        bit = self._by_key.get(key)
        if bit is not None:
            self._by_bit.move_to_end(bit)
        return bit

    def rehydrate(self, bit: int, rank: int) -> Optional[Request]:
        """Request template for a hit bit (tombstoned bits still resolve)."""
        entry = self._by_bit.get(bit)
        if entry is not None:
            self._by_bit.move_to_end(bit)
            return replace(entry[1], request_rank=rank)
        tomb = self._tombstones.get(bit)
        if tomb is not None:
            return replace(tomb[0], request_rank=rank)
        return None

    def maybe_insert(self, req: Request) -> Tuple[Optional[int], List[int]]:
        """Cache an eligible request; returns (new_bit|None, evicted_bits).

        A same-name entry with a different key (tensor changed shape/dtype)
        is evicted first, like the reference invalidating stale entries."""
        if req.request_type not in CACHEABLE:
            return None, []
        evicted: List[int] = []
        key = cache_key(req)
        stale = self._by_name.get(req.tensor_name)
        if stale is not None and self._by_bit.get(stale, (key,))[0] != key:
            self._evict(stale)
            evicted.append(stale)
        if key in self._by_key:
            return None, evicted
        while len(self._by_bit) >= self.capacity:
            old_bit = next(iter(self._by_bit))
            self._evict(old_bit)
            evicted.append(old_bit)
        if self._free_bits:
            bit = self._free_bits.pop()
        else:
            bit = self._next_bit
            self._next_bit += 1
        template = replace(req, request_rank=0)
        self._by_bit[bit] = (key, template)
        self._by_key[key] = bit
        self._by_name[req.tensor_name] = bit
        return bit, evicted

    def invalidate_name(self, name: str) -> Optional[int]:
        """Evict a tensor's entry by name; returns the freed bit.

        Reference ``InvalidateStalledCachedTensors``: a stalled tensor's
        cached negotiation must not survive the stall — after recovery the
        tensor renegotiates from scratch."""
        bit = self._by_name.get(name)
        if bit is None:
            return None
        self._evict(bit)
        return bit

    def _evict(self, bit: int) -> None:
        entry = self._by_bit.pop(bit, None)
        if entry is None:
            return
        key, template = entry
        self._by_key.pop(key, None)
        if self._by_name.get(template.tensor_name) == bit:
            self._by_name.pop(template.tensor_name, None)
        self._tombstones[bit] = (template, _TOMBSTONE_CYCLES)

    def tick(self) -> None:
        """Age tombstones one cycle; expired ids return to the free pool."""
        dead = []
        for bit, (tpl, left) in self._tombstones.items():
            if left <= 1:
                dead.append(bit)
            else:
                self._tombstones[bit] = (tpl, left - 1)
        for bit in dead:
            self._tombstones.pop(bit, None)
            self._free_bits.append(bit)

    def __len__(self) -> int:
        return len(self._by_bit)


class WorkerCacheMirror:
    """Worker side: {key → bit} plus the full request template per bit,
    learned from ResponseList assignments.

    The template is what makes the zero-payload fast path possible: on a
    fully-cached cycle the coordinator answers with the agreed bitvector
    only, and each worker reconstructs the Responses locally from these
    templates (``controller._responses_from_agreed_mask``) instead of
    deserializing a broadcast ResponseList."""

    def __init__(self):
        self._by_key: Dict[Tuple, int] = {}
        self._by_bit: Dict[int, Tuple[Tuple, Request]] = {}

    def hit(self, req: Request) -> Optional[int]:
        return self._by_key.get(cache_key(req))

    def template(self, bit: int) -> Optional[Request]:
        """Request template for a live bit (None if unknown/evicted)."""
        entry = self._by_bit.get(bit)
        return entry[1] if entry is not None else None

    def apply(self, assignments: List[Tuple[int, Request]],
              evicted_bits: List[int]) -> None:
        # Assignments first: within one batch an eviction is always the
        # *later* event for its bit (a capacity eviction can hit a bit
        # assigned earlier in the same cycle).  Bit ids RECYCLE after their
        # tombstone expires, so an assignment overwriting a known bit must
        # also drop the stale key that previously mapped to it.
        for bit, template in assignments:
            key = cache_key(template)
            stale = self._by_bit.get(bit)
            if stale is not None and stale[0] != key:
                self._by_key.pop(stale[0], None)
            self._by_key[key] = bit
            self._by_bit[bit] = (key, template)
        for bit in evicted_bits:
            entry = self._by_bit.pop(bit, None)
            if entry is not None:
                self._by_key.pop(entry[0], None)

    def __len__(self) -> int:
        return len(self._by_key)
