"""Process-global metrics registry — the cluster observability plane's core.

Role of the reference's timeline/stall-inspector telemetry plus what it
never had: a scrapeable, cross-rank metrics surface.  Three instrument
kinds, Prometheus-shaped:

- **Counter**: monotonically increasing float (``inc``); merged across
  ranks by summation at scrape time.
- **Gauge**: last-written value (``set_gauge``); labeled by rank at
  scrape time (a queue depth summed across ranks would be a lie).
- **Histogram**: fixed log2 buckets (powers of two from ~1 µs to 64 s,
  ``observe``); per-bucket counts merge across ranks by summation, so a
  cluster-wide latency distribution is exact, not approximated.

Labels ride as keyword arguments (``observe("collective_latency_seconds",
dt, op="ALLREDUCE", dtype="FLOAT32", size="2^22")``) and are flattened
into the Prometheus ``name{k="v"}`` form for storage and merging.

``phase_stats`` and ``wire_stats`` (core/timeline.py) predate this
registry and stay the hot-path accumulators; they are absorbed as
**registered views** — callables folded into every :func:`snapshot`, so
one scrape carries the whole process's story.  The controller's
fast-cycle counters join the same way (core/state.py registers the view).

Every metric name must be declared in :data:`CATALOG` — lint rule HVD007
(mirror of HVD003's fault-site registry) rejects an ``inc``/``observe``/
``set_gauge``/stats-``add`` call whose literal name is not cataloged, and
requires every catalog entry to appear in ``docs/observability.md``.  A
typo'd metric name must not silently record nothing.

Always-on by default like ``wire_stats`` (one small lock + dict update
per event); ``HOROVOD_METRICS=0`` turns every recording call into one
attribute read (the ``faults.ACTIVE`` pattern), and
``benchmarks/allreduce_bench.py --metrics-sweep`` is the overhead guard.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..common import env as env_mod

#: Rendezvous-KV scope the workers push snapshots into (``PUT
#: /metrics/rank-N``) and the server's ``GET /metrics`` aggregates from.
#: Re-exported from the scope registry (transport/scopes.py, HVD010) at
#: the BOTTOM of this module: importing the transport package pulls in
#: core/timeline, which needs ``metrics.registry`` to exist already.

#: Prefix stamped onto every rendered Prometheus series.
PROM_PREFIX = "hvd_"

#: Fixed log2 histogram bucket upper bounds: 2^-20 s (~1 µs) .. 2^6 s
#: (64 s), plus an implicit +Inf overflow bucket.  Fixed (not
#: configurable) so per-rank bucket arrays always merge element-wise.
BUCKET_BOUNDS: Tuple[float, ...] = tuple(2.0 ** k for k in range(-20, 7))

#: The metric catalog: every observable name, its kind, and its meaning.
#: ``counter``/``gauge``/``histogram`` entries are recorded through this
#: module; ``stat`` entries are the raw names fed to the pre-existing
#: ``phase_stats``/``wire_stats`` accumulators (their registered views
#: surface them here as the ``phase_*``/``wire_*`` counters).  This dict
#: is the HVD007-enforced registry: call sites may only use names listed
#: here, and every name here must appear in ``docs/observability.md``.
CATALOG: Dict[str, Tuple[str, str]] = {
    # -- controller / negotiation plane --
    "controller_cycles_total": (
        "counter", "negotiation cycles completed (busy and idle)"),
    "controller_fast_cycles_total": (
        "counter", "zero-payload mask-only cycles that completed work"),
    "controller_idle_fast_cycles_total": (
        "counter", "zero-payload mask-only cycles with nothing to do"),
    "controller_serialized_requests_total": (
        "counter", "Requests this rank put on / took off the wire"),
    "controller_fast_cycle_ratio": (
        "gauge", "(fast + idle-fast cycles) / all cycles since init"),
    "controller_cycle_seconds": (
        "histogram", "busy negotiation-round duration (idle parks excluded)"),
    "negotiation_fanin_frames_total": (
        "counter", "readiness frames this rank pushed toward the "
                   "coordinator, labeled path=tree (via/as the host "
                   "aggregator) or path=direct (straight to rank 0)"),
    "negotiation_fanin_fallbacks_total": (
        "counter", "stale-aggregator convictions on this rank — each one "
                   "is a coordinated abort + reshard that degrades the "
                   "host to the direct path for the veto cooldown"),
    "controller_ingress_frames_total": (
        "counter", "negotiation frames rank 0 received per-sender (tree "
                   "bundles count once; O(hosts) under fan-in vs "
                   "O(ranks) star — nonzero on the coordinator only)"),
    "controller_ingress_bytes_total": (
        "counter", "payload bytes behind controller_ingress_frames_total "
                   "(nonzero on the coordinator only)"),
    "tensor_queue_depth": (
        "gauge", "tensors in flight (submitted, not yet completed)"),
    # -- collectives --
    "collective_latency_seconds": (
        "histogram", "host-side dispatch latency per negotiated response, "
                     "labeled op/dtype/size (device-async ops record the "
                     "host dispatch cost; device completion is the "
                     "finalizer's)"),
    # -- stall inspector --
    "stalled_tensors": (
        "gauge", "tensors currently past the stall-warning age "
                 "(coordinator only; updated each stall check)"),
    "stall_shutdowns_total": (
        "counter", "hard stall-shutdown aborts fired (coordinator only)"),
    # -- straggler detector (coordinator-side; docs/observability.md) --
    "straggler_lag_seconds": (
        "histogram", "per-cycle readiness lag of a rank currently holding "
                     "tensors past the median announcer, labeled rank= "
                     "(coordinator only; lag-free cycles record nothing)"),
    "straggler_suspect": (
        "gauge", "rank id of the worst straggler suspect (readiness-lag "
                 "EWMA over HOROVOD_STRAGGLER_THRESHOLD_SECS), -1 when "
                 "no rank is flagged (coordinator only)"),
    "straggler_flags_total": (
        "counter", "straggler flag transitions — a rank's readiness-lag "
                   "EWMA crossing the threshold — labeled rank= "
                   "(coordinator only)"),
    "straggler_demotions_total": (
        "counter", "chronic-straggler demotions the elastic driver acted "
                   "on (host blacklisted + epoch advanced), labeled "
                   "rank=/host= (driver only; docs/elastic.md "
                   "self-healing demotion)"),
    "demotion_latency_seconds": (
        "histogram", "coordinator verdict posted -> driver blacklist "
                     "applied, wall-clock across processes (driver only; "
                     "the sim lane measures the full flag->first-step "
                     "curve on one clock)"),
    # -- rendezvous / elastic --
    "rendezvous_store_ops_total": (
        "counter", "HTTP KV store requests, labeled op=get|set|delete|keys"),
    "elastic_epoch": ("gauge", "membership epoch this process last adopted"),
    "elastic_epoch_changes_total": (
        "counter", "elastic re-rendezvous epoch adoptions"),
    "store_outage_seconds_total": (
        "counter", "seconds the rendezvous store was unreachable from "
                   "this process's push loop (accumulated across outages)"),
    "lease_renew_failures_total": (
        "counter", "liveness-lease renewals that failed to reach the "
                   "rendezvous store"),
    "lease_expirations_total": (
        "counter", "worker leases the elastic driver declared expired "
                   "(dead worker => epoch advance; driver only)"),
    # -- control plane: rendezvous server / journal / driver
    #    (docs/observability.md "Control-plane attribution") --
    "rendezvous_request_seconds": (
        "histogram", "server-side HTTP request handling latency, labeled "
                     "op=put|get|delete|keys|metrics|clock (rendezvous "
                     "server process only)"),
    "rendezvous_requests_in_flight": (
        "gauge", "HTTP requests the rendezvous server is handling right "
                 "now (threaded server; >1 means concurrent clients)"),
    "rendezvous_scope_ops_total": (
        "counter", "server-side KV operations per namespace, labeled "
                   "scope=/op= (which plane — lease, metrics, discovery, "
                   "rendezvous table — generates the request load)"),
    "rendezvous_store_lock_wait_seconds": (
        "histogram", "time a server handler thread waited to acquire the "
                     "store lock (contention term of request latency)"),
    # -- batched transactions (POST /batch) --
    "rendezvous_batch_ops_total": (
        "counter", "KV sub-operations carried inside batched /batch "
                   "transactions (client side; compare against "
                   "rendezvous_store_ops_total to see the coalescing win)"),
    "rendezvous_batch_fallbacks_total": (
        "counter", "batched requests degraded to per-op calls because the "
                   "server 404/501'd /batch (old protocol; sticky per "
                   "client)"),
    "rendezvous_batch_size": (
        "histogram", "sub-ops per /batch transaction, server side "
                     "(bucket bounds top out at 64 — larger batches land "
                     "in +Inf; use sum/count for the mean)"),
    # -- simulated cluster (horovod_tpu/sim/) --
    "sim_identities": (
        "gauge", "simulated worker identities currently renewing leases "
                 "(sim harness only)"),
    "sim_churn_events_total": (
        "counter", "churn events the simulated cluster injected, labeled "
                   "kind=lease_expiry|reset_request|worker_exit|demotion"),
    "sim_wire_delay_seconds_total": (
        "counter", "artificial shaped-wire delay the sim injected across "
                   "all links (latency + bandwidth + jitter terms)"),
    "journal_append_seconds": (
        "histogram", "durable-store journal append, frame write through "
                     "fsync (the per-mutation durability tax)"),
    "journal_fsync_seconds": (
        "histogram", "fsync portion of a journal append/compaction "
                     "(0-sample when HOROVOD_JOURNAL_FSYNC=0)"),
    "journal_replay_seconds": (
        "histogram", "journal recovery replay duration at store open"),
    "journal_truncated_tails_total": (
        "counter", "torn journal tails discarded during recovery (each is "
                   "one crash mid-append survived)"),
    "journal_compaction_seconds": (
        "histogram", "snapshot compaction duration (journal rewrite)"),
    "journal_generation": (
        "gauge", "current journal snapshot generation (bumps once per "
                 "compaction; pairs with journal_compaction_seconds)"),
    "leases_live": (
        "gauge", "worker liveness leases the elastic driver currently "
                 "tracks as live (driver only; updated each lease scan)"),
    "lease_min_ttl_seconds": (
        "gauge", "smallest time-to-expiry across live leases (driver "
                 "only; negative means a lease is inside its grace "
                 "window and about to be declared expired)"),
    "driver_tick_seconds": (
        "histogram", "elastic driver discovery-tick duration (lease scan "
                     "+ host discovery + any epoch transition it caused)"),
    "driver_epoch_transitions_total": (
        "counter", "elastic driver epoch advances, labeled cause="
                   "lease_expiry|demotion|reset_request|worker_exit|"
                   "host_change|reshard (driver only; the flight recorder "
                   "carries the same cause tag per event; a zero-restart "
                   "reshard counts BOTH its churn cause and one extra "
                   "cause=reshard sample when the commit lands)"),
    "reshard_seconds": (
        "histogram", "zero-restart reshard duration, driver side: "
                     "reshard-marked slot-table publish through the "
                     "survivor-acked topology commit (driver only; no "
                     "sample when the epoch falls back to the legacy "
                     "full-teardown path)"),
    "reshard_fallbacks_total": (
        "counter", "reshard attempts abandoned to the legacy full-"
                   "teardown path (a survivor crashed or stopped acking "
                   "mid-reshard, so the next epoch published without the "
                   "marker)"),
    # -- integrity / failure plane --
    "crc_verify_seconds_total": (
        "counter", "seconds spent computing/verifying wire CRC32 "
                   "(ROADMAP item 2's direct measurement)"),
    "crc_shadow_seconds_total": (
        "counter", "seconds spent in deferred (shadow) wire digests — "
                   "runs off the serial path, so this measures overlap "
                   "cost, not added step latency"),
    "wire_compress_seconds_total": (
        "counter", "seconds spent casting payloads to/from the wire "
                   "dtype (compress, widen-reduce, restore, quantize)"),
    "wire_codec_bytes_total": (
        "counter", "compressed payload bytes produced per wire codec, "
                   "labeled codec=fp16|bf16|int8|onebit|topk<K> — the "
                   "per-codec split of wire_compressed_bytes_total"),
    "wire_ef_residual_bytes": (
        "gauge", "bytes held in error-feedback residual accumulators "
                 "(lossy wire codecs; grows once per distinct "
                 "tensor-set/segment shape, then stays flat)"),
    "wire_ef_flush_seconds_total": (
        "counter", "seconds spent folding error-feedback residuals into "
                   "segments and computing the new residual after each "
                   "lossy encode"),
    "aborts_total": (
        "counter", "coordinated aborts, labeled dir=sent|received"),
    # -- transport selection (transport/select.py, transport/shm.py) --
    "shm_bytes_total": (
        "counter", "data payload bytes framed/delivered by the shared-"
                   "memory transport — the shm twin of "
                   "wire_bytes_on_wire_total, counted separately because "
                   "these bytes never cross a wire (one count per "
                   "endpoint per data frame; control and digest-check "
                   "frames excluded, same discipline as TCP)"),
    "transport_links_total": (
        "counter", "peer links classified at mesh bring-up, labeled "
                   "transport=shm|tcp (per-link selection seam)"),
    "faults_injected_total": (
        "counter", "fault-injection clauses fired (chaos runs only)"),
    # -- registered views (phase_stats / wire_stats) --
    "phase_seconds_total": (
        "counter", "accumulated wall time per dispatch-chain phase "
                   "(phase_stats view; labeled phase=)"),
    "phase_ops_total": (
        "counter", "events per dispatch-chain phase (phase_stats view)"),
    "wire_bytes_on_wire_total": (
        "counter", "data payload bytes framed/delivered by the TCP "
                   "transport (wire_stats view)"),
    "wire_heap_copies_total": (
        "counter", "payload materializations in the host data plane "
                   "(wire_stats view; the zero-copy guard's counter)"),
    "wire_compressed_bytes_total": (
        "counter", "narrow payload bytes produced/consumed by wire "
                   "compression (wire_stats view; compare against "
                   "wire_bytes_on_wire_total for the achieved ratio)"),
    # -- bandwidth plane --
    "fusion_reorders_total": (
        "counter", "negotiation cycles where readiness ordering changed "
                   "the fusion packing order (coordinator only)"),
    # -- raw stat names (the literals fed to phase_stats/wire_stats.add;
    #    HVD007 checks those call sites against this catalog too) --
    "negotiate": ("stat", "phase_stats: controller round, busy cycles"),
    "fuse": ("stat", "phase_stats: staging the fused buffer"),
    "collective": ("stat", "phase_stats: host cost of the collective"),
    "unfuse": ("stat", "phase_stats: slicing results to outputs"),
    "wait": ("stat", "phase_stats: framework-thread handle waits"),
    "bytes_on_wire": ("stat", "wire_stats: per-frame payload bytes"),
    "heap_copies": ("stat", "wire_stats: data-plane materializations"),
    "compressed_bytes": ("stat", "wire_stats: narrow wire-dtype bytes"),
}

#: Fast-path flag (the ``faults.ACTIVE`` pattern): when False every
#: module-level recording call returns after one attribute read.
ENABLED = env_mod.get_bool(env_mod.HOROVOD_METRICS, True)


def configure(enabled: Optional[bool] = None) -> None:
    """Set (or re-read from the environment) the enable flag — tests and
    the bench sweep use this; production processes inherit the env."""
    global ENABLED
    if enabled is None:
        enabled = env_mod.get_bool(env_mod.HOROVOD_METRICS, True)
    ENABLED = bool(enabled)


def flat(name: str, **labels) -> str:
    """Flatten a metric name + labels into the Prometheus series form
    (``name{k="v",...}``, keys sorted).  Label values must not contain
    ``"`` or newlines — enforced here because the flat string is also the
    storage/merge key and the renderer re-parses it."""
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        if '"' in v or "\n" in v:
            raise ValueError(f"label value {v!r} for {k} contains a "
                             "forbidden character")
        parts.append(f'{k}="{v}"')
    return name + "{" + ",".join(parts) + "}"


def parse_flat(flat_name: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`flat` (for the renderer's rank-label injection)."""
    if "{" not in flat_name:
        return flat_name, {}
    base, _, rest = flat_name.partition("{")
    labels: Dict[str, str] = {}
    for item in rest.rstrip("}").split(","):
        if not item:
            continue
        k, _, v = item.partition("=")
        labels[k] = v.strip('"')
    return base, labels


def size_bucket_label(nbytes: int) -> str:
    """Power-of-two-ceiling size label (``4 MiB`` → ``2^22``) for the
    per-collective latency histogram's ``size=`` dimension."""
    if nbytes <= 1:
        return "2^0"
    return f"2^{(int(nbytes) - 1).bit_length()}"


class MetricsRegistry:
    """One process's metric state.  All mutation is under one small lock;
    views are called OUTSIDE it (they hold their own locks)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # flat -> [per-bucket counts (len(BUCKET_BOUNDS)+1), sum, count]
        self._hists: Dict[str, List] = {}
        self._views: Dict[str, Callable[[], dict]] = {}

    # -- recording ------------------------------------------------------

    def inc(self, name: str, value: float = 1, **labels) -> None:
        key = flat(name, **labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = flat(name, **labels)
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value: float, **labels) -> None:
        key = flat(name, **labels)
        idx = bisect.bisect_left(BUCKET_BOUNDS, value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [[0] * (len(BUCKET_BOUNDS) + 1),
                                        0.0, 0]
            h[0][idx] += 1
            h[1] += value
            h[2] += 1

    def register_view(self, name: str,
                      fn: Callable[[], dict]) -> None:
        """Register (or replace) a snapshot view: ``fn()`` returns
        ``{"counters": {flat: v}, "gauges": {flat: v}}`` folded into
        every snapshot.  Re-registration under the same name replaces —
        elastic re-initialization must not accumulate stale closures."""
        with self._lock:
            self._views[name] = fn

    # -- reading --------------------------------------------------------

    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(flat(name, **labels), 0)

    def get_gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(flat(name, **labels))

    def snapshot(self) -> dict:
        """JSON-able copy of everything, views folded in — the unit the
        push thread ships to the rendezvous KV."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: {"counts": list(h[0]), "sum": h[1], "count": h[2]}
                     for k, h in self._hists.items()}
            views = list(self._views.items())
        for _, fn in views:
            try:
                out = fn() or {}
            except Exception:  # noqa: BLE001 — a broken view must not
                # take down the scrape; the other series still matter.
                continue
            counters.update(out.get("counters", {}))
            gauges.update(out.get("gauges", {}))
        return {
            "version": 1,
            "rank": env_mod.get_int(env_mod.HOROVOD_RANK, 0),
            "ts_unix_ns": time.time_ns(),
            "bucket_bounds": list(BUCKET_BOUNDS),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


#: The process-global registry every instrumented site records into.
registry = MetricsRegistry()


# -- module-level conveniences (the instrumented-site API; one attribute
#    read when disabled, like faults.ACTIVE) -------------------------------


def inc(name: str, value: float = 1, **labels) -> None:
    if ENABLED:
        registry.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    if ENABLED:
        registry.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    if ENABLED:
        registry.observe(name, value, **labels)


# -- cross-rank merge + Prometheus text rendering --------------------------


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _help_type(base: str, kind: str, out: List[str],
               emitted: set) -> None:
    if base in emitted:
        return
    emitted.add(base)
    entry = CATALOG.get(base)
    help_text = entry[1] if entry else ""
    out.append(f"# HELP {PROM_PREFIX}{base} {help_text}")
    out.append(f"# TYPE {PROM_PREFIX}{base} {kind}")


def render_prometheus(snapshots: Dict) -> str:
    """Aggregate per-rank snapshot dicts into Prometheus text format
    (version 0.0.4): counters and histogram buckets summed across ranks,
    gauges labeled by rank.  ``snapshots`` maps any key to a snapshot
    dict; the rank comes from each snapshot's own ``rank`` field."""
    counters: Dict[str, float] = {}
    hists: Dict[str, List] = {}
    gauge_lines: List[Tuple[str, str, float]] = []  # (base, flat+rank, v)
    for key, snap in sorted(snapshots.items(), key=lambda kv: str(kv[0])):
        if not isinstance(snap, dict):
            continue
        rank = snap.get("rank", key)
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, h in snap.get("histograms", {}).items():
            counts = list(h.get("counts", []))
            agg = hists.get(k)
            if agg is None:
                hists[k] = [counts, float(h.get("sum", 0.0)),
                            int(h.get("count", 0)),
                            list(snap.get("bucket_bounds", BUCKET_BOUNDS))]
            elif len(agg[0]) == len(counts):
                agg[0] = [a + b for a, b in zip(agg[0], counts)]
                agg[1] += float(h.get("sum", 0.0))
                agg[2] += int(h.get("count", 0))
        for k, v in snap.get("gauges", {}).items():
            base, labels = parse_flat(k)
            labels["rank"] = str(rank)
            gauge_lines.append((base, flat(base, **labels), v))

    out: List[str] = []
    emitted: set = set()
    for k in sorted(counters):
        base, _ = parse_flat(k)
        _help_type(base, "counter", out, emitted)
        out.append(f"{PROM_PREFIX}{k} {_fmt(counters[k])}")
    for base, flat_name, v in sorted(gauge_lines, key=lambda t: t[1]):
        _help_type(base, "gauge", out, emitted)
        out.append(f"{PROM_PREFIX}{flat_name} {_fmt(v)}")
    for k in sorted(hists):
        counts, total, n, bounds = hists[k]
        base, labels = parse_flat(k)
        _help_type(base, "histogram", out, emitted)
        cum = 0
        for i, bound in enumerate(list(bounds) + [float("inf")]):
            cum += counts[i] if i < len(counts) else 0
            le = "+Inf" if bound == float("inf") else repr(bound)
            out.append(PROM_PREFIX
                       + flat(base + "_bucket", **{**labels, "le": le})
                       + f" {cum}")
        out.append(f"{PROM_PREFIX}{flat(base + '_sum', **labels)} "
                   f"{_fmt(total)}")
        out.append(f"{PROM_PREFIX}{flat(base + '_count', **labels)} {n}")
    return "\n".join(out) + ("\n" if out else "")


# Deferred re-export (see the note near the top of the module): the
# transport package import chain reaches back into ``metrics.registry``,
# so the scope registry can only be imported once that exists.
from ..transport.scopes import METRICS_SCOPE  # noqa: E402,F401  (re-export)
