"""Tree negotiation fan-in — O(hosts) coordinator ingress on the mask
fast path.

The star negotiation ships every rank's readiness bitvector (PR 1 mask
frames, ``core/messages.py:MaskFrame``) straight to the coordinator:
O(ranks) blocking recvs per cycle at rank 0, the last O(ranks) hot path
after the control plane (elastic/fanin.py) and membership churn
(docs/elastic.md "Live resharding") were fixed.  This module supplies
the data-plane analog of the reference's hierarchical controller: each
host's ``local_rank 0`` becomes the **negotiation aggregator** — it
collects its colocated ranks' cycle payloads, ANDs the mask frames into
ONE :class:`~.messages.HostMaskFrame`, forwards a single bundle up to
the coordinator, and fans the coordinator's (identical-for-everyone)
response payload back down.  Coordinator ingress per cycle drops from
``np - 1`` frames to ``(hosts - 1) + (local_size - 1)``.

Scope is deliberately the mask fast path only: a rank whose cycle needs
a full ``RequestList`` (cache miss, join, shutdown-with-requests) rides
the aggregator's bundle UNFOLDED, and the coordinator ingests it exactly
as the star would — the PR 1 cache-bit semantics stay bit-exact because
folding only ever touches frames whose entire meaning is "AND me".

Statelessness is the correctness keystone: workers re-announce their
FULL pending cache-bit mask every cycle, so the aggregator keeps no
accumulated readiness — each cycle's fold is a pure function of that
cycle's frames, and no crash/reorder can lose or double-count a bit
across cycles (the ``hvd-mck`` fan-in model checks exactly this,
``tools/mck/fanin_model.py``).

Degrade semantics mirror ``elastic/fanin.py``'s aggregator-liveness
idiom, adapted to a blocking lockstep mesh where a member CANNOT
unilaterally reroute mid-epoch (the coordinator's recv set is fixed):

- aggregator DEATH: the member's blocking ``recv`` raises
  ``PeerGoneError`` promptly → coordinated abort → cheap in-place
  reshard (PR 19) → the respawned epoch re-trees.  No bit is lost: the
  aborted cycle is discarded on every path and the next cycle
  re-announces everything.
- aggregator WEDGE (alive but stuck): members check the aggregator's
  heartbeat file before each send; ~1.5 heartbeat periods of staleness
  (``elastic/fanin.py:HEARTBEAT_STALE_PERIODS``) convicts it —
  ``AggregatorStaleError`` → abort, with a best-effort veto written to
  the rendezvous store (``transport/scopes.py:NEGOTIATION_VETO_SCOPE``)
  so the recovered epoch runs this host DIRECT for the veto-cooldown
  window instead of re-treeing under the same wedge.
"""

from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..common import env as env_mod
from ..common.exceptions import AggregatorStaleError, HorovodInternalError
from ..common.logging_util import get_logger
from ..common.topology import ProcessTopology
from ..elastic.fanin import HEARTBEAT_STALE_PERIODS
from .messages import HostMaskFrame, MaskFrame, is_mask_frame

__all__ = [
    "AggregatorHeartbeat",
    "AggregatorStaleError",
    "FaninPlan",
    "build_plan",
    "fold_host",
    "heartbeat_dir",
    "resolve_mode",
]

log = get_logger("horovod_tpu.core.negotiation_fanin")


# ---------------------------------------------------------------------------
# the fold (the production kernel the mck model drives)
# ---------------------------------------------------------------------------

def fold_host(collected: Sequence[Tuple[int, bytes]]) -> List[Tuple[int, bytes]]:
    """One host's per-cycle fold: ``[(rank, payload)]`` (the aggregator's
    own payload included) → bundle entries for the coordinator.

    Mask frames collapse into ONE :class:`HostMaskFrame` — mask = AND of
    the senders' bitvectors, ``covered`` = exactly those senders,
    shutdown = OR of their flags (matching the coordinator's own OR-fold
    over per-rank frames).  Everything else passes through unfolded, so
    full-RequestList cycles keep per-rank fidelity.  Pure and stateless:
    the output is a function of this cycle's input alone.
    """
    covered: List[int] = []
    host_mask: Optional[int] = None
    shutdown = False
    entries: List[Tuple[int, bytes]] = []
    for rank, payload in collected:
        if is_mask_frame(payload):
            frame = MaskFrame.from_bytes(payload)
            covered.append(rank)
            host_mask = frame.mask_int if host_mask is None \
                else host_mask & frame.mask_int
            shutdown = shutdown or frame.shutdown
        else:
            entries.append((rank, payload))
    if covered:
        covered.sort()
        mask_bytes = host_mask.to_bytes((host_mask.bit_length() + 7) // 8,
                                        "little")
        entries.append((covered[0],
                        HostMaskFrame(covered=covered, mask=mask_bytes,
                                      shutdown=shutdown).to_bytes()))
    entries.sort()
    return entries


# ---------------------------------------------------------------------------
# role / plan derivation
# ---------------------------------------------------------------------------

def _blocked_layout(topology: ProcessTopology) -> bool:
    """True when global ranks are laid out host-major ("blocked"):
    rank = cross_rank * local_size + local_rank.  The plan derives every
    rank's role from arithmetic on this layout, so all three parties
    (member, aggregator, coordinator) agree without exchanging a table.
    """
    ls = topology.local_size
    return (ls > 0
            and topology.local_rank == topology.rank % ls
            and topology.cross_rank == topology.rank // ls)


def resolve_mode(topology: ProcessTopology) -> str:
    """The ``HOROVOD_NEGOTIATION_FANIN`` gate → "on" | "off".

    "auto" (default) turns the tree on exactly when it can pay: a
    blocked-homogeneous layout with >= 2 ranks per host on >= 2 hosts
    (single-rank hosts have nothing to fold — they bypass the tree
    entirely).  A forced "1" on a layout the plan cannot cover is a loud
    config error, never a silent star fallback.
    """
    raw = (env_mod.get_str(env_mod.HOROVOD_NEGOTIATION_FANIN, "auto")
           or "auto").strip().lower()
    if raw not in ("auto", "0", "1"):
        raise ValueError(
            f"HOROVOD_NEGOTIATION_FANIN={raw!r}: expected auto|0|1")
    if raw == "0":
        return "off"
    structural = (topology.size > 2
                  and topology.local_size >= 2
                  and topology.cross_size >= 2
                  and topology.is_homogeneous
                  and _blocked_layout(topology))
    if raw == "1" and not structural:
        raise HorovodInternalError(
            "HOROVOD_NEGOTIATION_FANIN=1 but the rank layout cannot host "
            f"a fan-in tree (size={topology.size}, "
            f"local_size={topology.local_size}, "
            f"cross_size={topology.cross_size}, "
            f"homogeneous={topology.is_homogeneous}, "
            f"blocked={_blocked_layout(topology)}); fan-in needs a "
            "blocked-homogeneous layout with >= 2 ranks/host on >= 2 "
            "hosts — fix the launcher's HOROVOD_LOCAL_* env or unset the "
            "knob")
    return "on" if structural else "off"


@dataclass(frozen=True)
class FaninPlan:
    """This rank's role in the negotiation tree for one epoch.

    Derived identically on every rank from (topology, vetoed hosts) —
    rank 0's decision record (``core/state.py``) carries only the mode
    and the vetoed host list, the rest is arithmetic.  While a plan is
    active it fully determines the wire shape (it supersedes
    ``HOROVOD_CONTROLLER_TOPOLOGY``): the coordinator's recv set is
    ``coordinator_senders`` and nothing else.
    """

    #: "coordinator" | "aggregator" | "member" | "direct"
    role: str
    #: member: the aggregator rank this member's frames route through.
    aggregator_rank: int
    #: aggregator: the colocated ranks it serves (itself excluded).
    member_ranks: Tuple[int, ...]
    #: coordinator: every rank it exchanges payloads with, sorted.
    coordinator_senders: Tuple[int, ...]
    #: coordinator: the subset of senders whose upward frame is a bundle.
    bundle_senders: frozenset

    @property
    def active(self) -> bool:
        return self.role != "direct" or bool(self.coordinator_senders)


def build_plan(topology: ProcessTopology,
               vetoed_hosts: Sequence[int] = ()) -> FaninPlan:
    """Build this rank's :class:`FaninPlan`.  ``vetoed_hosts`` are
    cross-rank indices whose ranks run direct (stale-aggregator
    conviction cooldown).  Host 0 is always direct: its would-be
    aggregator IS the coordinator, so its members' star sends already
    land at rank 0 — a fold there would add a hop to save nothing.
    """
    if not _blocked_layout(topology) or not topology.is_homogeneous:
        raise HorovodInternalError(
            f"rank {topology.rank}: negotiation fan-in requires a "
            "blocked-homogeneous rank layout "
            f"(local_rank={topology.local_rank}, "
            f"local_size={topology.local_size}, "
            f"cross_rank={topology.cross_rank}, size={topology.size})")
    ls = topology.local_size
    vetoed = set(vetoed_hosts)
    rank, host = topology.rank, topology.cross_rank

    senders: List[int] = []
    bundles: List[int] = []
    for h in range(topology.cross_size):
        base = h * ls
        if h == 0:
            senders.extend(range(1, base + ls))
        elif h in vetoed:
            senders.extend(range(base, base + ls))
        else:
            senders.append(base)
            bundles.append(base)

    if rank == 0:
        role, agg = "coordinator", -1
        members: Tuple[int, ...] = ()
    elif host == 0 or host in vetoed:
        role, agg, members = "direct", -1, ()
    elif topology.local_rank == 0:
        role, agg = "aggregator", rank
        members = tuple(range(rank + 1, rank + ls))
    else:
        role, agg = "member", host * ls
        members = ()
    return FaninPlan(role=role, aggregator_rank=agg, member_ranks=members,
                     coordinator_senders=tuple(senders),
                     bundle_senders=frozenset(bundles))


# ---------------------------------------------------------------------------
# aggregator-liveness heartbeat (elastic/fanin.py idiom)
# ---------------------------------------------------------------------------

def heartbeat_dir(job_key: str, cross_rank: int) -> str:
    """Per-(job, host) heartbeat directory shared by the host's ranks —
    keyed like ``elastic/fanin.py``'s spool root: the job key (store
    endpoint; two jobs on one box must not share heartbeats) plus the
    host identity and cross rank (two hosts simulated on one box via
    ``HOROVOD_SHM_HOSTID`` get distinct directories)."""
    from ..transport.select import host_identity

    base = env_mod.get_str(env_mod.HOROVOD_NEGOTIATION_FANIN_DIR) or None
    if base is None:
        import tempfile

        base = tempfile.gettempdir()
    token = hashlib.sha1(
        f"{job_key}|{host_identity(cross_rank)}".encode()).hexdigest()[:16]
    return os.path.join(base, f"hvd-neg-fanin-{token}")


class AggregatorHeartbeat:
    """Heartbeat file between one host's aggregator and its members.

    Aggregator side: :meth:`touch` after each completed relay cycle,
    rate-limited to one utime per half period — a wedged aggregator
    stops touching, which is the whole signal.  Member side:
    :meth:`check` before each upward send — raises
    :class:`AggregatorStaleError` when the file is older than
    ``HEARTBEAT_STALE_PERIODS`` periods; an ABSENT file is fresh during
    the same-sized arming grace (the aggregator may not have finished
    its first cycle) and stale after.  Stat calls are rate-limited the
    same way, so ~1 ms negotiation cycles don't turn into an fstat storm.
    Like ``elastic/fanin.py``, filesystem trouble on the aggregator side
    degrades loudly-but-gracefully: members will convict and the job
    falls back to direct.
    """

    def __init__(self, dir_path: str, period: float, aggregator_rank: int,
                 cross_rank: int, is_aggregator: bool):
        self._path = os.path.join(dir_path, "negotiation.hb")
        self._period = max(period, 1e-3)
        self._aggregator_rank = aggregator_rank
        self._cross_rank = cross_rank
        self._armed_at = time.time()
        self._last_touch = 0.0
        self._last_check = 0.0
        self._last_age = 0.0
        if is_aggregator:
            try:
                os.makedirs(dir_path, exist_ok=True)
                self._touch(force=True)
            except OSError as e:
                log.warning(
                    "negotiation heartbeat unavailable (%s); members will "
                    "convict this aggregator and the job will degrade to "
                    "direct pushes", e)

    # -- aggregator side ----------------------------------------------

    def _touch(self, force: bool = False) -> None:
        now = time.time()
        if not force and now - self._last_touch < self._period / 2:
            return
        self._last_touch = now
        try:
            with open(self._path, "a"):
                os.utime(self._path, None)
        except OSError as e:
            log.warning("negotiation heartbeat write failed (%s); members "
                        "will degrade this host to direct pushes", e)

    def touch(self) -> None:
        self._touch()

    # -- member side --------------------------------------------------

    def check(self) -> None:
        """Raise :class:`AggregatorStaleError` on a convicted (wedged)
        aggregator; return silently otherwise."""
        now = time.time()
        if now - self._last_check < self._period / 2:
            return
        self._last_check = now
        window = HEARTBEAT_STALE_PERIODS * self._period
        try:
            age = now - os.stat(self._path).st_mtime
        except OSError:
            # Absent: the aggregator hasn't completed a cycle yet (or
            # its filesystem is broken).  Grace-period from arming, then
            # convict — a host must never be silenced by a heartbeat
            # that was simply never born.
            age = now - self._armed_at
            if age < window:
                return
            raise AggregatorStaleError(self._aggregator_rank,
                                       self._cross_rank, age, window) \
                from None
        self._last_age = age
        if age >= window:
            raise AggregatorStaleError(self._aggregator_rank,
                                       self._cross_rank, age, window)


def make_heartbeat(plan: FaninPlan, topology: ProcessTopology,
                   job_key: str) -> Optional[AggregatorHeartbeat]:
    """Heartbeat for this rank's role, or None for roles that need none
    (coordinator / direct)."""
    if plan.role not in ("member", "aggregator"):
        return None
    period = env_mod.get_float(
        env_mod.HOROVOD_NEGOTIATION_FANIN_HEARTBEAT_SECS,
        env_mod.DEFAULT_NEGOTIATION_FANIN_HEARTBEAT_SECS)
    return AggregatorHeartbeat(
        heartbeat_dir(job_key, topology.cross_rank), period,
        aggregator_rank=plan.aggregator_rank
        if plan.role == "member" else topology.rank,
        cross_rank=topology.cross_rank,
        is_aggregator=plan.role == "aggregator")


# ---------------------------------------------------------------------------
# veto bookkeeping helpers (state.py reads/writes through these)
# ---------------------------------------------------------------------------

def veto_cooldown_epochs() -> int:
    return max(1, env_mod.get_int(
        env_mod.HOROVOD_NEGOTIATION_FANIN_VETO_EPOCHS,
        env_mod.DEFAULT_NEGOTIATION_FANIN_VETO_EPOCHS))


def active_vetoes(records: Dict[str, dict], epoch: int) -> List[str]:
    """Hostnames whose veto is still inside the cooldown window at
    ``epoch``.  ``records`` maps hostname → the stored veto JSON
    (``{"epoch": N, ...}``); malformed records are ignored — a veto is
    an optimization hint, never a correctness dependency."""
    out = []
    cooldown = veto_cooldown_epochs()
    for hostname, rec in records.items():
        try:
            veto_epoch = int(rec["epoch"])
        except (KeyError, TypeError, ValueError):
            continue
        if epoch - veto_epoch < cooldown:
            out.append(hostname)
    return sorted(out)
