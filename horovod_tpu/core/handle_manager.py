"""Handle bookkeeping for async collectives.

Role of the reference's ``horovod/torch/handle_manager.cc`` (mutex map
handle → Status) plus the poll/synchronize contract of
``mpi_ops_v2.cc:323-331``; we use events instead of busy-waiting so Python
threads sleep in the kernel rather than spinning the GIL."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ..common.exceptions import HorovodInternalError
from .tensor_queue import Status


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._done: Dict[int, Tuple[Status, Any]] = {}
        self._events: Dict[int, threading.Event] = {}

    def allocate(self) -> int:
        with self._lock:
            handle = self._next
            self._next += 1
            self._events[handle] = threading.Event()
            return handle

    def mark_done(self, handle: int, status: Status, result: Any = None) -> None:
        with self._lock:
            event = self._events.get(handle)
            if event is None:
                # Handle was discarded (abandoned window / failed enqueue):
                # drop the late result instead of resurrecting the entry —
                # nobody will ever wait on it.
                return
            self._done[handle] = (status, result)
        event.set()

    def discard(self, handle: int) -> None:
        """Release a handle nobody will wait on (failed enqueue, or an
        abandoned window whose collective never completed).  A callback
        that fires later is dropped by ``mark_done``."""
        with self._lock:
            self._events.pop(handle, None)
            self._done.pop(handle, None)

    def poll(self, handle: int) -> bool:
        with self._lock:
            return handle in self._done

    def wait(self, handle: int, timeout: Optional[float] = None) -> Any:
        """Block until done; raises on error status. Releases the handle."""
        with self._lock:
            event = self._events.get(handle)
        if event is None:
            raise ValueError(f"unknown handle {handle}")
        if not event.wait(timeout):
            raise TimeoutError(f"collective (handle {handle}) timed out")
        with self._lock:
            status, result = self._done.pop(handle)
            self._events.pop(handle, None)
        if not status.ok:
            raise HorovodInternalError(status.error_message)
        return result

    def wait_many(self, handles, timeout: Optional[float] = None) -> list:
        """Wait for a batch of handles; returns their results in order.

        One pass, one lock round per batch for the collection step — the
        per-fused-bucket wait the framework wrappers use instead of a
        per-tensor ``wait`` loop.  ``timeout`` bounds the WHOLE batch (one
        deadline, not per handle).  On any failure — error status or
        timeout — every handle in the batch is released before raising,
        so a partially-failed step cannot leak events."""
        import time

        events = []
        with self._lock:
            for h in handles:
                event = self._events.get(h)
                if event is None:
                    raise ValueError(f"unknown handle {h}")
                events.append(event)
        deadline = None if timeout is None else time.monotonic() + timeout
        timed_out = None
        for h, event in zip(handles, events):
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            if not event.wait(left):
                timed_out = h
                break
        results, first_error = [], None
        with self._lock:
            for h in handles:
                done = self._done.pop(h, None)
                self._events.pop(h, None)
                if done is None:        # timed out before completion
                    results.append(None)
                    continue
                status, result = done
                if not status.ok and first_error is None:
                    first_error = status.error_message
                results.append(result)
        if timed_out is not None:
            raise TimeoutError(
                f"collective batch timed out after {timeout}s waiting on "
                f"handle {timed_out}")
        if first_error is not None:
            raise HorovodInternalError(first_error)
        return results
