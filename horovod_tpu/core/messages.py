"""Control-plane wire messages: Request / Response and their lists.

Role of the reference's ``horovod/common/message.h:48-217`` +
``wire/message.fbs``: every rank describes each tensor it wants to reduce
with a ``Request`` (name, op, dtype, shape, root rank, pre/post scale);
the coordinator answers with fused ``Response``s naming the tensors that are
globally ready.  The reference serializes with FlatBuffers; we use a
hand-rolled length-prefixed binary format (little-endian, fixed-width struct
fields) that is deliberately trivial to reimplement in C++ for the native
controller — no schema compiler needed, and decode is allocation-light.

DataType covers the TPU-relevant set (bfloat16 is first-class; the reference
only knows fp16 — ``message.h:20-33``).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import List, Sequence

import numpy as np

from ..common.exceptions import TruncatedFrameError

WIRE_MAGIC = 0x48564454  # "HVDT"
MASK_MAGIC = 0x4B53414D  # "MASK" — steady-state fast-path frame
HOST_MASK_MAGIC = 0x4B534D48  # "HMSK" — fan-in aggregated mask frame
ABORT_MAGIC = 0x54524241  # "ABRT" — coordinated-abort control frame

#: AbortFrame.reason budget (bytes, UTF-8): an abort carrying a giant
#: traceback must not bloat the control frame every surviving link relays.
MAX_ABORT_REASON_BYTES = 512
_TRUNCATION_MARK = "…[truncated]"


class DataType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10

    @property
    def itemsize(self) -> int:
        return _ITEMSIZE[self]

    def to_numpy(self) -> np.dtype:
        return _TO_NUMPY[self]

    @staticmethod
    def from_numpy(dtype) -> "DataType":
        key = np.dtype(dtype).name
        try:
            return _FROM_NUMPY[key]
        except KeyError:
            raise ValueError(f"unsupported dtype {dtype!r}") from None


def _bfloat16_dtype():
    try:
        import ml_dtypes  # jax's dtype extension package, always present with jax

        return np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return np.dtype(np.uint16)  # raw-bits fallback


_ITEMSIZE = {
    DataType.UINT8: 1, DataType.INT8: 1, DataType.UINT16: 2, DataType.INT16: 2,
    DataType.INT32: 4, DataType.INT64: 8, DataType.FLOAT16: 2, DataType.FLOAT32: 4,
    DataType.FLOAT64: 8, DataType.BOOL: 1, DataType.BFLOAT16: 2,
}

_TO_NUMPY = {
    DataType.UINT8: np.dtype(np.uint8), DataType.INT8: np.dtype(np.int8),
    DataType.UINT16: np.dtype(np.uint16), DataType.INT16: np.dtype(np.int16),
    DataType.INT32: np.dtype(np.int32), DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT16: np.dtype(np.float16), DataType.FLOAT32: np.dtype(np.float32),
    DataType.FLOAT64: np.dtype(np.float64), DataType.BOOL: np.dtype(np.bool_),
    DataType.BFLOAT16: _bfloat16_dtype(),
}

_FROM_NUMPY = {
    "uint8": DataType.UINT8, "int8": DataType.INT8, "uint16": DataType.UINT16,
    "int16": DataType.INT16, "int32": DataType.INT32, "int64": DataType.INT64,
    "float16": DataType.FLOAT16, "float32": DataType.FLOAT32,
    "float64": DataType.FLOAT64, "bool": DataType.BOOL, "bfloat16": DataType.BFLOAT16,
}


class RequestType(enum.IntEnum):
    """Reference ``message.h:51`` (ALLREDUCE/ALLGATHER/BROADCAST/JOIN/ADASUM/
    ALLTOALL); BARRIER is our addition for the elastic/commit path."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    ERROR = 7


# ---------------------------------------------------------------------------
# binary writer/reader helpers
# ---------------------------------------------------------------------------

class Writer:
    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def u8(self, v: int): self.buf += struct.pack("<B", v)
    def u32(self, v: int): self.buf += struct.pack("<I", v)
    def i32(self, v: int): self.buf += struct.pack("<i", v)
    def i64(self, v: int): self.buf += struct.pack("<q", v)
    def f64(self, v: float): self.buf += struct.pack("<d", v)

    def string(self, s: str):
        b = s.encode("utf-8")
        self.u32(len(b))
        self.buf += b

    def i64_list(self, xs: Sequence[int]):
        self.u32(len(xs))
        self.buf += struct.pack(f"<{len(xs)}q", *xs)

    def i32_list(self, xs: Sequence[int]):
        self.u32(len(xs))
        self.buf += struct.pack(f"<{len(xs)}i", *xs)

    def str_list(self, xs: Sequence[str]):
        self.u32(len(xs))
        for s in xs:
            self.string(s)

    def getvalue(self) -> bytes:
        return bytes(self.buf)


class Reader:
    """Bounds-checked binary reader.

    Wire input is UNTRUSTED even inside the CRC envelope: a truncated
    application frame (misframed sender, injected ``truncate`` fault)
    passes the transport CRC — it was computed over the short payload —
    and arrives here with length fields pointing past the buffer end.
    Every read therefore checks its bounds and raises typed
    :class:`TruncatedFrameError` instead of leaking a raw
    ``struct.error`` (or, worse, silently slicing short)."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _need(self, size: int) -> None:
        if self.pos + size > len(self.buf):
            raise TruncatedFrameError(
                f"frame truncated: need {size} bytes at offset {self.pos} "
                f"but only {len(self.buf) - self.pos} remain "
                f"(buffer is {len(self.buf)} bytes)")

    def _take(self, fmt: str, size: int):
        self._need(size)
        v = struct.unpack_from(fmt, self.buf, self.pos)[0]
        self.pos += size
        return v

    def u8(self) -> int: return self._take("<B", 1)
    def u32(self) -> int: return self._take("<I", 4)
    def i32(self) -> int: return self._take("<i", 4)
    def i64(self) -> int: return self._take("<q", 8)
    def f64(self) -> float: return self._take("<d", 8)

    def bytes_(self, n: int) -> bytes:
        """Exactly ``n`` raw bytes — a short slice would silently
        misparse everything after it."""
        self._need(n)
        out = bytes(self.buf[self.pos:self.pos + n])
        self.pos += n
        return out

    def string(self) -> str:
        n = self.u32()
        return self.bytes_(n).decode("utf-8")

    def i64_list(self) -> List[int]:
        n = self.u32()
        self._need(8 * n)
        out = list(struct.unpack_from(f"<{n}q", self.buf, self.pos))
        self.pos += 8 * n
        return out

    def i32_list(self) -> List[int]:
        n = self.u32()
        self._need(4 * n)
        out = list(struct.unpack_from(f"<{n}i", self.buf, self.pos))
        self.pos += 4 * n
        return out

    def str_list(self) -> List[str]:
        return [self.string() for _ in range(self.u32())]

    def expect_magic(self, expected: int, what: str) -> None:
        """Check the leading u32 wire tag; a mismatch reports got vs
        expected plus a hexdump of the frame head — the diagnostic that
        distinguishes "wrong frame type" from "stream desync" at a
        glance."""
        got = self.u32()
        if got != expected:
            head = self.buf[:16].hex(" ")
            raise ValueError(
                f"bad {what} magic: got 0x{got:08X}, expected "
                f"0x{expected:08X}; first {min(16, len(self.buf))} bytes: "
                f"{head}")


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

@dataclass
class Request:
    """One rank's declaration that a named tensor is ready.

    Reference ``message.h:48-113``."""

    request_rank: int = 0
    request_type: RequestType = RequestType.ALLREDUCE
    tensor_name: str = ""
    tensor_type: DataType = DataType.FLOAT32
    tensor_shape: List[int] = field(default_factory=list)
    root_rank: int = -1          # broadcast only
    device: int = -1             # -1 = host memory
    group_id: int = -1           # grouped allreduce
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # ALLTOALL send splits travel in-band (the reference distributes them via
    # a separate MPI_Alltoall, ``mpi_controller.cc:212``; in-band is simpler
    # and lets the coordinator validate consistency).
    splits: List[int] = field(default_factory=list)

    def serialize(self, w: Writer) -> None:
        w.u32(self.request_rank)
        w.u8(int(self.request_type))
        w.string(self.tensor_name)
        w.u8(int(self.tensor_type))
        w.i64_list(self.tensor_shape)
        w.i32(self.root_rank)
        w.i32(self.device)
        w.i32(self.group_id)
        w.f64(self.prescale_factor)
        w.f64(self.postscale_factor)
        w.i64_list(self.splits)

    @staticmethod
    def deserialize(r: Reader) -> "Request":
        return Request(
            request_rank=r.u32(),
            request_type=RequestType(r.u8()),
            tensor_name=r.string(),
            tensor_type=DataType(r.u8()),
            tensor_shape=r.i64_list(),
            root_rank=r.i32(),
            device=r.i32(),
            group_id=r.i32(),
            prescale_factor=r.f64(),
            postscale_factor=r.f64(),
            splits=r.i64_list(),
        )

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.tensor_shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.num_elements * self.tensor_type.itemsize


@dataclass
class RequestList:
    requests: List[Request] = field(default_factory=list)
    shutdown: bool = False
    # Cache-hit bit positions (response_cache.py): tensors re-announced at
    # 4 bytes instead of a full Request — the steady-state fast path
    # (reference bitvector sync, ``controller.cc:826-851``).
    cache_hits: List[int] = field(default_factory=list)
    # Dense bitmask flavor of the same information (little-endian bytes of
    # a big integer): the coordinator aggregates these with C-speed
    # integer AND/OR instead of per-(rank × tensor) Python loops — the
    # part of the star protocol that must stay O(ranks) per cycle.
    cache_mask: bytes = b""

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(WIRE_MAGIC)
        w.u8(1 if self.shutdown else 0)
        w.i32_list(self.cache_hits)
        w.u32(len(self.cache_mask))
        w.buf += self.cache_mask
        w.u32(len(self.requests))
        for req in self.requests:
            req.serialize(w)
        return w.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "RequestList":
        r = Reader(data)
        r.expect_magic(WIRE_MAGIC, "request-list")
        shutdown = bool(r.u8())
        cache_hits = r.i32_list()
        mask = r.bytes_(r.u32())
        reqs = [Request.deserialize(r) for _ in range(r.u32())]
        return RequestList(requests=reqs, shutdown=shutdown,
                           cache_hits=cache_hits, cache_mask=mask)


@dataclass
class MaskFrame:
    """Compact steady-state negotiation frame — the zero-round-trip-payload
    cache fast path.

    When every pending tensor on a rank hits its cache mirror, the rank's
    whole cycle contribution is a bitvector; and when that holds on EVERY
    rank, the coordinator's whole verdict is the AND of those bitvectors.
    This frame carries exactly that (plus the shutdown flag) in both
    directions, replacing full ``RequestList``/``ResponseList`` payloads:
    each rank reconstructs the agreed Responses locally from its cached
    request templates (``controller._responses_from_agreed_mask``).  The
    reference's bitvector-allreduce cache sync (``controller.cc:826-851``)
    achieves the same wire shape inside MPI; ours is explicit because the
    frame must be self-describing next to the full-payload flavor (the
    leading magic distinguishes them).
    """

    mask: bytes = b""        # little-endian big-int bitvector
    shutdown: bool = False

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(MASK_MAGIC)
        w.u8(1 if self.shutdown else 0)
        w.u32(len(self.mask))
        w.buf += self.mask
        return w.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "MaskFrame":
        r = Reader(data)
        r.expect_magic(MASK_MAGIC, "mask-frame")
        shutdown = bool(r.u8())
        return MaskFrame(mask=r.bytes_(r.u32()), shutdown=shutdown)

    @property
    def mask_int(self) -> int:
        return int.from_bytes(self.mask, "little")


def is_mask_frame(data: bytes) -> bool:
    """True when ``data`` is a MaskFrame (vs RequestList/ResponseList)."""
    return len(data) >= 4 and \
        struct.unpack_from("<I", data)[0] == MASK_MAGIC


@dataclass
class HostMaskFrame:
    """One HOST's aggregated steady-state contribution — the negotiation
    fan-in frame (``core/negotiation_fanin.py``).

    Under tree fan-in the host's aggregator ANDs the MaskFrames of the
    colocated ranks it covers into one bitvector and forwards THIS frame
    in their place, so coordinator ingress per busy cycle scales with
    hosts, not ranks.  Correctness leans on the mask fast path's
    re-announcement property: every rank re-announces its FULL pending
    cache-bit mask every cycle, so the aggregation is a stateless
    per-cycle fold — nothing is accumulated at the aggregator, and an
    aggregator death can lose at most the in-flight cycle, which the
    lockstep abort already discards on every path.  ``covered`` names the
    exact ranks whose masks were folded (ranks that sent a full
    RequestList ride the bundle unfolded); the coordinator expands the
    frame to one identical pending-mask contribution per covered rank.
    ``shutdown`` is the OR of the covered ranks' flags, matching the
    coordinator's own OR-fold over per-rank frames.
    """

    covered: List[int] = field(default_factory=list)
    mask: bytes = b""        # little-endian big-int bitvector (AND-fold)
    shutdown: bool = False

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(HOST_MASK_MAGIC)
        w.u8(1 if self.shutdown else 0)
        w.i32_list(self.covered)
        w.u32(len(self.mask))
        w.buf += self.mask
        return w.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "HostMaskFrame":
        r = Reader(data)
        r.expect_magic(HOST_MASK_MAGIC, "host-mask-frame")
        shutdown = bool(r.u8())
        covered = r.i32_list()
        return HostMaskFrame(covered=covered, mask=r.bytes_(r.u32()),
                             shutdown=shutdown)

    @property
    def mask_int(self) -> int:
        return int.from_bytes(self.mask, "little")


def is_host_mask_frame(data: bytes) -> bool:
    return len(data) >= 4 and \
        struct.unpack_from("<I", data)[0] == HOST_MASK_MAGIC


@dataclass
class AbortFrame:
    """Coordinated-abort broadcast: the detecting rank tells every
    surviving peer that the job is dead and why.

    Rides the transport's *control-frame* channel (``transport/tcp.py``
    marks the length header), so it can never be confused with in-flight
    negotiation or tensor payload bytes.  Carries the elastic epoch: a
    late abort from a pre-reset incarnation of the job must be discarded,
    not kill the freshly re-rendezvoused world.
    """

    epoch: int = 0
    origin_rank: int = 0
    reason: str = ""

    def __post_init__(self):
        # Bound the reason AT CONSTRUCTION (not serialization): the cap
        # must hold everywhere the frame travels — relays, logs, the mesh
        # abort flag — not just on this rank's wire.  A multi-KB
        # traceback in every control frame would bloat exactly the path
        # that must stay small to deliver promptly during teardown.
        raw = self.reason.encode("utf-8")
        if len(raw) > MAX_ABORT_REASON_BYTES:
            mark = _TRUNCATION_MARK.encode("utf-8")
            keep = raw[:MAX_ABORT_REASON_BYTES - len(mark)]
            # errors="ignore" drops a multi-byte sequence split by the
            # cut instead of raising (or keeping a mojibake tail).
            self.reason = keep.decode("utf-8", "ignore") + _TRUNCATION_MARK

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(ABORT_MAGIC)
        w.i64(self.epoch)
        w.i32(self.origin_rank)
        w.string(self.reason)
        return w.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "AbortFrame":
        r = Reader(data)
        r.expect_magic(ABORT_MAGIC, "abort-frame")
        return AbortFrame(epoch=r.i64(), origin_rank=r.i32(),
                          reason=r.string())


def is_abort_frame(data: bytes) -> bool:
    return len(data) >= 4 and \
        struct.unpack_from("<I", data)[0] == ABORT_MAGIC


@dataclass
class Response:
    """Coordinator verdict for one (possibly fused) set of tensors.

    Reference ``message.h:145-217``.  ``tensor_sizes`` carries per-rank first
    dimensions for ALLGATHER and flattened per-rank recv splits for ALLTOALL
    (reference packs both into the same field)."""

    response_type: ResponseType = ResponseType.ALLREDUCE
    tensor_names: List[str] = field(default_factory=list)
    tensor_type: DataType = DataType.FLOAT32
    tensor_sizes: List[int] = field(default_factory=list)
    error_message: str = ""
    devices: List[int] = field(default_factory=list)
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    last_joined_rank: int = -1

    def serialize(self, w: Writer) -> None:
        w.u8(int(self.response_type))
        w.str_list(self.tensor_names)
        w.u8(int(self.tensor_type))
        w.i64_list(self.tensor_sizes)
        w.string(self.error_message)
        w.i32_list(self.devices)
        w.f64(self.prescale_factor)
        w.f64(self.postscale_factor)
        w.i32(self.last_joined_rank)

    @staticmethod
    def deserialize(r: Reader) -> "Response":
        return Response(
            response_type=ResponseType(r.u8()),
            tensor_names=r.str_list(),
            tensor_type=DataType(r.u8()),
            tensor_sizes=r.i64_list(),
            error_message=r.string(),
            devices=r.i32_list(),
            prescale_factor=r.f64(),
            postscale_factor=r.f64(),
            last_joined_rank=r.i32(),
        )


@dataclass
class ResponseList:
    responses: List[Response] = field(default_factory=list)
    shutdown: bool = False
    # Coordinator-authoritative cache maintenance (response_cache.py):
    # (bit, request-template) assignments workers mirror, and evictions.
    cache_assignments: List[tuple] = field(default_factory=list)
    evicted_bits: List[int] = field(default_factory=list)
    # Autotuned runtime parameters, broadcast when they change (reference
    # ``SynchronizeParameters``, ``controller.cc:43-57``): (fusion_threshold
    # bytes, cycle_time_ms) or None.
    tuned_params: "tuple | None" = None

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(WIRE_MAGIC)
        w.u8(1 if self.shutdown else 0)
        w.i32_list(self.evicted_bits)
        w.u32(len(self.cache_assignments))
        for bit, template in self.cache_assignments:
            w.i32(bit)
            template.serialize(w)
        if self.tuned_params is None:
            w.u8(0)
        else:
            w.u8(1)
            w.i64(int(self.tuned_params[0]))
            w.f64(float(self.tuned_params[1]))
        w.u32(len(self.responses))
        for resp in self.responses:
            resp.serialize(w)
        return w.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "ResponseList":
        r = Reader(data)
        r.expect_magic(WIRE_MAGIC, "response-list")
        shutdown = bool(r.u8())
        evicted = r.i32_list()
        assignments = []
        for _ in range(r.u32()):
            bit = r.i32()
            assignments.append((bit, Request.deserialize(r)))
        tuned = None
        if r.u8():
            tuned = (r.i64(), r.f64())
        resps = [Response.deserialize(r) for _ in range(r.u32())]
        return ResponseList(responses=resps, shutdown=shutdown,
                            cache_assignments=assignments,
                            evicted_bits=evicted, tuned_params=tuned)
