"""Ordered backend dispatch — first enabled op wins.

Role of the reference's ``OperationManager`` (``operation_manager.cc:41-121``):
each response type has an ordered chain of candidate backend ops (registration
order at ``operations.cc:145-252``: most-specialized first, host fallback
last); the first whose ``enabled()`` returns true executes.  Our chains put
XLA/TPU ops ahead of the TCP-ring host ops.
"""

from __future__ import annotations

from typing import Dict, List

from ..backend.cpu_ring import CollectiveOp
from .messages import Response, ResponseType
from .tensor_queue import Status, TensorTableEntry


class OperationManager:
    def __init__(self):
        self._chains: Dict[ResponseType, List[CollectiveOp]] = {
            t: [] for t in ResponseType
        }

    def register(self, response_type: ResponseType, op: CollectiveOp,
                 front: bool = False) -> None:
        chain = self._chains[response_type]
        if front:
            chain.insert(0, op)
        else:
            chain.append(op)

    def select(self, response: Response,
               entries: List[TensorTableEntry]):
        """First enabled op for a response, or None — lets the dispatch
        loop route device-plane work to the pipeline thread without
        executing it."""
        for op in self._chains[response.response_type]:
            if op.enabled(response, entries):
                return op
        return None

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        op = self.select(response, entries)
        if op is None:
            return Status.error(
                f"no enabled backend op for {response.response_type.name}")
        return op.execute(response, entries)
